"""Gluon Trainer (ref: python/mxnet/gluon/trainer.py).

API-compatible DP training driver. On a mesh-sharded compiled path the
gradient all-reduce is emitted by XLA inside the step function; in the
eager/multi-context path the kvstore reduces across device copies
(ref: trainer.py:174-261 _init_kvstore, :320 step, :349 allreduce_grads,
:430 _update).
"""
from __future__ import annotations

from ..base import MXNetError, telem_flags as _telem
from ..ndarray.ndarray import NDArray
from .. import optimizer as opt
from .. import kvstore as kvs
from ..resilience import faults as _faults
from ..telemetry import trace as _trace, flight as _flight, \
    memory as _memory, compile as _compile
from .parameter import ParameterDict, Parameter


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore='device',
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError("First argument must be a list or dict of Parameters")
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise ValueError(f"First argument must contain Parameters, got {type(param)}")
            self._param2idx[param.name] = i
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get('rescale_grad', 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_type = kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._update_on_kvstore = update_on_kvstore
        self._params_to_init = []
        self._contains_sparse_weight = any(
            p._stype != 'default' for p in self._params)
        self._contains_sparse_grad = any(
            p._grad_stype != 'default' for p in self._params)
        # telemetry: perf_counter of the previous step() call — the
        # inter-step interval is the true iteration time (fwd+bwd+update).
        # The EMA guards the histogram against counting pauses between
        # steps (eval pass, checkpoint save) as step time.
        self._telem_last_step = None
        self._telem_step_ema = None
        # ZeRO state of the fused update; populated by _fused_apply
        # when the weights live on a >1-device dp mesh (see _zero_layout).
        # Stage 1 shards the optimizer states 1/dp; stage 3 (MXTPU_ZERO=3)
        # additionally re-places the weight NDArrays themselves sharded.
        self._zero_active = False
        self._zero_dp = 1
        self._zero_stage = 0
        self._zero3_mesh = None   # mesh to re-place onto after a restore
        # resilience.NonFiniteGuard bound via attach_guard(): the fused
        # update then also reduces isfinite over every gradient and
        # skips the writeback ON DEVICE when the step is non-finite
        self._guard = None
        # resilience.ElasticController bound via attach_elastic():
        # step() then consults it first, so preemption/peer loss turns
        # into commit -> re-form -> resume with the user loop unmodified
        self._elastic = None

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an Optimizer instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = None

    @property
    def optimizer(self):
        return self._optimizer

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def sparse_layout(self):
        """RowSparse layout of the eager update path for the checkpoint
        manifest (``optimizer_state_layout.sparse``), mirroring
        ShardedTrainStep.sparse_layout: None when no parameter carries
        a ``row_sparse`` gradient; otherwise the update mode (lazy when
        the optimizer dispatches lazy row updates) and the (vocab, dim)
        of every sparse-grad table. Provenance only — state tensors
        stay table-shaped either way."""
        tables = {}
        for p in self._params:
            if p._grad_stype != 'row_sparse':
                continue
            shape = tuple(p.shape or ())
            if len(shape) == 2:
                tables[p.name] = {'vocab': int(shape[0]),
                                  'dim': int(shape[1])}
        if not tables:
            return None
        lazy = bool(getattr(self._optimizer, 'lazy_update', False))
        return {'mode': 'lazy' if lazy else 'exact',
                'table_axis': None, 'tables': tables}

    def _compression_requested(self):
        return self._compression_params is not None and \
            self._compression_params.get('type', '2bit') != 'none'

    def _local_compression(self):
        """The trainer-owned error-feedback compressor for the paths
        that never pass a kvstore push (kvstore=None, and the
        GSPMD-mesh / single-copy path where the push is skipped) —
        routed for real instead of rejected (ISSUE 12). Residuals key
        by parameter index; a ``set_states_bytes`` restore resets them
        (deterministic reseed — the old error state no longer describes
        the rewound trajectory)."""
        comp = getattr(self, '_local_gc', None)
        if comp is None:
            from ..kvstore.gradient_compression import GradientCompression
            p = self._compression_params or {}
            comp = self._local_gc = GradientCompression(
                p.get('type', '2bit'), p.get('threshold', 0.5),
                p.get('block_size', 0))
        return comp

    def _init_kvstore(self):
        """Ref: trainer.py:174."""
        if self._kvstore_type is None or self._kvstore_type is False:
            self._kvstore = None
            if self._update_on_kvstore is None:
                self._update_on_kvstore = False
        else:
            kv = self._kvstore_type if isinstance(self._kvstore_type, kvs.KVStoreBase) \
                else kvs.create(self._kvstore_type)
            self._kvstore = kv
            if self._compression_params:
                kv.set_gradient_compression(self._compression_params)
            if self._update_on_kvstore is None:
                # local training prefers updating on workers (ref :195);
                # dist + sparse forces update_on_kvstore
                self._update_on_kvstore = bool(self._contains_sparse_weight)
            if self._update_on_kvstore:
                kv.set_optimizer(self._optimizer)
        # one updater shared across ctxs: reference keeps per-device updaters
        # but states are per-parameter, so a single updater suffices here.
        self._updater = opt.get_updater(self._optimizer)
        # register params into kvstore
        if self._kvstore is not None:
            for i, param in enumerate(self._params):
                if param._data is not None:
                    self._kvstore.init(i, param.data(param.list_ctx()[0]))
        self._kv_initialized = True
        # memory observability: this trainer's params + optimizer state
        # become tracked pools for the fallback watermark (weakly
        # referenced — a dropped trainer never pins its arrays)
        _memory.register_provider(self)

    def _row_sparse_pull(self, parameter, out, row_id, full_idx=False):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._kvstore is None:
            return
        idx = self._param2idx[parameter.name]
        self._kvstore.row_sparse_pull(idx, out=out, row_ids=row_id)

    def step(self, batch_size, ignore_stale_grad=False):
        """Gradient sync + optimizer update (ref: trainer.py:320)."""
        if _telem['on']:
            import time as _time
            from .. import telemetry as _telemetry
            now = _time.perf_counter()
            last, ema = self._telem_last_step, self._telem_step_ema
            self._telem_last_step = now
            if last is not None:
                dt = now - last
                if ema is None:
                    # the first interval seeds the filter but is NOT
                    # recorded: it typically contains the step compile
                    # (and may contain a pause), either of which would
                    # poison both the histogram and the EMA baseline
                    self._telem_step_ema = dt
                elif dt <= 20.0 * ema:
                    _telemetry.record_step(dt, batch_size)
                    self._telem_step_ema = 0.9 * ema + 0.1 * dt
                # else: >20x the running step time is a pause (eval,
                # checkpoint) or a recompile spike, not a step — keep it
                # out of the histogram and the samples/sec + MFU gauges
        if self._elastic is not None:
            # preemption -> Preempted (final checkpoint committed);
            # peer loss -> commit + mesh re-form + restore happened just
            # now: the gradients in the param buffers were computed
            # against pre-re-form weights, so this step's update is
            # dropped and training resumes on the next batch
            if self._elastic.pre_step() is not None:
                return
        if not self._kv_initialized:
            self._init_kvstore()
        with _trace.span('step.dispatch'):
            kind = _faults.fire('step.dispatch')
            if kind == 'nan':
                self._poison_grads()
            if self._guard is not None and \
                    self._guard.pre_step(on_bad=self._rewind_update_counts):
                # a rollback just restored params/optimizer/RNG: the
                # gradients sitting in the param buffers were computed
                # against the pre-rollback weights — applying them would
                # corrupt the freshly restored state, so this step's
                # update is dropped and training resumes on the next
                # batch
                return
            self._optimizer.rescale_grad = self._scale / batch_size
            with _trace.span('comm.allreduce'):
                self._allreduce_grads()
            with _trace.span('optimizer.update'), \
                    _memory.oom_guard('step.dispatch'):
                self._update(ignore_stale_grad)
        _memory.on_step(self._optimizer.num_update)
        _flight.record_step(self._optimizer.num_update)
        if self._elastic is not None:
            # feed the controller's commit point (and the heartbeat's
            # piggybacked step) — an elastic commit must capture THIS
            # step, not the last cadence save
            self._elastic.beat(self._optimizer.num_update)

    def attach_guard(self, guard):
        """Bind a ``resilience.NonFiniteGuard``. The fused update gains
        an on-device all-gradients-finite reduction whose flag the guard
        reads (deferred, no extra host sync) at the next step; a
        non-finite step's weight/state writeback is skipped inside the
        same XLA program. Forces a retrace (the guard changes the fused
        program's signature)."""
        self._guard = guard
        self._fused_cache = None
        self._fused_traced = False

    def attach_elastic(self, controller):
        """Bind a ``resilience.ElasticController``: every ``step()``
        then consults it first (preemption -> ``Preempted`` after the
        final commit; peer loss -> commit + re-form + restore, this
        step's stale gradients dropped) and the controller re-forms this
        trainer via ``_on_reform`` — user training loops run
        unmodified."""
        self._elastic = controller
        controller.attach_trainer(self)
        return controller

    def _on_reform(self, mesh=None):
        """Elastic re-form: the world size (and with it the dp degree
        and ZeRO layout) just changed. Drop the fused-update cache and
        the remembered ZeRO placement so the next step re-derives the
        layout from wherever the restored weights now live; the
        optimizer-state scatter re-runs there too (the restored states
        payload is host-gathered, same as after set_states_bytes)."""
        self._fused_cache = None
        self._fused_traced = False
        self._zero_active = False
        self._zero_dp = 1
        self._zero_stage = 0
        self._zero3_mesh = mesh if mesh is not None and \
            dict(getattr(mesh, 'shape', {})).get('dp', 0) > 1 else None
        self.reset_step_timer()

    def _poison_grads(self):
        """Injected ``step.dispatch:nan`` fault: overwrite every gradient
        with NaN on device, so the guard's detection/skip/rollback path
        is exercised by a REAL non-finite step."""
        for param in self._params:
            if param.grad_req == 'null' or param._data is None:
                continue
            for g in param.list_grad():
                g._data = g._data * float('nan')

    def _guard_grads_ok(self, grads=None):
        """Eager all-finite check (host sync — only for the paths that
        cannot fuse the check into a compiled program: kvstore-side
        updates and non-traceable optimizers). ``grads`` is an optional
        iterable of gradient NDArrays; by default every parameter's
        gradient copies are scanned."""
        import jax.numpy as jnp
        if grads is None:
            grads = (g for param in self._params
                     if param.grad_req != 'null' and param._data is not None
                     for g in param.list_grad())
        # reduce on device first: ONE host sync per step, not one per
        # gradient
        checks = [jnp.all(jnp.isfinite(g._data)) for g in grads]
        if not checks:
            return True
        return bool(jnp.all(jnp.stack(checks)))

    def _rewind_update_counts(self):
        """A guard-skipped step was a device no-op, but the fused
        dispatch advanced the host-side optimizer update counts before
        the flag was known — rewind them so bias correction and
        num_update-keyed LR schedules see the skip as a true no-op.
        (The pjit ShardedTrainStep keeps t inside the where-gated
        optimizer state, so only this path needs the rewind.)"""
        snap = getattr(self, '_fused_count_snapshot', None)
        if snap is not None:
            counts, num = snap
            self._optimizer._index_update_count = dict(counts)
            self._optimizer.num_update = num
            self._fused_count_snapshot = None

    def reset_step_timer(self):
        """Forget the previous step() timestamp so an intervening pause
        (validation pass, checkpoint save) is not measured as step time
        by the telemetry step histogram. Call after any long gap."""
        self._telem_last_step = None

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        """Ref: trainer.py:349."""
        if self._kvstore is None:
            return
        for i, param in enumerate(self._params):
            if param.grad_req == 'null' or param._data is None:
                continue
            grads = param.list_grad()
            if len(grads) == 1 and self._kvstore.num_workers == 1:
                if self._compression_requested() and \
                        not self._update_on_kvstore:
                    # update_on_kvstore pushes in _update (compression
                    # applies there); THIS path skips the push entirely,
                    # so apply the SAME error-feedback codec in place —
                    # the semantics of a push through a compressing
                    # kvstore, minus the no-op self-reduce (ISSUE 12:
                    # routed for real instead of raising)
                    comp = self._kvstore._compression \
                        if getattr(self._kvstore, '_compression', None) \
                        is not None else self._local_compression()
                    grads[0]._data = comp.compress_decompress(
                        grads[0], i)._data
                continue
            if self._update_on_kvstore:
                continue  # push+pull happens in _update via kvstore updater
            self._kvstore.push(i, grads)
            self._kvstore.pull(i, grads, ignore_sparse=False)

    def _update(self, ignore_stale_grad=False):
        """Ref: trainer.py:430."""
        # AMP dynamic loss scaling: skip the update on non-finite grads and
        # shrink the scale (ref: contrib/amp/loss_scaler.py via trainer
        # hook). Lives here so both step() and update()/allreduce_grads()
        # (gradient accumulation) are covered.
        scaler = getattr(self, '_amp_loss_scaler', None)
        if scaler is not None and scaler.dynamic:
            overflow = scaler.has_overflow(self._params)
            scaler.update_scale(overflow)
            if overflow:
                return
        if self._update_on_kvstore and self._kvstore is not None:
            if self._guard is not None:
                # the update applies on the kvstore side, out of reach of
                # the fused on-device gate — check eagerly BEFORE the
                # push, or a NaN step poisons every replica
                self._fused_count_snapshot = None   # nothing to rewind
                ok = self._guard_grads_ok()
                self._guard.push_flag(ok)
                if not ok:
                    return
            for i, param in enumerate(self._params):
                if param.grad_req == 'null' or param._data is None:
                    continue
                self._kvstore.push(i, param.list_grad())
                self._kvstore.pull(i, param.list_data())
            return
        import jax
        from ..kvstore.kvstore import _reduce
        compress_here = self._kvstore is None and \
            self._compression_requested()
        items = []
        for i, param in enumerate(self._params):
            if param.grad_req == 'null' or param._data is None:
                continue
            datas = param.list_data()
            grads = param.list_grad()
            # after allreduce every ctx grad is identical; with no kvstore
            # the reduction happens here so no context's contribution drops
            g = grads[0] if (self._kvstore is not None or len(grads) == 1) \
                else _reduce(grads)
            if compress_here:
                # kvstore=None: no push exists, so the error-feedback
                # codec applies to the merged gradient right here
                # (ISSUE 12: routed for real instead of raising)
                g = self._local_compression().compress_decompress(g, i)
            items.append((i, param, g, datas))
        # one jitted multi-tensor apply for ALL parameters (the analog of
        # the reference's fused preloaded_multi_sgd/multi_lamb update ops,
        # ref: src/operator/contrib/preloaded_multi_sgd.cc) — falls back to
        # the per-param python loop for optimizers that sync to host
        # mid-update (e.g. LARS norms)
        if self._fused_apply(items):
            pass
        else:
            if self._guard is not None and items:
                # eager fallback can't skip on device: check the grads
                # up front (this path already syncs per parameter); the
                # skip happens before any count advances — no rewind
                self._fused_count_snapshot = None
                ok = self._guard_grads_ok([g for _, _, g, _ in items])
                self._guard.push_flag(ok)
                if not ok:
                    return
            for i, param, g, datas in items:
                self._updater(i, g, datas[0])
        # broadcast the updated first copy to the other context copies
        # (ref: trainer.py:430 per-device update; collapsed so state
        # copies don't ping-pong between devices). ONE batched
        # device_put for every (param, copy) pair — per-array transfers
        # paid a dispatch round-trip per parameter per step.
        dsts, srcs, shards = [], [], []
        for i, param, g, datas in items:
            src = datas[0]._data
            for d in datas[1:]:
                dsts.append(d)
                srcs.append(src)
                shards.append(d._data.sharding)
        if dsts:
            with _trace.span('comm.broadcast'):
                for d, out in zip(dsts, jax.device_put(srcs, shards)):
                    d._data = out
            if _telem['on']:
                from .. import telemetry as _telemetry
                _telemetry.counter(
                    'mxnet_tpu_comm_collective_bytes_total').inc(
                        sum(int(s.size) * s.dtype.itemsize for s in srcs),
                        kind='broadcast', axis='ctx')
                _telemetry.counter('mxnet_tpu_comm_collectives_total').inc(
                    1, kind='broadcast', axis='ctx')

    def _zero_layout(self, items):
        """Mesh layout for the fused update, or None when the weights'
        primary copies do not all live on one NamedSharding mesh. When
        they do, the optimizer states must be placed on that mesh too
        (a jit cannot mix device sets). 'zero' is set when MXTPU_ZERO
        allows (default on) and the mesh has a 'dp' axis of >1 devices:
        each optimizer-state tensor (fp32 master + moments) then shards
        1/dp over that axis — the traced multi-tensor update computes
        only the local slice and all-gathers the updated weights back to
        their own layout. With zero off the states replicate.

        Stage 3 (MXTPU_ZERO=3): the weight NDArrays THEMSELVES are
        re-placed dp-sharded (one batched device_put) and the fused
        update's out_shardings keep them sharded — eager forward/backward
        consume the logically-global sharded arrays directly, so user
        training loops run unmodified. A checkpoint restore rewrites the
        params as host arrays; the mesh is remembered and the placement
        re-runs on the next fused-cache rebuild (set_states_bytes clears
        the cache)."""
        from jax.sharding import NamedSharding, PartitionSpec
        from ..parallel.step import compose_zero_spec
        from .. import config as _config
        stage = int(_config.get('MXTPU_ZERO') or 0)
        mesh = None
        on_mesh = True
        for _, _, _, datas in items:
            sh = datas[0]._data.sharding
            if not isinstance(sh, NamedSharding):
                on_mesh = False
                break
            if mesh is None:
                mesh = sh.mesh
            elif sh.mesh != mesh:
                return None
        replaced = False
        if not on_mesh:
            # a restore (CheckpointManager / load_params) rewrote the
            # weights as host arrays: under a previously-active stage 3
            # re-adopt the remembered mesh and re-place below
            if stage == 3 and self._zero3_mesh is not None:
                mesh, replaced = self._zero3_mesh, True
            else:
                return None
        if mesh is None:
            return None
        dp = dict(mesh.shape).get('dp', 0)
        zero_on = stage >= 1 and dp > 1
        stage3 = stage == 3 and dp > 1
        repl = NamedSharding(mesh, PartitionSpec())
        w_sh, state_sh, place = [], [], []
        for _, _, _, datas in items:
            cur = datas[0]._data.sharding
            if not isinstance(cur, NamedSharding):
                cur = repl
            zspec = compose_zero_spec(tuple(datas[0].shape), cur.spec,
                                      'dp', dp) if zero_on else None
            zsh = NamedSharding(mesh, zspec) if zspec is not None else None
            target = zsh if (stage3 and zsh is not None) else cur
            w_sh.append(target)
            state_sh.append(zsh)
            if (stage3 or replaced) and \
                    datas[0]._data.sharding != target:
                place.append((datas[0], target))
        if place:
            import jax
            with _memory.oom_guard('h2d.param_place'):
                placed = jax.device_put([d._data for d, _ in place],
                                        [sh for _, sh in place])
            nbytes = 0
            for (d, _), out in zip(place, placed):
                d._data = out
                nbytes += int(out.size) * out.dtype.itemsize
            if _telem['on']:
                from .. import telemetry as _telemetry
                _telemetry.counter(
                    'mxnet_tpu_comm_collective_bytes_total').inc(
                        nbytes, kind='param_scatter', axis='dp')
                _telemetry.counter('mxnet_tpu_comm_collectives_total').inc(
                    1, kind='param_scatter', axis='dp')
        self._zero3_mesh = mesh if stage3 else None
        return {'mesh': mesh, 'dp': dp if zero_on else 1, 'zero': zero_on,
                'stage': (3 if stage3 else 1) if zero_on else 0,
                'w_sh': w_sh, 'state_sh': state_sh, 'repl': repl}

    def _zero_place_states(self, items, zero):
        """Scatter optimizer-state NDArrays into the ZeRO layout (one
        batched transfer). Weight-shaped leaves take the param's 1/dp
        spec; everything else replicates onto the mesh so the fused jit
        sees one device set. Re-runs after set_states_bytes — a restored
        payload is host-gathered numpy, so checkpoints stay
        layout-independent and resume at any dp degree."""
        import jax
        from ..ndarray.ndarray import NDArray
        pending = []

        def _walk(s, target, wshape):
            if isinstance(s, NDArray):
                sh = target if tuple(s._data.shape) == wshape \
                    else zero['repl']
                if s._data.sharding != sh:
                    pending.append((s, sh))
            elif isinstance(s, (list, tuple)):
                for x in s:
                    _walk(x, target, wshape)

        for n, (i, p, g, datas) in enumerate(items):
            # no 1/dp spec -> weight-shaped leaves follow the weight's own
            # layout (fsdp-style dp-sharded weights keep sharded states)
            _walk(self._updater.states[i],
                  zero['state_sh'][n] or zero['w_sh'][n],
                  tuple(datas[0].shape))
        if pending:
            with _memory.oom_guard('h2d.param_place'):
                placed = jax.device_put([s._data for s, _ in pending],
                                        [sh for _, sh in pending])
            nbytes = 0
            for (s, _), d in zip(pending, placed):
                s._data = d
                nbytes += int(d.size) * d.dtype.itemsize
            if _telem['on']:
                from .. import telemetry as _telemetry
                _telemetry.counter(
                    'mxnet_tpu_comm_collective_bytes_total').inc(
                        nbytes, kind='state_scatter', axis='dp')
                _telemetry.counter('mxnet_tpu_comm_collectives_total').inc(
                    1, kind='state_scatter', axis='dp')
        if _telem['on']:
            from .. import telemetry as _telemetry
            _telemetry.set_gauge(
                'mxnet_tpu_comm_opt_state_bytes_per_device',
                self.opt_state_bytes_per_device())
            _telemetry.set_gauge(
                'mxnet_tpu_comm_param_bytes_per_device',
                self.param_bytes_per_device())

    def opt_state_bytes_per_device(self):
        """Bytes of optimizer state ONE device holds (ZeRO-1: ~1/dp of
        the replicated footprint, ± tensors too small to shard)."""
        from ..ndarray.ndarray import NDArray
        from ..parallel.step import device_nbytes
        total = 0

        def _walk(s):
            nonlocal total
            if isinstance(s, NDArray):
                total += device_nbytes(s._data)
            elif isinstance(s, (list, tuple)):
                for x in s:
                    _walk(x)

        if self._updater is not None:
            for st in self._updater.states.values():
                _walk(st)
        return total

    def param_bytes_per_device(self):
        """Bytes of the parameters' primary copies ONE device holds —
        under ZeRO-3 (stage-3 fused layout) the dp-sharded weights count
        their 1/dp shard; replicated/single-device weights count in
        full."""
        from ..parallel.step import device_nbytes
        total = 0
        for p in self._params:
            if p._data is None:
                continue
            total += device_nbytes(p.data()._data)
        return total

    def memory_pools(self):
        """The trainer path's live arrays as named residency pools for
        ``telemetry.memory``'s fallback watermark — the gluon sibling
        of ``ShardedTrainStep.memory_pools`` (params' primary copies +
        the updater's per-param optimizer state)."""
        from ..ndarray.ndarray import NDArray
        pools = {'params': {}, 'optimizer_state': {}}
        for p in self._params:
            if p._data is not None:
                pools['params'][p.name] = p.data()._data

        def _walk(prefix, s):
            if isinstance(s, NDArray):
                pools['optimizer_state'][prefix] = s._data
            elif isinstance(s, (list, tuple)):
                for j, x in enumerate(s):
                    _walk(f'{prefix}/{j}', x)

        if self._updater is not None:
            names = {i: p.name for i, p in enumerate(self._params)}
            for i, st in self._updater.states.items():
                _walk(f'state/{names.get(i, i)}', st)
        return pools

    def _fused_apply(self, items):
        """Run every parameter update as ONE compiled XLA program.

        The optimizer's python `update()` is traced once (per param-set /
        dtype signature) with the per-step host scalars — lr, wd, update
        count t, rescale_grad — fed in as traced inputs, so subsequent
        steps re-run the cached program with zero python dispatch per
        parameter. Optimizer state NDArrays are updated in place (their
        `_data` is swapped), preserving save_states()/set_states().
        Returns False when the optimizer cannot be traced (host syncs) —
        caller falls back to the eager per-param loop."""
        if not items:
            return True
        if getattr(self, '_fused_disabled', False):
            return False
        if not getattr(self._optimizer, 'fused_update', False):
            # opt-in only: an impure update() (host syncs, python-state
            # mutation) can trace "successfully" but compute the wrong
            # schedule — never guess
            self._fused_disabled = True
            return False
        if any(p._stype != 'default' or p._grad_stype != 'default'
               for _, p, _, _ in items):
            return False
        import jax
        import jax.numpy as jnp
        from ..ndarray.ndarray import NDArray

        opt = self._optimizer
        updater = self._updater
        indices = [i for i, _, _, _ in items]
        # materialize states eagerly (outside the trace)
        for i, p, g, datas in items:
            if i not in updater.states:
                updater.states[i] = opt.create_state_multi_precision(
                    i, datas[0])
                updater.states_synced[i] = True
        # mesh-resident weights: states must live on the same mesh —
        # sharded 1/dp under ZeRO-1, replicated otherwise. Layout
        # detection + placement walk every param, so they run only in
        # the cache-(re)build branch below (first step, new param
        # set/dtype, or after set_states_bytes cleared the cache to
        # re-scatter a restore) — never on the per-step hot path.
        zero = None

        def _flat(s, out):
            if isinstance(s, NDArray):
                out.append(s._data)
            elif isinstance(s, (list, tuple)):
                for x in s:
                    _flat(x, out)
            return out

        def _reshape(s, leaves):
            """Rebuild the state structure from flat leaves as NDArrays."""
            if isinstance(s, NDArray):
                return NDArray(leaves.pop(0))
            if isinstance(s, (list, tuple)):
                return tuple(_reshape(x, leaves) for x in s)
            return s

        guard_on = self._guard is not None
        sig = (tuple(indices), opt.__class__,
               tuple(d._data.dtype.name for _, _, _, ds in items
                     for d in ds[:1]),
               guard_on,
               (self._zero_active, self._zero_dp, self._zero_stage))
        cache = getattr(self, '_fused_cache', None)
        if cache is None or cache[0] != sig:
            zero = self._zero_layout(items)
            self._zero_active = zero is not None and zero['zero']
            self._zero_dp = zero['dp'] if zero else 1
            self._zero_stage = zero['stage'] if zero else 0
            if zero is not None:
                self._zero_place_states(items, zero)
            sig = sig[:4] + ((self._zero_active, self._zero_dp,
                              self._zero_stage),)
            structs = [updater.states[i] for i in indices]
            zero_cache = zero

            # wds ride as a STATIC tuple: the ops branch on `if wd` with
            # python control flow, so weight decay must be concrete at
            # trace time (wd changes retrace — they only change via
            # set_wd_mult, not per step). lr/t/rescale are traced.
            def fused(weights, grads, states_flat, lrs, ts, rescale, wds):
                leaves = list(states_flat)
                saved_count = opt._index_update_count
                saved_rescale = opt.rescale_grad
                pos = {idx: n for n, idx in enumerate(indices)}
                # shadow the scalar accessors on the INSTANCE with traced
                # values for the duration of the trace; the class methods
                # come back when the shadows are deleted (restoring bound
                # methods would leave unpicklable attrs in __dict__,
                # breaking save_states(dump_optimizer=True))
                opt._get_lr = lambda idx: lrs[pos[idx]]
                opt._get_wd = lambda idx: wds[pos[idx]]
                opt._update_count = lambda idx: None
                opt._index_update_count = \
                    type('T', (), {'__getitem__':
                                   staticmethod(lambda idx: ts[pos[idx]])})()
                opt.rescale_grad = rescale
                try:
                    new_w, new_s, gs = [], [], []
                    for n, idx in enumerate(indices):
                        w = NDArray(weights[n])
                        gdat = grads[n]
                        if zero_cache is not None and \
                                zero_cache['state_sh'][n] is not None:
                            # the grad is consumed against 1/dp-sharded
                            # moments: constrain it so the partitioner
                            # slices once up front instead of keeping
                            # the full copy live through the update
                            gdat = jax.lax.with_sharding_constraint(
                                gdat, zero_cache['state_sh'][n])
                        gs.append(gdat)
                        g = NDArray(gdat)
                        st = _reshape(structs[n], leaves)
                        opt.update_multi_precision(idx, w, g, st)
                        wd_ = w._data
                        if zero_cache is not None:
                            # all-gather the updated weight back to its
                            # own (replicated / tp) layout
                            wd_ = jax.lax.with_sharding_constraint(
                                wd_, zero_cache['w_sh'][n])
                        new_w.append(wd_)
                        new_s.extend(_flat(st, []))
                finally:
                    for name in ('_get_lr', '_get_wd', '_update_count'):
                        opt.__dict__.pop(name, None)
                    opt._index_update_count = saved_count
                    opt.rescale_grad = saved_rescale
                if guard_on:
                    # non-finite guard, fused into THIS program: one
                    # isfinite reduction over every gradient in its
                    # SHARDED (reduce-scattered) layout where ZeRO is
                    # active — each device scans 1/dp and GSPMD psums
                    # the flag — and the whole writeback gated on it; a
                    # NaN/Inf step keeps the old weights and optimizer
                    # state on device; the host reads the flag a step
                    # later (no extra sync)
                    import functools as _functools
                    ok = _functools.reduce(
                        jnp.logical_and,
                        [jnp.all(jnp.isfinite(g)) for g in gs])
                    new_w = [jnp.where(ok, nw, w)
                             for nw, w in zip(new_w, weights)]
                    new_s = [jnp.where(ok, ns, s)
                             for ns, s in zip(new_s, states_flat)]
                    return new_w, new_s, ok
                return new_w, new_s

            jit_kwargs = {}
            if zero_cache is not None:
                # pin outputs: weights back to their own layout, state
                # leaves to the ZeRO layout they arrived in (donation
                # then reuses the sharded buffers in place)
                leaf_sh = [x.sharding for i in indices
                           for x in _flat(updater.states[i], [])]
                out_sh = ([s for s in zero_cache['w_sh']], leaf_sh)
                if guard_on:
                    out_sh = out_sh + (zero_cache['repl'],)
                jit_kwargs['out_shardings'] = out_sh
            jitted = jax.jit(fused, donate_argnums=(0, 2),
                             static_argnums=(6,), **jit_kwargs)
            self._fused_cache = (sig, fused, jitted)
            self._fused_traced = False
        elif _telem['on']:
            from .. import telemetry as _telemetry
            _telemetry.record_cache_hit('trainer:fused_update')
        _, fused_fn, jitted = self._fused_cache

        # host-side per-step scalars (counts first, as the reference does);
        # snapshot them so a failed trace can roll back before the eager
        # fallback re-counts — and so the guard can rewind the advance
        # if this step's flag comes back non-finite (device no-op)
        count_snapshot = (dict(opt._index_update_count), opt.num_update)
        self._fused_count_snapshot = count_snapshot
        for i in indices:
            opt._update_count(i)
        lrs = jnp.asarray(opt._get_lrs(indices), jnp.float32)
        wds = tuple(float(w) for w in opt._get_wds(indices))
        ts = jnp.asarray([opt._index_update_count[i] for i in indices],
                         jnp.float32)
        rescale = jnp.asarray(opt.rescale_grad, jnp.float32)
        weights = [datas[0]._data for _, _, _, datas in items]
        grads = [g._data for _, _, g, _ in items]
        states_flat = []
        for i in indices:
            _flat(updater.states[i], states_flat)
        was_traced = getattr(self, '_fused_traced', False)
        cctx = None
        if not was_traced:
            # compile ledger window: eval_shape trace probe + the first
            # jitted execution below (where XLA lazily compiles)
            cctx = _compile.begin('trainer:fused_update')
            # probe traceability ABSTRACTLY first: eval_shape consumes no
            # buffers, so a trace failure here can still fall back to the
            # eager loop with every weight/state intact. The real jitted
            # call below donates its inputs — after it dispatches there is
            # nothing to fall back TO, so its errors propagate.
            try:
                import time as _time
                t0 = _time.perf_counter()
                jax.eval_shape(lambda w, g, s, a, b, c: fused_fn(
                    w, g, s, a, b, c, wds), weights, grads, states_flat,
                    lrs, ts, rescale)
                self._fused_traced = True
                if cctx is not None:
                    _compile.set_signature(cctx, _compile.signature(
                        args=[_compile.array_sig(f'w{n}', w, donated=True)
                              for n, w in enumerate(weights[:8])],
                        flags={'optimizer': opt.__class__.__name__,
                               'guard': bool(guard_on),
                               'zero': self._zero_stage
                               if self._zero_active else 0,
                               'dp': self._zero_dp,
                               'params': len(weights),
                               'state_leaves': len(states_flat)}))
                elif _telem['on']:
                    from .. import telemetry as _telemetry
                    _telemetry.record_compile(
                        'trainer:fused_update', repr(sig),
                        _time.perf_counter() - t0)
            except Exception:
                _compile.abort(cctx)
                from .. import config as _config
                if _config.get('MXNET_TPU_FUSED_DEBUG'):
                    import traceback
                    traceback.print_exc()
                import warnings
                warnings.warn(
                    f"Trainer: {opt.__class__.__name__}.update() did not "
                    f"trace; falling back to the eager per-parameter "
                    f"update loop for this trainer.", RuntimeWarning)
                # restore the update counts the eager path will re-apply
                opt._index_update_count, opt.num_update = count_snapshot
                self._fused_disabled = True
                self._fused_cache = None
                return False
        import time as _time
        t0 = _time.perf_counter()
        try:
            with _trace.span('optimizer.fused'):
                out = jitted(weights, grads, states_flat, lrs, ts,
                             rescale, wds)
        except BaseException:
            _compile.abort(cctx)
            raise
        if guard_on:
            new_w, new_s, ok_flag = out
            self._guard.push_flag(ok_flag)
        else:
            new_w, new_s = out
        if not was_traced:
            # first execution after a (re)trace: jit is lazy, so this is
            # where XLA actually compiles — account it as compile time
            if cctx is not None:
                _compile.end(cctx)
            elif _telem['on']:
                from .. import telemetry as _telemetry
                _telemetry.counter('mxnet_tpu_compile_seconds_total').inc(
                    _time.perf_counter() - t0, site='trainer:fused_update')
        for (_, _, _, datas), w in zip(items, new_w):
            datas[0]._data = w
        pos = 0

        def _assign(s):
            nonlocal pos
            if isinstance(s, NDArray):
                s._data = new_s[pos]
                pos += 1
            elif isinstance(s, (list, tuple)):
                for x in s:
                    _assign(x)
        for i in indices:
            _assign(updater.states[i])
        return True

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def get_states_bytes(self):
        """The save_states payload as bytes: optimizer states + the
        pickled optimizer itself (update counts, rescale_grad, schedule
        position). This is what checkpoint.CheckpointManager snapshots on
        the training thread for an async save."""
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        return self._updater.get_states(dump_optimizer=True)

    def set_states_bytes(self, states):
        """Restore a get_states_bytes() payload (CheckpointManager's
        restore path; load_states is the file-based wrapper)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._updater.set_states(states)
        # a restore rewinds the trajectory: carried error-feedback
        # residuals no longer describe it — deterministic zero reseed
        # (the kvstore compressor keys residuals the same way)
        if getattr(self, '_local_gc', None) is not None:
            self._local_gc.reset()
        if self._kvstore is not None and \
                getattr(self._kvstore, '_compression', None) is not None:
            self._kvstore._compression.reset()
        if hasattr(self._updater, 'optimizer'):
            self._optimizer = self._updater.optimizer
            # re-attach live params: __getstate__ drops param_dict, so
            # per-parameter lr_mult/wd_mult must be rebound after restore
            self._optimizer.param_dict = {
                i: p for i, p in enumerate(self._params)}
        # the restored optimizer replaces the one the fused-update trace
        # closed over — force a retrace against the new instance
        self._fused_cache = None
        self._fused_traced = False

    def save_states(self, fname):
        """Ref: trainer.py:463. Atomic: tmp file + os.replace, so a kill
        mid-write never corrupts the previous states file."""
        from ..serialization import atomic_write_file
        atomic_write_file(fname, self.get_states_bytes())

    def load_states(self, fname):
        """Ref: trainer.py:492."""
        with open(fname, 'rb') as f:
            states = f.read()
        self.set_states_bytes(states)
