"""Gluon Trainer (ref: python/mxnet/gluon/trainer.py).

API-compatible DP training driver. On a mesh-sharded compiled path the
gradient all-reduce is emitted by XLA inside the step function; in the
eager/multi-context path the kvstore reduces across device copies
(ref: trainer.py:174-261 _init_kvstore, :320 step, :349 allreduce_grads,
:430 _update).
"""
from __future__ import annotations

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .. import optimizer as opt
from .. import kvstore as kvs
from .parameter import ParameterDict, Parameter


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore='device',
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError("First argument must be a list or dict of Parameters")
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise ValueError(f"First argument must contain Parameters, got {type(param)}")
            self._param2idx[param.name] = i
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get('rescale_grad', 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_type = kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._update_on_kvstore = update_on_kvstore
        self._params_to_init = []
        self._contains_sparse_weight = any(
            p._stype != 'default' for p in self._params)
        self._contains_sparse_grad = any(
            p._grad_stype != 'default' for p in self._params)

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an Optimizer instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = None

    @property
    def optimizer(self):
        return self._optimizer

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def _init_kvstore(self):
        """Ref: trainer.py:174."""
        if self._kvstore_type is None or self._kvstore_type is False:
            self._kvstore = None
            if self._update_on_kvstore is None:
                self._update_on_kvstore = False
        else:
            kv = self._kvstore_type if isinstance(self._kvstore_type, kvs.KVStoreBase) \
                else kvs.create(self._kvstore_type)
            self._kvstore = kv
            if self._compression_params:
                kv.set_gradient_compression(self._compression_params)
            if self._update_on_kvstore is None:
                # local training prefers updating on workers (ref :195);
                # dist + sparse forces update_on_kvstore
                self._update_on_kvstore = bool(self._contains_sparse_weight)
            if self._update_on_kvstore:
                kv.set_optimizer(self._optimizer)
        # one updater shared across ctxs: reference keeps per-device updaters
        # but states are per-parameter, so a single updater suffices here.
        self._updater = opt.get_updater(self._optimizer)
        # register params into kvstore
        if self._kvstore is not None:
            for i, param in enumerate(self._params):
                if param._data is not None:
                    self._kvstore.init(i, param.data(param.list_ctx()[0]))
        self._kv_initialized = True

    def _row_sparse_pull(self, parameter, out, row_id, full_idx=False):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._kvstore is None:
            return
        idx = self._param2idx[parameter.name]
        self._kvstore.row_sparse_pull(idx, out=out, row_ids=row_id)

    def step(self, batch_size, ignore_stale_grad=False):
        """Gradient sync + optimizer update (ref: trainer.py:320)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        """Ref: trainer.py:349."""
        if self._kvstore is None:
            return
        for i, param in enumerate(self._params):
            if param.grad_req == 'null' or param._data is None:
                continue
            grads = param.list_grad()
            if len(grads) == 1 and self._kvstore.num_workers == 1:
                continue
            if self._update_on_kvstore:
                continue  # push+pull happens in _update via kvstore updater
            self._kvstore.push(i, grads)
            self._kvstore.pull(i, grads, ignore_sparse=False)

    def _update(self, ignore_stale_grad=False):
        """Ref: trainer.py:430."""
        # AMP dynamic loss scaling: skip the update on non-finite grads and
        # shrink the scale (ref: contrib/amp/loss_scaler.py via trainer
        # hook). Lives here so both step() and update()/allreduce_grads()
        # (gradient accumulation) are covered.
        scaler = getattr(self, '_amp_loss_scaler', None)
        if scaler is not None and scaler.dynamic:
            overflow = scaler.has_overflow(self._params)
            scaler.update_scale(overflow)
            if overflow:
                return
        if self._update_on_kvstore and self._kvstore is not None:
            for i, param in enumerate(self._params):
                if param.grad_req == 'null' or param._data is None:
                    continue
                self._kvstore.push(i, param.list_grad())
                self._kvstore.pull(i, param.list_data())
            return
        import jax
        from ..kvstore.kvstore import _reduce
        for i, param in enumerate(self._params):
            if param.grad_req == 'null' or param._data is None:
                continue
            datas = param.list_data()
            grads = param.list_grad()
            # after allreduce every ctx grad is identical; with no kvstore
            # the reduction happens here so no context's contribution drops
            g = grads[0] if (self._kvstore is not None or len(grads) == 1) \
                else _reduce(grads)
            # update the first copy (optimizer state lives with it),
            # broadcast to the rest (ref: trainer.py:430 per-device update;
            # collapsed so state copies don't ping-pong between devices)
            self._updater(i, g, datas[0])
            src = datas[0]._data
            for d in datas[1:]:
                d._data = jax.device_put(src, d._data.sharding)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def save_states(self, fname):
        """Ref: trainer.py:463."""
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        with open(fname, 'wb') as f:
            f.write(self._updater.get_states(dump_optimizer=True))

    def load_states(self, fname):
        """Ref: trainer.py:492."""
        if not self._kv_initialized:
            self._init_kvstore()
        with open(fname, 'rb') as f:
            states = f.read()
        self._updater.set_states(states)
        if hasattr(self._updater, 'optimizer'):
            self._optimizer = self._updater.optimizer
            # re-attach live params: __getstate__ drops param_dict, so
            # per-parameter lr_mult/wd_mult must be rebound after restore
            self._optimizer.param_dict = {
                i: p for i, p in enumerate(self._params)}
