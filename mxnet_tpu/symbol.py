"""Symbol: lazy graph construction API (ref: python/mxnet/symbol/symbol.py).

TPU-native design: a Symbol is a lightweight DAG node over the same op
registry the imperative path uses (there is no separate NNVM graph — the
"graph compile" is a jax.jit trace of the DAG evaluation, which is exactly
what CachedOp does for hybridized blocks). `simple_bind` returns an
Executor whose forward/backward run one compiled XLA executable each
(ref: src/executor/graph_executor.cc — memory planning, op fusion and
scheduling are XLA's job here).
"""
from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as onp

from .base import MXNetError, _OP_REGISTRY, get_op, telem_flags as _telem
from .context import cpu
from .ndarray.ndarray import NDArray, array, zeros as nd_zeros, _wrap


def _iter_nodes(root, order='pre', key=id):
    """Iterative DFS over the Symbol DAG, each node visited once (by
    `key`): no RecursionError on deep chains, no exponential re-walks of
    shared (residual/diamond) subgraphs. 'pre' yields a node before its
    inputs; 'post' after (inputs always precede consumers in 'post')."""
    seen = set()
    out = []
    stack = [(root, False)]
    while stack:
        node, expanded = stack.pop()
        if expanded:
            out.append(node)
            continue
        k = key(node)
        if k in seen:
            continue
        seen.add(k)
        if order == 'pre':
            out.append(node)
        else:
            stack.append((node, True))
        for i in reversed(node.inputs):
            stack.append((i, False))
    return out


def _resolve_name(op, name):
    """One naming path for nodes AND pre-named nodes (auto-created
    params need the node name before the node exists)."""
    from .name import current as _nm_current
    nm = _nm_current()
    if nm is not None:
        # managers see explicit names too: Prefix prepends to both
        # (reference semantics, name.py Prefix.get)
        return nm.get(name, op or 'var')
    if name is None:
        base = op if op else 'var'
        Symbol._counter[0] += 1
        return f"{base}{Symbol._counter[0]}"
    return name


class Symbol:
    _counter = [0]

    def __init__(self, op=None, inputs=(), attrs=None, name=None,
                 num_outputs=1, out_index=0, pre_resolved=False):
        self.op = op                  # None => variable
        self.inputs = list(inputs)
        self.attrs = dict(attrs or {})
        # pre_resolved: _apply already ran the name through the manager
        # (auto-created params need the node name before the node) —
        # resolving twice would double-apply a Prefix manager
        name = name if pre_resolved else _resolve_name(op, name)
        self._name = name
        self.num_outputs = num_outputs
        self.out_index = out_index
        # node identity shared by indexed output views (set by __getitem__);
        # variables share by name so rebuilt graphs bind consistently
        Symbol._counter[0] += 1
        self._uid = name if op is None else Symbol._counter[0]

    # ---- introspection ----------------------------------------------------
    @property
    def name(self):
        return self._name

    def list_arguments(self):
        seen = []
        for s in _iter_nodes(self, 'pre'):
            if s.op is None and s._name not in seen \
                    and not s.attrs.get('__aux__'):
                seen.append(s._name)
        return seen

    def list_outputs(self):
        return [self._name + '_output']

    def list_auxiliary_states(self):
        """Variables carrying the __aux__ marker (auto-created BN moving
        stats): allocated and initialized by executors, excluded from
        gradients and optimizer updates (ref: nnvm mutable inputs).
        NOTE symbol-path limitation (documented): training-mode BN
        normalizes with batch statistics but does not write running
        averages back into the aux arrays — the gluon path owns running
        stats; set_params/aux_dict load them for inference here."""
        seen = []
        for s in _iter_nodes(self, 'pre'):
            if s.op is None and s.attrs.get('__aux__') \
                    and s._name not in seen:
                seen.append(s._name)
        return seen

    def get_internals(self):
        return _SymbolList(_iter_nodes(self, 'post'))

    def attr(self, key):
        return self.attrs.get(key)

    def __getitem__(self, idx):
        if isinstance(idx, int):
            if self.num_outputs == 1:
                if idx != 0:
                    raise MXNetError("index out of range")
                return self
            if not 0 <= idx < self.num_outputs:
                raise MXNetError("index out of range")
            view = Symbol.__new__(Symbol)
            view.op = self.op
            view.inputs = list(self.inputs)
            view.attrs = dict(self.attrs)
            view._name = self._name   # verbatim: no NameManager re-prefix
            view.num_outputs = self.num_outputs
            view.out_index = idx
            view._uid = self._uid     # same node, different output slot
            return view
        raise MXNetError("Symbol only supports integer indexing")

    # ---- graph building ---------------------------------------------------
    def _bin(self, other, opname, scalar_op):
        if isinstance(other, Symbol):
            return _apply(opname, [self, other], {})
        return _apply(scalar_op, [self], {'scalar': other})

    def __add__(self, other):
        return self._bin(other, 'broadcast_add', 'plus_scalar')

    __radd__ = __add__

    def __sub__(self, other):
        return self._bin(other, 'broadcast_sub', 'minus_scalar')

    def __rsub__(self, other):
        return _apply('rminus_scalar', [self], {'scalar': other})

    def __mul__(self, other):
        return self._bin(other, 'broadcast_mul', 'mul_scalar')

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._bin(other, 'broadcast_div', 'div_scalar')

    def __rtruediv__(self, other):
        return _apply('rdiv_scalar', [self], {'scalar': other})

    def __pow__(self, other):
        return self._bin(other, 'broadcast_power', 'power_scalar')

    def __neg__(self):
        return _apply('negative', [self], {})

    # ---- evaluation -------------------------------------------------------
    def eval_dict(self, bindings):
        """Evaluate eagerly given {name: NDArray}."""
        cache = {}
        out = _eval_node(self, {k: (v._data if isinstance(v, NDArray) else v)
                                for k, v in bindings.items()}, cache)
        return _wrap(out)

    def eval(self, ctx=None, **kwargs):
        out = self.eval_dict(kwargs)
        return [out]

    def infer_shape(self, **shapes):
        """Shape inference via jax.eval_shape over the DAG."""
        names = self.list_arguments()
        specs = {}
        for n in names:
            if n in shapes:
                specs[n] = jax.ShapeDtypeStruct(tuple(shapes[n]), jnp.float32)
            else:
                return None, None, None
        def f(bind):
            cache = {}
            return _eval_node(self, bind, cache)
        out = jax.eval_shape(f, specs)
        arg_shapes = [tuple(specs[n].shape) for n in names]
        return arg_shapes, [tuple(out.shape)], []

    def infer_type(self, **types):
        names = self.list_arguments()
        return ([onp.float32] * len(names), [onp.float32], [])

    # ---- binding ----------------------------------------------------------
    def simple_bind(self, ctx=None, grad_req='write', group2ctx=None,
                    **shapes):
        """Ref: symbol.py:1507 simple_bind. group2ctx maps __ctx_group__
        attr values (set via mx.AttrScope(ctx_group=...)) to Contexts for
        manual model parallelism (ref: executor_group group2ctxs)."""
        names = self.list_arguments()
        # grouped variables allocate on their group's context so model
        # memory is actually distributed (the reference allocates args on
        # the group ctx) and the executor's per-node placement finds the
        # weights already resident — no per-step re-transfer
        arg_ctx = {n: ctx for n in names}
        if group2ctx:
            for node in _iter_nodes(self, 'pre', key=lambda n: n._uid):
                if node.op is None:
                    grp = node.attrs.get('__ctx_group__')
                    if grp in group2ctx:
                        arg_ctx[node._name] = group2ctx[grp]
        aux_names = self.list_auxiliary_states()
        missing = [n for n in names + aux_names if n not in shapes]
        if missing:
            # auto-created params + anything reachable by forward shape
            # propagation resolve here (ref: simple_bind's InferShape)
            inferred = infer_shapes_partial(self, shapes)
            for n in missing:
                if n in inferred:
                    shapes[n] = inferred[n]
        args = {}
        for n in names:
            if n not in shapes:
                raise MXNetError(
                    f"simple_bind missing shape for {n} (not inferable "
                    f"from the given shapes)")
            args[n] = nd_zeros(shapes[n], arg_ctx[n])
        aux = {}
        for n in aux_names:
            if n not in shapes:
                raise MXNetError(
                    f"simple_bind missing shape for aux state {n}")
            aux[n] = nd_zeros(shapes[n], arg_ctx.get(n, ctx))
            if n.endswith(('moving_var', 'running_var')):
                aux[n][:] = 1.0   # variance aux starts at one
        grads = {n: nd_zeros(shapes[n], arg_ctx[n]) for n in names} \
            if grad_req != 'null' else {}
        return Executor(self, args, grads, grad_req, ctx,
                        group2ctx=group2ctx, aux_states=aux)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req='write',
             aux_states=None, **kwargs):
        """Ref: symbol.py:1809 bind."""
        names = self.list_arguments()
        if isinstance(args, (list, tuple)):
            args = dict(zip(names, args))
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(names, args_grad))
        return Executor(self, args or {}, args_grad or {}, grad_req, ctx)

    # ---- serialization ----------------------------------------------------
    def tojson(self):
        nodes = []
        index = {}  # node uid -> node idx (indexed views share the uid)
        names = {}  # serialized name -> uid (duplicate-name guard)

        # postorder by uid: every node's inputs are indexed before it
        for s in _iter_nodes(self, 'post', key=lambda n: n._uid):
            in_refs = [(index[i._uid], i.out_index) for i in s.inputs]
            if s._name in names and names[s._name] != s._uid:
                raise MXNetError(
                    f"duplicate node name '{s._name}' in graph; names must "
                    "be unique to serialize")
            names[s._name] = s._uid
            index[s._uid] = len(nodes)
            nodes.append({'op': s.op or 'null', 'name': s._name,
                          'attrs': {k: str(v) for k, v in s.attrs.items()},
                          'inputs': [[i, oi, 0] for i, oi in in_refs]})

        return json.dumps({'nodes': nodes,
                           'heads': [[index[self._uid], self.out_index, 0]],
                           'mxnet_tpu_version': 2}, indent=2)

    def save(self, fname):
        from .serialization import atomic_write_file
        atomic_write_file(fname, self.tojson().encode('utf-8'))

    def __repr__(self):
        return f"<Symbol {self._name}>"


class _SymbolList(list):
    def __getitem__(self, key):
        if isinstance(key, str):
            for s in self:
                if s.name == key or s.name + '_output' == key:
                    return s
            raise MXNetError(f"no internal symbol {key}")
        return super().__getitem__(key)


def _eval_node(s, bindings, cache, device_map=None, hook=None):
    # cache by node uid: indexed output views of one multi-output node
    # share the uid, so the op runs once; distinct nodes never collide
    # even under duplicate user-assigned names
    base_key = s._uid
    if base_key in cache:
        out = cache[base_key]
    elif s.op is None:
        if s._name not in bindings:
            raise MXNetError(f"unbound variable {s._name}")
        out = bindings[s._name]
        if hook is not None:
            hook(s, out)
        cache[base_key] = out
    else:
        in_vals = [_eval_node(i, bindings, cache, device_map, hook)
                   for i in s.inputs]
        opdef = get_op(s.op)
        clean_attrs = {k: v for k, v in s.attrs.items()
                       if not k.startswith('__')}
        # manual model parallelism (group2ctxs): every node executes on
        # ITS device — the mapped group's, or the executor default for
        # unannotated nodes — so inputs arriving from other groups are
        # transferred first (the reference's cross_device_copy between
        # symbol groups). Without this, eager jax raises on ops whose
        # arguments sit committed on different devices.
        if device_map:
            import jax as _jax
            grp = s.attrs.get('__ctx_group__')
            target = device_map.get(grp) or device_map.get(None)
            if target is not None:
                in_vals = [_jax.device_put(v, target) if hasattr(v, 'devices')
                           else v for v in in_vals]
        out = opdef.fn(*in_vals, **clean_attrs)
        if hook is not None:
            hook(s, out)
        cache[base_key] = out
    if isinstance(out, tuple):
        return out[s.out_index]
    return out


def _op_arity(opname, attrs):
    """Static output count of an op node (multi-output ops declare it in
    the registry; -1 means attr-dependent)."""
    opdef = get_op(opname)
    n = opdef.num_outputs
    if n != -1:
        return n
    if opname in ('split', 'SliceChannel', 'slice_channel'):
        return int(attrs.get('num_outputs', 1))
    if opname == 'topk':
        return 2 if attrs.get('ret_typ') == 'both' else 1
    if opname == 'rnn':
        return 3 if attrs.get('mode', 'lstm') == 'lstm' else 2
    return 1


# ---------------------------------------------------------------------------
# Auto-created parameters (ref: nnvm registers hidden weight/bias inputs
# per layer op; symbol users write sym.FullyConnected(x, num_hidden=N)
# and fcN_weight / fcN_bias appear as graph inputs, shapes inferred at
# bind). Table: op -> [(suffix, shape_rule(data_shape, attrs), skip_if)].
# ---------------------------------------------------------------------------

def _truthy(v):
    return v in (True, 1, '1', 'true', 'True')


def _prod(t):
    out = 1
    for s in t:
        out *= int(s)
    return out


def _t2(v):
    return (int(v), int(v)) if isinstance(v, int) else tuple(int(x) for x in v)


_AUTO_PARAMS = {
    'fully_connected': [
        ('weight', lambda d, a: (int(a['num_hidden']),
                                 _prod(d[1:])
                                 if _truthy(a.get('flatten', True))
                                 else int(d[-1])), None),
        ('bias', lambda d, a: (int(a['num_hidden']),),
         lambda a: _truthy(a.get('no_bias', False))),
    ],
    'convolution': [
        ('weight', lambda d, a: (int(a['num_filter']), int(d[1]))
         + _t2(a['kernel']), None),
        ('bias', lambda d, a: (int(a['num_filter']),),
         lambda a: _truthy(a.get('no_bias', False))),
    ],
    'deconvolution': [
        # mxnet layout: (in_channels, num_filter, kh, kw)
        ('weight', lambda d, a: (int(d[1]), int(a['num_filter']))
         + _t2(a['kernel']), None),
        ('bias', lambda d, a: (int(a['num_filter']),),
         lambda a: _truthy(a.get('no_bias', True))),
    ],
    # suffixes starting '!' mark AUXILIARY states (no grad, no optimizer
    # update — the reference's mutable inputs)
    'batch_norm': [
        ('gamma', lambda d, a: (int(d[1]),), None),
        ('beta', lambda d, a: (int(d[1]),), None),
        ('!moving_mean', lambda d, a: (int(d[1]),), None),
        ('!moving_var', lambda d, a: (int(d[1]),), None),
    ],
    'layer_norm': [
        ('gamma', lambda d, a: (int(d[int(a.get('axis', -1))]),), None),
        ('beta', lambda d, a: (int(d[int(a.get('axis', -1))]),), None),
    ],
    'instance_norm': [
        ('gamma', lambda d, a: (int(d[1]),), None),
        ('beta', lambda d, a: (int(d[1]),), None),
    ],
    'embedding': [
        ('weight', lambda d, a: (int(a['input_dim']),
                                 int(a['output_dim'])), None),
    ],
}


def infer_shapes_partial(root, known):
    """Forward shape propagation over the DAG: {var name: shape} for
    every variable resolvable from `known` (typically just the data
    shapes) — auto-created params resolve through their shape rules,
    op outputs through jax.eval_shape (abstract evaluation IS the
    shape-inference pass; ref: nnvm InferShape)."""
    import jax

    shape_of = {}    # uid -> tuple (single) | list[tuple] (multi-output)

    def shape_for(node):
        raw = shape_of.get(node._uid)
        if raw is None:
            return None
        return raw[node.out_index] if isinstance(raw, list) else raw

    result = {}
    for node in _iter_nodes(root, 'post', key=lambda n: n._uid):
        if node.op is None:
            shp = known.get(node._name) or node.attrs.get('__shape__')
            if shp is not None:
                shape_of[node._uid] = tuple(shp)
                result[node._name] = tuple(shp)
            continue
        dshape = shape_for(node.inputs[0]) if node.inputs else None
        for v in node.inputs[1:]:
            if v.op is not None or v._uid in shape_of or dshape is None:
                continue
            rule = getattr(v, '_shape_rule', None)
            if rule is None:
                # round-tripped graph: the live rule is gone but the
                # serialized marker names it
                suffix = v.attrs.get('__auto_param__')
                if suffix is not None:
                    for sfx, r, _skip in _AUTO_PARAMS.get(node.op, ()):
                        if sfx == suffix:
                            rule = r
                            break
            if rule is None:
                continue
            try:
                shp = tuple(rule(dshape, node.attrs))
            except (KeyError, TypeError, ValueError, IndexError):
                continue
            shape_of[v._uid] = shp
            result[v._name] = shp
        in_shapes = [shape_for(i) for i in node.inputs]
        if any(s is None for s in in_shapes):
            continue
        opdef = get_op(node.op)
        clean = {k: v for k, v in node.attrs.items()
                 if not k.startswith('__')}
        out = None
        for probe_dtype in (jnp.float32, jnp.int32):
            try:
                out = jax.eval_shape(
                    lambda *xs: opdef.fn(*xs, **clean),
                    *[jax.ShapeDtypeStruct(s_, probe_dtype)
                      for s_ in in_shapes])
                break
            except Exception:
                continue
        if out is None:
            continue
        if isinstance(out, (list, tuple)):
            shape_of[node._uid] = [tuple(o.shape) for o in out]
        else:
            shape_of[node._uid] = tuple(out.shape)
    return result


def _apply(opname, inputs, attrs, name=None):
    from .attribute import current_attrs
    attrs = current_attrs(attrs)
    specs = _AUTO_PARAMS.get(opname)
    resolved = None
    if specs is not None and len(inputs) == 1:
        # only the data input given: synthesize {node}_{suffix} param
        # variables carrying their shape rules for bind-time inference
        resolved = _resolve_name(opname, name)
        for suffix, rule, skip in specs:
            if skip is not None and skip(attrs):
                continue
            aux = suffix.startswith('!')
            clean_suffix = suffix[1:] if aux else suffix
            v = Symbol(None, (), None, f"{resolved}_{clean_suffix}",
                       pre_resolved=True)
            v._shape_rule = rule
            # the declarative markers SERIALIZE (attrs survive
            # tojson/fromjson), so a round-tripped graph re-binds its
            # auto-params: infer_shapes_partial falls back to looking
            # the rule up by (consumer op, suffix)
            v.attrs['__auto_param__'] = suffix
            if aux:
                v.attrs['__aux__'] = True
            inputs = list(inputs) + [v]
    n = _op_arity(opname, attrs)
    s = Symbol(opname, inputs, attrs, resolved or name, num_outputs=n,
               pre_resolved=resolved is not None)
    if n == 1:
        return s
    return tuple(s[i] for i in range(n))


def var(name, attr=None, shape=None, dtype=None, init=None, stype=None,
        lr_mult=None, wd_mult=None, **kwargs):
    """Ref: symbol.py var/Variable."""
    from .attribute import current_attrs
    s = Symbol(None, (), current_attrs(attr), name)
    if shape is not None:
        s.attrs['__shape__'] = shape
    return s


Variable = var


def zeros(shape, dtype='float32', **kwargs):
    return _apply('zeros', [], {'shape': shape, 'dtype': dtype})


def ones(shape, dtype='float32', **kwargs):
    return _apply('ones', [], {'shape': shape, 'dtype': dtype})


def load(fname):
    with open(fname) as f:
        data = json.load(f)
    return fromjson(json.dumps(data))


def fromjson(js):
    data = json.loads(js)
    nodes = data['nodes']
    built = []
    for node in nodes:
        inputs = []
        for ref in node['inputs']:
            src = built[ref[0]]
            oi = ref[1] if len(ref) > 1 else 0
            inputs.append(src[oi] if src.num_outputs > 1 else src)
        attrs = {}
        for k, v in node.get('attrs', {}).items():
            try:
                attrs[k] = eval(v, {'__builtins__': {}})  # literals only
            except Exception:
                attrs[k] = v
        if node['op'] == 'null':
            v = var(node['name'])
            v.attrs.update(attrs)   # __shape__/__auto_param__ markers
            built.append(v)
        else:
            n = _op_arity(node['op'], attrs)
            built.append(Symbol(node['op'], inputs, attrs, node['name'],
                                num_outputs=n))
    head = data['heads'][0]
    s = built[head[0]]
    oi = head[1] if len(head) > 1 else 0
    return s[oi] if s.num_outputs > 1 else s


class Executor:
    """Compiled executor (ref: include/mxnet/executor.h:53, python
    executor.py). forward/backward each run one jitted XLA call."""

    def __init__(self, symbol, args, args_grad, grad_req, ctx,
                 group2ctx=None, aux_states=None):
        self._symbol = symbol
        self.arg_dict = args
        self.grad_dict = args_grad
        # aux states (BN moving stats): bound into the graph like args
        # but carry no gradient and no optimizer update
        self.aux_dict = dict(aux_states or {})
        self._grad_req = grad_req
        self._ctx = ctx
        self._names = symbol.list_arguments()
        self.outputs = []
        self._jit_fwd = None
        self._vjp = None
        # group2ctx (manual model parallelism): resolve groups to jax
        # devices and run the DAG EAGERLY with per-node placement — each
        # op executes on the device its ctx_group names, and jax inserts
        # the cross-device copies (the reference\'s per-op engine dispatch
        # + cross_device_copy). Without groups, the whole DAG compiles to
        # one XLA program.
        self._group2ctx = group2ctx
        self._device_map = None
        if group2ctx:
            self._device_map = {g: c.jax_device()
                                for g, c in group2ctx.items()}
            # unannotated nodes run on the executor's own context
            from .context import cpu as _cpu
            self._device_map[None] = (ctx or _cpu()).jax_device()

        def f(bind):
            return _eval_node(symbol, bind, {}, self._device_map)

        self._f = f
        self._jit_fwd = f if self._device_map else jax.jit(f)
        self._monitor = None  # set by monitor.Monitor.install

    def set_monitor_callback(self, callback, monitor_all=False):
        """Reference API (executor.py set_monitor_callback): `callback`
        receives (name, value) for every node output on every forward —
        an always-active monitor without interval gating."""
        class _AlwaysOn:
            activated = True

            def __init__(self, cb, mall):
                self._cb = cb
                self.monitor_all = mall

            def _record(self, name, value):
                self._cb(name, value)

        self._monitor = None if callback is None else \
            _AlwaysOn(callback, monitor_all)

    def forward(self, is_train=False, **kwargs):
        _t0 = None
        if _telem['on']:
            import time as _time
            _t0 = _time.perf_counter()
        for k, v in kwargs.items():
            if isinstance(v, NDArray):
                self.arg_dict[k]._data = v._data
            else:
                self.arg_dict[k]._data = jnp.asarray(v)
        bind = {n: self.arg_dict[n]._data for n in self._names}
        for n, a in self.aux_dict.items():
            bind[n] = a._data
        mon = getattr(self, '_monitor', None)
        if mon is not None and mon.activated:
            # monitored forward: eager per-node evaluation feeding the
            # monitor's stat queue (ref: monitor.py — the engine callback
            # path; bulking is likewise disabled there)
            def _rec(node, value):
                if node.op is None and not getattr(mon, 'monitor_all',
                                                   False):
                    return  # inputs/weights only under monitor_all
                vals = value if isinstance(value, tuple) else (value,)
                for vi, v in enumerate(vals):
                    nm = node._name + (f'_out{vi}' if len(vals) > 1 else
                                       '_output')
                    mon._record(nm, v)
            out = _eval_node(self._symbol, bind, {}, self._device_map,
                             _rec)
            if is_train and self._grad_req != 'null':
                _, self._vjp = jax.vjp(self._f, bind)
            else:
                self._vjp = None
        elif is_train and self._grad_req != 'null':
            out, self._vjp = jax.vjp(self._f, bind)
        else:
            out = self._jit_fwd(bind)
            self._vjp = None
        self.outputs = [_wrap(out)]
        if _t0 is not None:
            from . import telemetry as _telemetry
            _telemetry.inc('mxnet_tpu_executor_forward_total')
            _telemetry.observe('mxnet_tpu_executor_forward_seconds',
                               _time.perf_counter() - _t0)
        return self.outputs

    def backward(self, out_grads=None):
        if self._vjp is None:
            raise MXNetError("call forward(is_train=True) before backward")
        if out_grads is None:
            ct = jnp.ones_like(self.outputs[0]._data)
        elif isinstance(out_grads, NDArray):
            ct = out_grads._data
        elif isinstance(out_grads, (list, tuple)):
            ct = out_grads[0]._data
        else:
            ct = jnp.asarray(out_grads)
        grads = self._vjp(ct)[0]
        for n, g in grads.items():
            if n in self.grad_dict and self.grad_dict[n] is not None:
                if self._grad_req == 'add':
                    self.grad_dict[n]._data = self.grad_dict[n]._data + g
                else:
                    self.grad_dict[n]._data = g

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        new_args = {}
        for n in self._names:
            shape = tuple(kwargs.get(n, self.arg_dict[n].shape))
            if shape == tuple(self.arg_dict[n].shape):
                # unchanged args (weights) share storage with this
                # executor, matching the reference's memory-sharing
                # reshape — a reshaped executor computes the same
                # function at the new batch size
                new_args[n] = self.arg_dict[n]
            else:
                new_args[n] = nd_zeros(shape, self._ctx)
        grads = {n: nd_zeros(new_args[n].shape, self._ctx)
                 for n in self._names} if self._grad_req != 'null' else {}
        # aux states (BN moving_mean/moving_var) are batch-independent:
        # carry the SAME bindings over, not fresh zeros — dropping them
        # silently broke inference-mode BN after a reshape
        return Executor(self._symbol, new_args, grads, self._grad_req,
                        self._ctx, group2ctx=self._group2ctx,
                        aux_states=self.aux_dict)

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, arr in arg_params.items():
            if name in self.arg_dict:
                self.arg_dict[name]._data = arr._data
            elif not allow_extra_params:
                raise MXNetError(f"extra param {name}")


class _OpMaker:
    """Populate sym.<op> wrappers mirroring nd.<op>."""

    @staticmethod
    def populate(namespace):
        def make(opname):
            def fn(*args, name=None, **kwargs):
                sym_inputs = [a for a in args if isinstance(a, Symbol)]
                attrs = {k: v for k, v in kwargs.items()
                         if not isinstance(v, Symbol)}
                sym_inputs += [v for v in kwargs.values()
                               if isinstance(v, Symbol)]
                return _apply(opname, sym_inputs, attrs, name)
            fn.__name__ = opname
            return fn

        for opname in _OP_REGISTRY:
            if opname not in namespace:
                namespace[opname] = make(opname)


_OpMaker.populate(globals())

# CamelCase legacy aliases (ref: symbol API: FullyConnected, Convolution...)
_CAMEL = {
    'FullyConnected': 'fully_connected', 'Convolution': 'convolution',
    'Deconvolution': 'deconvolution', 'Pooling': 'pooling',
    'Activation': 'activation', 'BatchNorm': 'batch_norm',
    'LayerNorm': 'layer_norm', 'Dropout': 'dropout', 'Flatten': 'flatten',
    'SoftmaxOutput': 'softmax_output', 'Embedding': 'embedding',
    'Concat': 'concat', 'LeakyReLU': 'leaky_relu', 'RNN': 'rnn',
    'SequenceMask': 'sequence_mask', 'SequenceLast': 'sequence_last',
    'SequenceReverse': 'sequence_reverse', 'SliceChannel': 'split',
    'UpSampling': 'upsampling', 'LRN': 'lrn', 'Cast': 'cast',
    'SwapAxis': 'swapaxes', 'Reshape': 'reshape',
}
for camel, snake in _CAMEL.items():
    if snake in globals():
        globals()[camel] = globals()[snake]
