"""Legacy data-parallel executor manager (ref:
python/mxnet/executor_manager.py DataParallelExecutorManager — the
pre-Module training driver used by FeedForward/model.py).

TPU-native: contexts are logical devices; each holds an executor bound
to its batch slice, exactly the Module bind path. Kept thin — new code
should use Module or ShardedTrainStep — but the API (params/copy_to,
load_data_batch, forward/backward/update_metric) works."""
from __future__ import annotations

import logging

import numpy as onp

from .context import cpu
from .ndarray.ndarray import NDArray, array


def _split_input_slice(batch_size, work_load_list):
    """Batch slices proportional to work loads (ref:
    executor_manager.py:_split_input_slice)."""
    total = sum(work_load_list)
    slices = []
    start = 0
    for i, w in enumerate(work_load_list):
        end = batch_size if i == len(work_load_list) - 1 else \
            start + int(round(batch_size * w / total))
        slices.append(slice(start, end))
        start = end
    return slices


class DataParallelExecutorManager:
    """One executor per context over sliced batches (ref:
    executor_manager.py:DataParallelExecutorManager)."""

    def __init__(self, symbol, ctx, train_data=None, arg_names=None,
                 param_names=None, aux_names=None, work_load_list=None,
                 logger=logging, sym_gen=None, data_shapes=None,
                 label_shapes=None):
        self.symbol = symbol
        self.ctx = list(ctx) if isinstance(ctx, (list, tuple)) else [ctx]
        self.logger = logger
        work_load_list = work_load_list or [1] * len(self.ctx)
        assert len(work_load_list) == len(self.ctx)
        self._work_load_list = work_load_list

        # I/O names keep the PROVIDE order (data first, then labels):
        # load_data_batch zips batch tensors against this order, so it
        # must match the iterator's, not alphabetical order
        shapes = {}
        self._io_names = []

        def add(desc_list):
            for desc in desc_list:
                name, shape = (desc.name, desc.shape) \
                    if hasattr(desc, 'name') else desc[:2]
                if name not in shapes:
                    self._io_names.append(name)
                shapes[name] = tuple(shape)

        # all DATA names first (explicit + iterator), then all LABELS —
        # the zip target must be [batch.data..., batch.label...]
        add(data_shapes or [])
        if train_data is not None:
            add(list(getattr(train_data, 'provide_data', [])))
        add(label_shapes or [])
        if train_data is not None:
            add(list(getattr(train_data, 'provide_label', [])))
        batch = shapes[self._io_names[0]][0] if self._io_names else 0
        self.slices = _split_input_slice(batch, work_load_list)

        arg_names = arg_names or symbol.list_arguments()
        self.param_names = param_names or \
            [n for n in arg_names if n not in shapes]
        self.arg_names = arg_names
        self.aux_names = aux_names or []

        self.execs = []
        for i, c in enumerate(self.ctx):
            ctx_shapes = dict(shapes)
            n = self.slices[i]
            for io in self._io_names:
                full = shapes[io]
                ctx_shapes[io] = (n.stop - n.start,) + full[1:]
            missing = [a for a in arg_names if a not in ctx_shapes]
            if missing:
                from .module import _infer_missing
                ctx_shapes.update(_infer_missing(symbol, ctx_shapes))
            self.execs.append(symbol.simple_bind(c, grad_req='write',
                                                 **ctx_shapes))

    @property
    def param_arrays(self):
        return [[e.arg_dict[n] for e in self.execs]
                for n in self.param_names]

    @property
    def grad_arrays(self):
        return [[e.grad_dict[n] for e in self.execs]
                for n in self.param_names]

    def set_params(self, arg_params, aux_params=None):
        for e in self.execs:
            e.copy_params_from(arg_params, aux_params,
                               allow_extra_params=True)

    def copy_to(self, arg_params, aux_params=None):
        """Copy current parameter VALUES out (ref: executor_manager.py
        copy_to — a snapshot, not an alias of the live weights)."""
        for name in self.param_names:
            src = self.execs[0].arg_dict[name]
            if name in arg_params:
                arg_params[name]._data = src._data
            else:
                arg_params[name] = array(src.asnumpy())
        if aux_params is not None:
            for name in self.aux_names:
                if name in self.execs[0].aux_dict:
                    aux_params[name] = array(
                        self.execs[0].aux_dict[name].asnumpy())

    def load_data_batch(self, data_batch):
        datas = list(data_batch.data) + list(data_batch.label or [])
        for arr, name in zip(datas, self._io_names):
            a = arr.asnumpy() if isinstance(arr, NDArray) else \
                onp.asarray(arr)
            for e, sl in zip(self.execs, self.slices):
                e.arg_dict[name]._data = array(a[sl])._data

    def forward(self, is_train=False):
        for e in self.execs:
            e.forward(is_train=is_train)

    def backward(self):
        for e in self.execs:
            e.backward()

    def update_metric(self, metric, labels):
        outs = [e.outputs[0] for e in self.execs]
        for out, sl in zip(outs, self.slices):
            metric.update([l[sl] for l in labels], [out])
