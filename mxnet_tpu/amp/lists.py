"""AMP op lists (ref: python/mxnet/contrib/amp/lists/symbol_fp16.py).

TPU-native: the low-precision target is bfloat16 — the MXU's native input
dtype — rather than fp16. Three classes, mirroring the reference's
FP16_FUNCS / FP32_FUNCS / WIDEST_TYPE_CASTS:

- LP16_OPS: matmul-class ops where the FLOPs are; run in bf16 on the MXU.
- FP32_OPS: numerically sensitive ops pinned to fp32.
- WIDEST_OPS: multi-input elementwise ops cast to the widest input dtype.

Ops not listed run in whatever dtype their inputs already have.
"""

# MXU-bound ops: cast float inputs down to the target dtype.
LP16_OPS = [
    'fully_connected',
    'convolution',
    'deconvolution',
    'dot',
    'batch_dot',
    'rnn',
    'interleaved_matmul_selfatt_qk',
    'interleaved_matmul_selfatt_valatt',
    'interleaved_matmul_encdec_qk',
    'interleaved_matmul_encdec_valatt',
]

# Numerically sensitive: cast low-precision float inputs up to fp32.
FP32_OPS = [
    'softmax',
    'log_softmax',
    'softmax_cross_entropy',
    'softmax_output',
    'batch_norm',
    'layer_norm',
    'group_norm',
    'instance_norm',
    'l2_normalization',
    'lrn',
    'norm',
    'exp',
    'log',
    'log2',
    'log10',
    'log1p',
    'expm1',
    'power',
    'square',
    'sqrt',
    'rsqrt',
    'cbrt',
    'rcbrt',
    'reciprocal',
    'erfinv',
    'gamma',
    'gammaln',
    'sum',
    'mean',
    'prod',
    'nansum',
    'nanprod',
    'ctc_loss',
    'smooth_l1',
    'make_loss',
]

# Multi-input elementwise: unify on the widest floating dtype present.
WIDEST_OPS = [
    'broadcast_add',
    'broadcast_sub',
    'broadcast_mul',
    'broadcast_div',
    'broadcast_maximum',
    'broadcast_minimum',
    'broadcast_hypot',
    'broadcast_power',
    'elemwise_add',
    'elemwise_sub',
    'elemwise_mul',
    'elemwise_div',
    'add_n',
    'concat',
    'stack',
    'where',
    'maximum',
    'minimum',
]
