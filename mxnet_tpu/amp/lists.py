"""AMP op lists (ref: python/mxnet/contrib/amp/lists/symbol_fp16.py).

TPU-native: the low-precision target is bfloat16 — the MXU's native input
dtype — rather than fp16. Three classes, mirroring the reference's
FP16_FUNCS / FP32_FUNCS / WIDEST_TYPE_CASTS:

- LP16_OPS: matmul-class ops where the FLOPs are; run in bf16 on the MXU.
- FP32_OPS: numerically sensitive ops pinned to fp32.
- WIDEST_OPS: multi-input elementwise ops cast to the widest input dtype.

Ops not listed run in whatever dtype their inputs already have.
"""

# MXU-bound ops: cast float inputs down to the target dtype.
LP16_OPS = [
    'fully_connected',
    'convolution',
    'deconvolution',
    'dot',
    'batch_dot',
    'rnn',
    'interleaved_matmul_selfatt_qk',
    'interleaved_matmul_selfatt_valatt',
    'interleaved_matmul_encdec_qk',
    'interleaved_matmul_encdec_valatt',
]

# Numerically sensitive: cast low-precision float inputs up to fp32.
FP32_OPS = [
    'softmax',
    'log_softmax',
    'softmax_cross_entropy',
    'softmax_output',
    'batch_norm',
    'layer_norm',
    'group_norm',
    'instance_norm',
    'l2_normalization',
    'lrn',
    'norm',
    'exp',
    'log',
    'log2',
    'log10',
    'log1p',
    'expm1',
    'power',
    'erfinv',
    'gamma',
    'gammaln',
    'sum',
    'mean',
    'prod',
    'nansum',
    'nanprod',
    'ctc_loss',
    'smooth_l1',
    'make_loss',
]

# Multi-input elementwise: unify on the widest floating dtype present.
WIDEST_OPS = [
    'broadcast_add',
    'broadcast_sub',
    'broadcast_mul',
    'broadcast_div',
    'broadcast_maximum',
    'broadcast_minimum',
    'broadcast_hypot',
    'broadcast_power',
    'elemwise_add',
    'elemwise_sub',
    'elemwise_mul',
    'elemwise_div',
    'add_n',
    'concat',
    'stack',
    'where',
    'maximum',
    'minimum',
]


# ---------------------------------------------------------------------------
# Full-registry policy derivation (VERDICT r4 #10: with ~640 registered
# ops, most had no explicit policy — the default cast behavior was
# implicit). Every registered op now gets exactly one policy:
#
#   lp16        matmul-class, cast float inputs to the bf16 target
#   fp32        numerically sensitive, cast low-precision floats up
#   widest      multi-float-input elementwise, unify on widest input
#   nofloat     integer/bool/index/sampling semantics — casting is
#               meaningless or harmful
#   passthrough runs in whatever dtype the inputs already have (an
#               EXPLICIT decision now, not a fallthrough)
#
# The reference's per-dtype lists (ref: python/mxnet/contrib/amp/lists/
# symbol_fp16.py, ~600 lines) are hand-enumerated; here the long tail is
# derived by family rules with the hand lists as overrides, and
# tests/test_amp_policy.py asserts total coverage.
# ---------------------------------------------------------------------------

# Family matching works on NAME TOKENS (underscore-split segments), not
# bare substrings: 'exp' must catch `exp`/`broadcast_exp` but NOT
# `expand_dims`, and 'sign' must catch `sign` but NOT `softsign` or
# `copysign` (those are float math). A few families are genuine
# substrings ('conv' in deconvolution/convolution) and stay that way.
_LP16_PAT = ('conv', 'fully_connected', 'dot', 'gemm', 'matmul', 'einsum',
             'rnn', 'attention', 'krprod')
_FP32_TOKENS = frozenset([
    'softmax', 'norm', 'normalization', 'loss', 'exp', 'expm1', 'log',
    'log2', 'log10', 'log1p', 'gamma', 'gammaln', 'digamma', 'erf',
    'erfinv', 'entropy', 'pdf', 'moments', 'cumsum', 'cumprod', 'mean',
    'var', 'std', 'nanvar', 'nanstd', 'svd', 'det', 'slogdet',
    'inverse', 'potrf', 'potri', 'eig', 'eigh', 'eigvals', 'eigvalsh',
    'trsm', 'trmm', 'syrk', 'syevd', 'gelqf', 'cholesky', 'pinv',
    'lstsq', 'solve', 'tensorinv', 'tensorsolve', 'regression', 'power',
    'softrelu', 'softplus', 'xent'])
_NOFLOAT_TOKENS = frozenset([
    'index', 'indices', 'one', 'hot', 'shape', 'size', 'nonzero',
    'topk', 'sort', 'argsort', 'equal', 'greater', 'less', 'lesser',
    'logical', 'bitwise', 'boolean', 'isnan', 'isinf', 'isfinite',
    'isneginf', 'isposinf', 'quantize', 'quantized', 'requantize',
    'dequantize', 'randint', 'bernoulli', 'multinomial', 'categorical',
    'zipfian', 'unique', 'nnz', 'getnnz', 'digitize', 'searchsorted',
    'bincount', 'invert', 'sign', 'argmax', 'argmin', 'argwhere'])
_WIDEST_PREF = ('broadcast_', 'elemwise_', '_npi_add', '_npi_subtract',
                '_npi_multiply', '_npi_true_divide', '_npi_mod',
                '_npi_maximum', '_npi_minimum', '_npi_fmax',
                '_npi_fmin', '_npi_hypot', '_npi_arctan2', '_npi_ldexp',
                '_npi_copysign', '_npi_lcm', '_npi_gcd')
_WIDEST_NAMES = frozenset(['add_n', 'concat', 'stack', 'where', 'maximum',
                           'minimum', 'hypot', 'vstack', 'hstack',
                           'dstack', 'column_stack'])


def derive_policy(name):
    """Family-rule policy for one op name; explicit lists win."""
    if name in LP16_OPS:
        return 'lp16'
    if name in FP32_OPS:
        return 'fp32'
    if name in WIDEST_OPS:
        return 'widest'
    base = name
    for pre in ('_npi_', '_npx_', '_np_', '_contrib_'):
        if base.startswith(pre):
            base = base[len(pre):]
            break
    low = base.lower()
    toks = set(low.split('_'))
    # order matters: update ops first (their states must never be cast
    # behind the optimizer's back), then integer semantics, then the
    # numerics-sensitive and matmul families
    if low.endswith('_update') or low in ('multi_lars', 'reset_arrays',
                                          'multi_sum_sq', 'multi_all_finite',
                                          'all_finite', 'amp_cast',
                                          'amp_multicast'):
        return 'passthrough'
    if toks & _NOFLOAT_TOKENS or any(t.startswith('arg') for t in toks):
        return 'nofloat'
    if any(p in low for p in _LP16_PAT):
        return 'lp16'
    if toks & _FP32_TOKENS:
        return 'fp32'
    # accumulation-sensitive reductions only: cheap elementwise math
    # (sqrt, square, reciprocal, rsqrt, rcbrt, cbrt) runs in the dtype it
    # receives — pinning those to fp32 upcast bf16 activations
    # mid-network and dragged every downstream op back to fp32
    if low in ('sum', 'prod', 'nansum', 'nanprod', 'max', 'min', 'amax',
               'amin', 'average', 'trace'):
        return 'fp32'
    if name.startswith(_WIDEST_PREF) or low in _WIDEST_NAMES:
        return 'widest'
    return 'passthrough'


def policy_table():
    """{canonical op name: policy} covering every registered op."""
    from ..base import list_ops
    return {op: derive_policy(op) for op in list_ops()}


def derived_ops(policy):
    """All registered ops whose derived policy is `policy`."""
    return sorted(op for op, p in policy_table().items() if p == policy)
