"""Automatic mixed precision (ref: python/mxnet/contrib/amp/amp.py:82-215).

TPU-native: target dtype is bfloat16 (MXU-native; same exponent range as
fp32, so loss scaling is optional rather than required as with fp16). The
reference rewrites the op namespaces by wrapping each listed function with
casts; we do the same to the `mxnet_tpu.ndarray` module — the `F` handle
every Gluon layer dispatches through, eager and hybridized alike — so one
patch point covers both execution modes. XLA fuses the inserted casts into
the consuming matmul/conv, so autocast adds no extra HBM traffic.
"""
from __future__ import annotations

import logging
from contextlib import contextmanager

import numpy as onp

from ..base import MXNetError
from . import lists
from .loss_scaler import LossScaler

_amp_initialized = False
_target_dtype = 'bfloat16'
_originals = {}
_patch_epoch = 0  # bumped on init/_deinit; part of the hybridize cache key


def patch_epoch():
    return _patch_epoch

_LOW_DTYPES = ('float16', 'bfloat16')


def _is_low_float(dt):
    return str(dt) in _LOW_DTYPES


def _is_float(dt):
    s = str(dt)
    if s in ('bfloat16', 'float16', 'float32', 'float64'):
        return True
    try:
        return onp.issubdtype(onp.dtype(s), onp.floating)
    except TypeError:
        return False


def _cast_nd(x, dtype):
    from ..ndarray.ndarray import NDArray
    if isinstance(x, NDArray) and _is_float(x.dtype) and str(x.dtype) != dtype:
        return x.astype(dtype)
    return x


def _map_args(args, kwargs, fn):
    from ..ndarray.ndarray import NDArray
    new_args = [fn(a) if isinstance(a, NDArray) else
                ([fn(e) if isinstance(e, NDArray) else e for e in a]
                 if isinstance(a, (list, tuple)) else a)
                for a in args]
    new_kwargs = {k: (fn(v) if isinstance(v, NDArray) else v)
                  for k, v in kwargs.items()}
    return new_args, new_kwargs


def _wrap_lp16(orig, target):
    def wrapper(*args, **kwargs):
        a, k = _map_args(args, kwargs, lambda x: _cast_nd(x, target))
        return orig(*a, **k)
    wrapper.__name__ = getattr(orig, '__name__', 'amp_lp16')
    wrapper.__amp_original__ = orig
    return wrapper


def _wrap_fp32(orig):
    def wrapper(*args, **kwargs):
        a, k = _map_args(args, kwargs,
                         lambda x: _cast_nd(x, 'float32')
                         if _is_low_float(x.dtype) else x)
        return orig(*a, **k)
    wrapper.__name__ = getattr(orig, '__name__', 'amp_fp32')
    wrapper.__amp_original__ = orig
    return wrapper


def _wrap_widest(orig):
    def wrapper(*args, **kwargs):
        from ..ndarray.ndarray import NDArray
        leaves = [a for a in list(args) + list(kwargs.values())
                  if isinstance(a, NDArray)]
        for a in args:
            if isinstance(a, (list, tuple)):
                leaves += [e for e in a if isinstance(e, NDArray)]
        float_dts = {str(x.dtype) for x in leaves if _is_float(x.dtype)}
        if 'float32' in float_dts and (float_dts & set(_LOW_DTYPES)):
            a, k = _map_args(args, kwargs,
                             lambda x: _cast_nd(x, 'float32')
                             if _is_low_float(x.dtype) else x)
            return orig(*a, **k)
        return orig(*args, **kwargs)
    wrapper.__name__ = getattr(orig, '__name__', 'amp_widest')
    wrapper.__amp_original__ = orig
    return wrapper


def init(target_dtype='bfloat16'):
    """Turn on autocast (ref: amp.py:82 init). Patches the nd namespace in
    place; ops in LP16_OPS run in `target_dtype`, FP32_OPS in fp32."""
    global _amp_initialized, _target_dtype, _patch_epoch
    if target_dtype not in _LOW_DTYPES:
        raise MXNetError(f"AMP target_dtype must be one of {_LOW_DTYPES}, "
                         f"got {target_dtype!r}")
    if _amp_initialized:
        if target_dtype != _target_dtype:
            logging.warning(
                "amp.init(target_dtype=%r) ignored: AMP already initialized "
                "with target_dtype=%r", target_dtype, _target_dtype)
        return
    logging.info("Using AMP (target_dtype=%s)", target_dtype)
    _target_dtype = target_dtype
    _patch_epoch += 1

    from .. import ndarray as ndmod
    # full-registry policies (hand lists are overrides inside
    # derive_policy); patch every op that surfaces in the nd namespace
    table = lists.policy_table()
    for name, pol in sorted(table.items()):
        if not hasattr(ndmod, name):
            continue
        if pol == 'lp16':
            _originals[name] = getattr(ndmod, name)
            setattr(ndmod, name, _wrap_lp16(_originals[name], target_dtype))
        elif pol == 'fp32':
            _originals[name] = getattr(ndmod, name)
            setattr(ndmod, name, _wrap_fp32(_originals[name]))
        elif pol == 'widest':
            _originals[name] = getattr(ndmod, name)
            setattr(ndmod, name, _wrap_widest(_originals[name]))
        # 'passthrough' / 'nofloat': explicitly untouched
    _amp_initialized = True


def _deinit():
    """Undo init() — test helper; the reference has no un-init."""
    global _amp_initialized, _patch_epoch
    from .. import ndarray as ndmod
    for name, orig in _originals.items():
        setattr(ndmod, name, orig)
    _originals.clear()
    _amp_initialized = False
    _patch_epoch += 1


def init_trainer(optimizer_or_trainer, loss_scale=None):
    """Attach a dynamic loss scaler to a Trainer (ref: amp.py init_trainer).

    With bf16 the default scale is 1.0 (bf16 shares fp32's exponent range);
    fp16 gets the reference's 2**16 dynamic scaler.
    """
    from ..gluon.trainer import Trainer
    if not isinstance(optimizer_or_trainer, Trainer):
        raise MXNetError("init_trainer expects a gluon.Trainer")
    if loss_scale is None:
        loss_scale = 1.0 if _target_dtype == 'bfloat16' else 2.**16
    # bf16 shares fp32's exponent range: overflow checking is off unless the
    # user opts into a real scale
    scaler = LossScaler(init_scale=loss_scale,
                        dynamic=(_target_dtype != 'bfloat16'
                                 or loss_scale != 1.0))
    optimizer_or_trainer._amp_loss_scaler = scaler
    optimizer_or_trainer._amp_original_scale = optimizer_or_trainer._scale
    return optimizer_or_trainer


@contextmanager
def scale_loss(loss, optimizer_or_trainer):
    """Scale the loss and set the trainer to unscale gradients at step()
    (ref: amp.py scale_loss)."""
    scaler = getattr(optimizer_or_trainer, '_amp_loss_scaler', None)
    if scaler is None:
        raise MXNetError("call amp.init_trainer(trainer) before scale_loss")
    optimizer_or_trainer._scale = (optimizer_or_trainer._amp_original_scale /
                                   scaler.loss_scale)
    if scaler.loss_scale == 1.0:
        # bf16 default: no scaling needed, pass through unchanged (also
        # keeps the graph intact if used outside autograd.record)
        yield loss
    elif isinstance(loss, (list, tuple)):
        yield [l * scaler.loss_scale for l in loss]
    else:
        yield loss * scaler.loss_scale


def unscale(optimizer_or_trainer):
    """Divide accumulated gradients by the loss scale in place."""
    scaler = getattr(optimizer_or_trainer, '_amp_loss_scaler', None)
    if scaler is None:
        raise MXNetError("call amp.init_trainer(trainer) before unscale")
    for p in optimizer_or_trainer._params:
        if p.grad_req != 'null' and p._grad is not None:
            for g in p.list_grad():
                g[:] = g / scaler.loss_scale
    # grads are now unscaled: step() must not divide by the scale again
    optimizer_or_trainer._scale = optimizer_or_trainer._amp_original_scale


_NORM_PARAM_SUFFIXES = ('gamma', 'beta', 'running_mean', 'running_var',
                        'moving_mean', 'moving_var')


def convert_hybrid_block(block, target_dtype='bfloat16',
                         cast_optional_params=False):
    """Offline conversion of a trained block for low-precision inference
    (ref: amp.py convert_hybrid_block — which also returns a converted
    copy, leaving the input model untouched). Casts weights to
    `target_dtype` (norm-layer statistics stay fp32 unless
    cast_optional_params) and returns a wrapper that casts inputs down and
    outputs back to fp32 — the analog of the reference's inserted amp_cast
    symbols.
    """
    import copy
    from .. import gluon

    block = copy.deepcopy(block)
    for name, p in block.collect_params().items():
        if not cast_optional_params and name.endswith(_NORM_PARAM_SUFFIXES):
            continue
        if p._data is not None and _is_float(p.dtype):
            p.cast(target_dtype)

    class _AMPConverted(gluon.HybridBlock):
        def __init__(self, inner):
            super().__init__()
            self.inner = inner

        def hybrid_forward(self, F, *args):
            cast = [_cast_nd(a, target_dtype) for a in args]
            out = self.inner(*cast)
            if isinstance(out, (list, tuple)):
                return type(out)(_cast_nd(o, 'float32') for o in out)
            return _cast_nd(out, 'float32')

    return _AMPConverted(block)


def convert_model(*args, **kwargs):
    raise NotImplementedError(
        "convert_model operates on the legacy symbol API; use "
        "convert_hybrid_block (Module users: rebuild via gluon)")


def list_lp16_ops():
    return list(lists.LP16_OPS)


def list_fp32_ops():
    return list(lists.FP32_OPS)
