"""Dynamic loss scaler (ref: python/mxnet/contrib/amp/loss_scaler.py).

With bf16 (the TPU default) the exponent range matches fp32 and scaling is
a no-op; the scaler exists for fp16 parity and for users who opt into it.
"""
from __future__ import annotations


class LossScaler:
    """Doubles the scale every `scale_window` clean steps, halves on
    non-finite gradients, and tells the trainer to skip that update."""

    def __init__(self, init_scale=2.**16, scale_factor=2., scale_window=2000,
                 min_scale=1., dynamic=True):
        self.loss_scale = float(init_scale)
        self.dynamic = dynamic  # False for bf16: scaling is a formality
        self._scale_factor = float(scale_factor)
        self._scale_window = int(scale_window)
        self._min_scale = float(min_scale)
        self._unskipped = 0

    def has_overflow(self, params):
        """True if any gradient is non-finite (ref: loss_scaler.py
        has_overflow / multi_all_finite). Single device→host sync: the
        per-grad finiteness bits are reduced on device first."""
        import jax.numpy as jnp
        bits = []
        for p in params:
            if p.grad_req == 'null' or p._grad is None:
                continue
            g = p._grad
            grads = list(g) if (hasattr(g, '__iter__')
                                and not hasattr(g, '_data')) else [g]
            bits.extend(jnp.isfinite(garr._data).all() for garr in grads)
        if not bits:
            return False
        return not bool(jnp.stack(bits).all())

    def update_scale(self, overflow: bool):
        if overflow:
            self.loss_scale = max(self._min_scale,
                                  self.loss_scale / self._scale_factor)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0
