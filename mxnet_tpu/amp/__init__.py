"""AMP: bfloat16/float16 mixed precision (ref: python/mxnet/contrib/amp/)."""
from .amp import (init, init_trainer, scale_loss, unscale,  # noqa: F401
                  convert_hybrid_block, convert_model,
                  list_lp16_ops, list_fp32_ops)
from .loss_scaler import LossScaler  # noqa: F401
