"""Subgraph partitioning backends (ref: src/operator/subgraph/
subgraph_property.h:86,252 + partitioner registry in subgraph/
build_subgraph.cc).

The reference lets an accelerator backend pattern-match regions of the
operator graph and swap them for fused super-ops at `hybridize(backend=)`
time. The TPU-native analog operates on the traced jaxpr: a registered
`SubgraphBackend.rewrite(fn)` wraps the function CachedOp compiles, makes
its jaxpr, scans the equation list for known patterns, and re-evaluates
the program with matched segments replaced by fused kernels.

One production backend ships: `fuse_attention`, which recognises the
naive attention lowering — dot_general(QK^T) → elementwise scale/mask
chain → softmax (reduce_max/sub/exp/reduce_sum/div) → dot_general(AV) —
and substitutes the Pallas flash-attention kernel
(ops/pallas_attention.py), eliminating the materialised T×T probability
tensor from any model that wrote its attention by hand.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .base import MXNetError, Registry

__all__ = ['SubgraphBackend', 'register_backend', 'get_backend',
           'list_backends', 'FuseAttentionBackend']

_backends = Registry('subgraph_backend')


class SubgraphBackend:
    """A graph partitioner (ref: SubgraphProperty). Subclasses override
    `rewrite(fn) -> fn`, returning a function with identical semantics
    whose implementation may route matched subgraphs through fused
    kernels. `stats` accumulates match counts for tests/diagnostics."""

    name = 'base'

    def __init__(self):
        self.stats = {'matches': 0}

    def rewrite(self, fn):
        return fn


def register_backend(cls):
    _backends.register(cls, name=cls.name)
    return cls


def get_backend(name):
    try:
        backend = _backends.get(name)
    except Exception:
        raise MXNetError(
            f"subgraph backend {name!r} is not registered; "
            f"available: {list_backends()}") from None
    return backend() if isinstance(backend, type) else backend


def list_backends():
    return _backends.list()


# ---------------------------------------------------------------------------
# jaxpr scanning helpers
# ---------------------------------------------------------------------------

def _is_lit(v):
    return hasattr(v, 'val')


def _scalar_lit(v):
    """Float value of a scalar literal var, else None."""
    if _is_lit(v) and getattr(v.val, 'shape', ()) == ():
        try:
            return float(v.val)
        except Exception:
            return None
    return None


class _AttnMatch:
    __slots__ = ('dg1', 'dg2', 'skip', 'q', 'k', 'v', 'scale',
                 'add_mask', 'add_mask_scale', 'sel_mask', 'out_var',
                 'k_transposed')

    def __init__(self):
        self.skip = set()
        self.scale = 1.0
        self.add_mask = None
        self.add_mask_scale = 1.0
        self.sel_mask = None
        self.k_transposed = False


def _key_mask_shape(aval, scores_shape):
    """True when `aval` broadcasts over scores (B,H,Tq,Tk) purely along
    the key axis — i.e. reshapeable to (B, Tk)."""
    s = tuple(aval.shape)
    B, H, Tq, Tk = scores_shape
    if len(s) != 4 or s[3] != Tk:
        return False
    return s[0] in (1, B) and s[1] == 1 and s[2] == 1


def _find_attention(jaxpr):
    """All fusable naive-attention segments in `jaxpr`.

    Matches: dg2 = dot_general(softmax_out_or_convert, V) where the
    softmax chain is div(exp_t, bcast(reduce_sum(exp_t))) with
    exp_t = exp(sub(scores', bcast(stop_grad(max(reduce_max(scores'))))))
    and scores' reaches a QK^T dot_general through an elementwise chain of
    scalar mul/div, additive key-mask add, or select_n key-masking.
    """
    producer = {}
    consumers = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for o in eqn.outvars:
            producer[o] = (i, eqn)
        for v in eqn.invars:
            if not _is_lit(v):
                consumers.setdefault(v, []).append(i)
    for v in jaxpr.outvars:
        if not _is_lit(v):
            consumers.setdefault(v, []).append(-1)

    def prod(v):
        return producer.get(v, (None, None))

    def single_use(v):
        return len(consumers.get(v, ())) == 1

    matches = []
    for i2, dg2 in enumerate(jaxpr.eqns):
        if dg2.primitive.name != 'dot_general':
            continue
        m = _AttnMatch()
        m.dg2 = i2
        a_var, v_var = dg2.invars
        # (B,H,Tq,Tk) x (B,H,Tk,D): batch (0,1), contract 3 vs 2
        dn = dg2.params['dimension_numbers']
        if dn != (((3,), (2,)), ((0, 1), (0, 1))):
            continue
        # optional dtype cast between softmax and the AV matmul
        ci, ce = prod(a_var)
        if ce is not None and ce.primitive.name == 'convert_element_type' \
                and single_use(a_var):
            m.skip.add(ci)
            a_var = ce.invars[0]
        di, div_eqn = prod(a_var)
        if div_eqn is None or div_eqn.primitive.name != 'div' \
                or not single_use(a_var):
            continue
        exp_var, den_var = div_eqn.invars
        ei, exp_eqn = prod(exp_var)
        bi, bcast_eqn = prod(den_var)
        if exp_eqn is None or exp_eqn.primitive.name != 'exp' or \
                bcast_eqn is None or \
                bcast_eqn.primitive.name != 'broadcast_in_dim':
            continue
        si, sum_eqn = prod(bcast_eqn.invars[0])
        if sum_eqn is None or sum_eqn.primitive.name != 'reduce_sum' or \
                sum_eqn.invars[0] is not exp_var:
            continue
        sbi, sub_eqn = prod(exp_eqn.invars[0])
        if sub_eqn is None or sub_eqn.primitive.name != 'sub':
            continue
        scores_var, max_b_var = sub_eqn.invars
        # max-subtraction chain: any ordering of broadcast_in_dim /
        # stop_gradient / max(-inf, ·) around reduce_max(scores)
        mchain = set()
        cur = max_b_var
        ok = False
        for _ in range(5):
            pi, pe = prod(cur)
            if pe is None:
                break
            if pe.primitive.name in ('stop_gradient', 'broadcast_in_dim'):
                mchain.add(pi)
                cur = pe.invars[0]
                continue
            if pe.primitive.name == 'max':
                mchain.add(pi)
                cur = pe.invars[1] if _is_lit(pe.invars[0]) \
                    else pe.invars[0]
                continue
            if pe.primitive.name == 'reduce_max' and \
                    pe.invars[0] is scores_var:
                mchain.add(pi)
                ok = True
            break
        if not ok:
            continue
        scores_shape = tuple(scores_var.aval.shape)

        # walk the pre-softmax chain down to the QK^T dot_general
        chain = set()
        cur = scores_var
        dg1 = None
        for _ in range(8):
            pi, pe = prod(cur)
            if pe is None:
                break
            if pe.primitive.name == 'dot_general':
                dn1 = pe.params['dimension_numbers']
                # K either arrives (B,H,Tk,D) (contract 3v3) or
                # pre-transposed (B,H,D,Tk) (contract 3v2)
                if dn1 == (((3,), (3,)), ((0, 1), (0, 1))):
                    dg1 = (pi, pe, False)
                elif dn1 == (((3,), (2,)), ((0, 1), (0, 1))):
                    dg1 = (pi, pe, True)
                break
            if pe.primitive.name in ('div', 'mul'):
                x, y = pe.invars
                sl = _scalar_lit(y) if not _is_lit(x) else _scalar_lit(x)
                t = y if _is_lit(x) else x
                # no single-use requirement: chain vars are legitimately
                # consumed twice inside the segment (reduce_max + sub),
                # and the liveness pass resurrects anything consumed
                # outside it
                if sl is None:
                    break
                m.scale *= (1.0 / sl if pe.primitive.name == 'div' else sl)
                chain.add(pi)
                cur = t
                continue
            if pe.primitive.name == 'add' and m.add_mask is None:
                x, y = pe.invars
                other = None
                for cand, tens in ((x, y), (y, x)):
                    if _is_lit(cand):
                        continue
                    # the mask operand either IS key-mask-shaped
                    # ((B,1,1,Tk) — lax.add broadcasts it in place) or is
                    # an explicit broadcast_in_dim of such a tensor
                    if _key_mask_shape(cand.aval, scores_shape):
                        m.add_mask = cand
                        # scales matched SO FAR sit between the add and
                        # the softmax in the original program, so they
                        # apply to the mask too: softmax((s+mask)/c) has
                        # an effective additive bias of mask/c
                        m.add_mask_scale = m.scale
                        other = tens
                        break
                    ci2, ce2 = prod(cand)
                    if ce2 is not None and \
                            ce2.primitive.name == 'broadcast_in_dim' and \
                            _key_mask_shape(ce2.invars[0].aval,
                                            scores_shape):
                        m.add_mask = ce2.invars[0]
                        m.add_mask_scale = m.scale
                        chain.add(ci2)
                        other = tens
                        break
                if other is None:
                    break
                chain.add(pi)
                cur = other
                continue
            if pe.primitive.name == 'select_n' and m.sel_mask is None:
                pred, on_false, on_true = pe.invars
                pi2, pe2 = prod(pred)
                if pe2 is not None and \
                        pe2.primitive.name == 'broadcast_in_dim' and \
                        _key_mask_shape(pe2.invars[0].aval, scores_shape) \
                        and _is_lit(on_false) is False:
                    fi, fe = prod(on_false)
                    # on_false must be a broadcast large-negative constant
                    neg = None
                    if fe is not None and \
                            fe.primitive.name == 'broadcast_in_dim':
                        neg = _scalar_lit(fe.invars[0])
                        chain.add(fi)
                    if neg is not None and neg < -1e20:
                        m.sel_mask = pe2.invars[0]
                        chain.add(pi2)
                        chain.add(pi)
                        cur = on_true
                        continue
                break
            break
        if dg1 is None:
            continue
        i1, dg1_eqn, k_t = dg1
        m.dg1 = i1
        m.q, m.k = dg1_eqn.invars
        m.k_transposed = k_t
        m.v = v_var
        m.out_var = dg2.outvars[0]
        m.skip |= chain | mchain | {i1, di, ei, bi, si, sbi, i2}
        matches.append(m)
    return matches


def _fused_attention(q, k, v, scale, add_mask, add_mask_scale, sel_mask,
                     out_aval, k_transposed=False):
    from .ops.pallas_attention import flash_attention
    if k_transposed:                       # (B,H,D,Tk) -> (B,H,Tk,D)
        k = jnp.swapaxes(k, -1, -2)
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    # flash_attention applies 1/sqrt(D) internally; fold the matched
    # chain's scale (often the same 1/sqrt(D)) into q
    qs = q * jnp.asarray(scale * math.sqrt(D), q.dtype)
    km = None
    if add_mask is not None:
        km = add_mask.reshape(-1, Tk).astype(jnp.float32) * add_mask_scale
        if km.shape[0] == 1:
            km = jnp.broadcast_to(km, (B, Tk))
    elif sel_mask is not None:
        km = sel_mask.reshape(-1, Tk)
        if km.shape[0] == 1:
            km = jnp.broadcast_to(km, (B, Tk))
        km = km.astype(jnp.bool_)
    out = flash_attention(qs, k, v, key_mask=km)
    return out.astype(out_aval.dtype)


@register_backend
class FuseAttentionBackend(SubgraphBackend):
    """Swaps hand-written naive attention for the flash kernel."""

    name = 'fuse_attention'

    def rewrite(self, fn):
        backend = self

        def wrapped(*args):
            # ONE trace: make_jaxpr(return_shape=True) yields the jaxpr
            # and the output pytree together; both the match and no-match
            # paths then evaluate the jaxpr instead of retracing fn
            closed, out_shape = jax.make_jaxpr(
                fn, return_shape=True)(*args)
            out_tree = jax.tree_util.tree_structure(out_shape)
            matches = _find_attention(closed.jaxpr)
            backend.stats['matches'] += len(matches)
            flat, _ = jax.tree_util.tree_flatten(args)
            out_flat = _run_rewritten(closed, matches, flat)
            return jax.tree_util.tree_unflatten(out_tree, out_flat)
        return wrapped


def _run_rewritten(closed, matches, flat_args):
    """Evaluate `closed` with matched segments replaced by fused calls.

    A matched segment's equations are candidates for skipping, but any of
    them whose outputs are still consumed elsewhere (shared scores,
    reused masks, jaxpr outputs) is resurrected by a reverse liveness
    pass — correctness never depends on the matcher's single-consumer
    checks alone."""
    jaxpr = closed.jaxpr

    by_dg2 = {m.dg2: m for m in matches}
    skip = set()
    for m in matches:
        skip |= m.skip - {m.dg2}

    # reverse liveness: seed with jaxpr outputs, live-eqn inputs and the
    # fused calls' own inputs; resurrect skipped eqns whose outputs are
    # needed, propagating their inputs
    needed = {v for v in jaxpr.outvars if not _is_lit(v)}
    for i, eqn in enumerate(jaxpr.eqns):
        if i in skip:
            continue
        if i in by_dg2:
            m = by_dg2[i]
            for v in (m.q, m.k, m.v, m.add_mask, m.sel_mask):
                if v is not None and not _is_lit(v):
                    needed.add(v)
            continue
        for v in eqn.invars:
            if not _is_lit(v):
                needed.add(v)
    for i in sorted(skip, reverse=True):
        eqn = jaxpr.eqns[i]
        if any(o in needed for o in eqn.outvars):
            skip.discard(i)
            for v in eqn.invars:
                if not _is_lit(v):
                    needed.add(v)

    env = {}

    def read(v):
        return v.val if _is_lit(v) else env[v]

    def write(v, val):
        env[v] = val

    for cv, cval in zip(jaxpr.constvars, closed.consts):
        write(cv, cval)
    for iv, a in zip(jaxpr.invars, flat_args):
        write(iv, a)

    for i, eqn in enumerate(jaxpr.eqns):
        m = by_dg2.get(i)
        if m is not None:
            out = _fused_attention(
                read(m.q), read(m.k), read(m.v), m.scale,
                None if m.add_mask is None else read(m.add_mask),
                m.add_mask_scale,
                None if m.sel_mask is None else read(m.sel_mask),
                m.out_var.aval, m.k_transposed)
            write(m.out_var, out)
            continue
        if i in skip:
            continue
        vals = [read(v) for v in eqn.invars]
        ans = eqn.primitive.bind(*vals, **eqn.params)
        if eqn.primitive.multiple_results:
            for o, a in zip(eqn.outvars, ans):
                write(o, a)
        else:
            write(eqn.outvars[0], ans)
    return [read(v) for v in jaxpr.outvars]
