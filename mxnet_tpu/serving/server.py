"""Replica predict server: ``POST /predict`` on the PR 12 endpoint.

One serving replica = one ``InferenceEngine`` fronted by the same
bounded stdlib HTTP server the telemetry endpoint uses — ``/metrics``,
``/healthz`` and ``/flight`` keep working unchanged (a router ejects on
the SAME /healthz document a fleet operator reads), and three POST
routes are added:

- ``POST /predict``  {"inputs": [...]} — one sequence or a list of
  sequences; every sequence rides the continuous batcher. Admission
  control sheds with 503 **before** touching the device: replica
  draining, engine queue full, or live device memory above
  ``MXTPU_SERVE_MEMORY_LIMIT_MB`` (read from the PR 14 memory
  observability, the same numbers /healthz reports). An OOM inside the
  dispatch sheds that batch with 503 too — the replica never dies of a
  burst.
- ``POST /reload``   {"ns": ..., "step": ...} (or {"path": ...}) —
  swap in new weights: the fleet front stages a checkpoint over the
  replica transport (``dist.file_put`` + ``replica_commit`` into this
  replica's store), then points this route at it. Shapes are
  unchanged, so the swap needs NO recompile — the compiled programs
  read parameters per call.
- ``POST /drain``    — graceful exit: stop admitting, flush in-flight
  requests, leave the membership (peers see a departure, not a
  failure), then close the listener. SIGTERM does the same via
  ``install_sigterm``.

Weight quantization for the predict path rides the PR 11 codecs:
``quantize_weights(block, 'bf16')`` casts parameters (true 2x
residency); ``'int8'`` snaps each float parameter to the codec's
block-scaled int8 value grid in place (the values an int8-weights
deployment would serve, stored in float for this backend — honest
about residency, exact about accuracy effects).
"""
from __future__ import annotations

import json
import os
import threading
import time as _time

import numpy as onp

from ..base import MXNetError, telem_flags as _telem
from ..telemetry import flight as _flight, memory as _memory, \
    trace as _trace
from ..telemetry.server import TelemetryServer
from .batcher import RequestShed, RequestTooLarge, ServeError

__all__ = ['PredictServer', 'quantize_weights', 'memory_admission']


def quantize_weights(block, mode):
    """Quantize a block's weights for serving. Returns the block."""
    if not mode or mode == 'none':
        return block
    if mode in ('bf16', 'bfloat16'):
        block.cast('bfloat16')
        return block
    if mode == 'int8':
        import jax.numpy as jnp
        from ..ndarray.ndarray import NDArray
        from ..parallel import compression as _compression
        for p in block.collect_params().values():
            d = p.data()._data
            if jnp.issubdtype(d.dtype, jnp.floating):
                q = _compression.encode_decode(d, 'int8')
                p.set_data(NDArray(q))
        return block
    raise MXNetError(
        f"unknown MXTPU_SERVE_QUANTIZE mode {mode!r} "
        f"(use '', 'bf16' or 'int8')")


def memory_admission(limit_mb=None):
    """Admission predicate over the PR 14 memory observability: returns
    a shed reason when live device bytes exceed the limit, else None.
    ``limit_mb=None`` reads ``MXTPU_SERVE_MEMORY_LIMIT_MB``; 0 = off."""
    from .. import config as _config
    if limit_mb is None:
        limit_mb = float(_config.get('MXTPU_SERVE_MEMORY_LIMIT_MB'))
    if not limit_mb or limit_mb <= 0:
        return None

    def _admit():
        try:
            live = _memory.health_fields().get('live_bytes') or 0
        except Exception:
            return None
        if live > limit_mb * (1 << 20):
            return f'memory_pressure ({live >> 20}MiB > {limit_mb:g}MiB)'
        return None
    return _admit


class PredictServer(TelemetryServer):
    """One replica's front door. ``engine`` is an ``InferenceEngine``;
    ``block`` (optional) enables /reload; ``replica_root`` (optional)
    is this replica's ``ReplicaServer`` store so /reload can resolve a
    transport-pushed checkpoint by (ns, step)."""

    max_body_bytes = 4 << 20

    def __init__(self, engine, port=0, bind=None, membership=None,
                 block=None, replica_root=None, max_handlers=8,
                 start=True):
        self.engine = engine
        self.block = block
        self.replica_root = replica_root
        self.draining = threading.Event()
        self.reloaded_step = None
        super().__init__(port=port, bind=bind, membership=membership,
                         max_handlers=max_handlers, start=start)

    # -- routes ------------------------------------------------------------

    def _route(self, path, method='GET', body=b''):
        if method == 'POST':
            if body is None:
                return ('413 Payload Too Large', 'application/json',
                        b'{"error": "body too large"}')
            if path == '/predict':
                return self._predict(body)
            if path == '/reload':
                return self._reload(body)
            if path == '/drain':
                return self._drain_async()
            return ('404 Not Found', 'text/plain',
                    b'POST endpoints: /predict /reload /drain\n')
        return super()._route(path, method, body)

    @staticmethod
    def _json(status, doc):
        return (status, 'application/json',
                json.dumps(doc, default=str).encode())

    def _predict(self, body):
        t0 = _time.monotonic()
        if self.draining.is_set():
            return self._json('503 Service Unavailable',
                              {'error': 'draining'})
        try:
            doc = json.loads(body.decode('utf-8'))
            inputs = doc['inputs']
        except (ValueError, KeyError, UnicodeDecodeError) as e:
            return self._json('400 Bad Request',
                              {'error': f'bad request body: {e!r}'})
        single = bool(inputs) and not isinstance(inputs[0], (list, tuple))
        seqs = [inputs] if single else inputs
        try:
            with _trace.span('serving.predict', n=len(seqs)):
                handles = [self.engine.submit_async(s) for s in seqs]
                outs = [self.engine.result(h) for h in handles]
        except ServeError as e:
            status = {503: '503 Service Unavailable',
                      400: '400 Bad Request'}.get(e.status,
                                                  '500 Internal Server Error')
            return self._json(status, {'error': str(e)})
        except Exception as e:                        # noqa: BLE001
            return self._json('500 Internal Server Error',
                              {'error': repr(e)})
        payload = [onp.asarray(o, onp.float64).tolist() for o in outs]
        return self._json('200 OK', {
            'outputs': payload[0] if single else payload,
            'latency_ms': round((_time.monotonic() - t0) * 1e3, 3)})

    def _reload(self, body):
        if self.block is None:
            return self._json('400 Bad Request',
                              {'error': 'no block attached'})
        try:
            doc = json.loads(body.decode('utf-8')) if body else {}
        except ValueError as e:
            return self._json('400 Bad Request', {'error': repr(e)})
        path = doc.get('path')
        step = doc.get('step')
        if path is None:
            if self.replica_root is None or step is None:
                return self._json('400 Bad Request', {
                    'error': "need 'path' or ('ns' + 'step' with a "
                             "replica_root)"})
            from ..checkpoint import manifest as mf
            d = os.path.join(self.replica_root,
                             str(doc.get('ns', 'serving')),
                             mf.step_dir_name(int(step)))
            try:
                mf.validate_step_dir(d)
            except Exception as e:
                return self._json('409 Conflict',
                                  {'error': f'checkpoint invalid: {e}'})
            path = os.path.join(d, 'weights.params')
        try:
            # per-call parameter reads mean the swap needs no recompile:
            # same shapes, new values, next batch serves the new weights
            self.block.load_parameters(path)
        except Exception as e:                        # noqa: BLE001
            return self._json('500 Internal Server Error',
                              {'error': repr(e)})
        self.reloaded_step = step
        _flight.note('serving.reload', step=step, path=path)
        return self._json('200 OK', {'reloaded': True, 'step': step})

    # -- drain -------------------------------------------------------------

    def _drain_async(self):
        threading.Thread(target=self.drain, daemon=True,
                         name='mxtpu-serve-drain').start()
        return self._json('200 OK', {'draining': True})

    def drain(self):
        """Graceful exit: finish in-flight work, leave the membership,
        close the listener. Idempotent."""
        if self.draining.is_set():
            return
        self.draining.set()
        flushed = self.engine.drain()
        _flight.note('serving.drain', flushed=flushed,
                     rank=getattr(self.membership, 'rank', None))
        if _telem['on']:
            from .. import telemetry as _telemetry
            _telemetry.counter(
                'mxnet_tpu_serving_drained_replicas_total').inc(1)
        ms = self.membership
        if ms is not None:
            try:
                ms.leave()
            except Exception:
                pass
        self.stop()

    def install_sigterm(self):
        """SIGTERM -> graceful drain (the preemption path). Main thread
        only (signal module restriction)."""
        import signal as _signal

        def _term(_sig, _frm):
            threading.Thread(target=self.drain, daemon=True,
                             name='mxtpu-serve-drain').start()
        _signal.signal(_signal.SIGTERM, _term)
