"""Continuous-batching inference engine (ref: c_predict_api.h, the
reference's dedicated predict path — PAPER.md layer 8).

A serving replica sees single requests of ragged lengths; a TPU wants
large fixed-shape batches and NEVER a fresh shape (every novel shape is
an XLA compile — seconds of p99 on a path budgeted in milliseconds).
The engine reconciles the two:

- requests queue per **sequence bucket** (lengths round UP to a small
  fixed set, ``MXTPU_SERVE_BUCKETS``, padded with ``pad_value``);
- a worker forms a batch when a bucket reaches the largest batch bucket
  (**fill**) or when its oldest request has waited
  ``MXTPU_SERVE_BATCH_DEADLINE_MS`` (**deadline**) — the knob trades
  p50 latency against device efficiency;
- the formed batch pads its row count up to a **batch bucket**
  (``MXTPU_SERVE_BATCH_BUCKETS``), so the compiled-shape universe is
  exactly ``len(seq_buckets) x len(batch_buckets)`` — after the AOT
  warmup pass (``serving.warmup``) the PR 15 recompile detector stays
  silent no matter what lengths the traffic draws;
- dispatch goes through a CachedOp-backed pjit program
  (``BlockRunner``) under the OOM guard: allocator exhaustion sheds the
  batch with ``RequestShed`` (HTTP 503 upstream) instead of killing the
  replica.

Padding is exact, not approximate: batch-dim pad rows are dead weight
the slicer drops, and the per-request output is sliced back to the
request's true length when the model is per-position — tested
bit-identical against unpadded single-request calls.
"""
from __future__ import annotations

import collections
import threading
import time as _time

import numpy as onp

from ..base import MXNetError, telem_flags as _telem
from ..telemetry import trace as _trace, flight as _flight, \
    memory as _memory

__all__ = ['ServeError', 'RequestShed', 'RequestTooLarge',
           'parse_buckets', 'seq_bucket_for', 'batch_bucket_for',
           'BlockRunner', 'InferenceEngine']


class ServeError(MXNetError):
    """Base class for predict-path failures; ``status`` is the HTTP
    code the replica server maps it to."""
    status = 500


class RequestShed(ServeError):
    """Admission control refused the request (queue full, memory
    pressure, OOM mid-batch, draining) — the client should retry on
    another replica. Never fatal to the replica."""
    status = 503


class RequestTooLarge(ServeError):
    """The request exceeds the largest compiled sequence bucket — no
    amount of retrying helps; fix the client or widen the buckets."""
    status = 400


def parse_buckets(spec):
    """'32,64,128' -> (32, 64, 128) (sorted, deduplicated)."""
    if isinstance(spec, (list, tuple)):
        vals = [int(v) for v in spec]
    else:
        vals = [int(v) for v in str(spec).split(',') if v.strip()]
    if not vals or any(v <= 0 for v in vals):
        raise MXNetError(f"invalid bucket spec: {spec!r}")
    return tuple(sorted(set(vals)))


def seq_bucket_for(length, buckets):
    """Smallest bucket >= length, or None when the request is too long
    for every compiled shape."""
    for b in buckets:
        if length <= b:
            return b
    return None


def batch_bucket_for(n, buckets):
    """Smallest batch bucket >= n (callers never exceed max(buckets))."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


class BlockRunner:
    """pjit inference program over one gluon block: ``hybridize()``
    routes every call through CachedOp, which compiles ONE executable
    per (batch, seq) bucket and replays it from its cache (and, across
    processes, from the persistent XLA cache) afterwards."""

    def __init__(self, block, dtype='int32'):
        self.block = block
        self.dtype = dtype
        block.hybridize()

    def __call__(self, mat):
        from .. import nd
        out = self.block(nd.array(onp.asarray(mat, self.dtype)))
        if isinstance(out, (list, tuple)):
            out = out[0]
        return onp.asarray(out.asnumpy())


class _Request:
    __slots__ = ('data', 'length', 'enqueued', 'event', 'result', 'error')

    def __init__(self, data):
        self.data = data
        self.length = int(data.shape[0])
        self.enqueued = _time.monotonic()
        self.event = threading.Event()
        self.result = None
        self.error = None


class InferenceEngine:
    """The continuous batcher: ``submit()`` blocks the calling (HTTP
    handler) thread until its request's batch has been formed,
    dispatched and sliced; one worker thread owns batch formation so
    the deadline-vs-fill decision is made in exactly one place."""

    def __init__(self, runner, seq_buckets=None, batch_buckets=None,
                 deadline_ms=None, queue_limit=None, admission=None,
                 pad_value=0, dtype='int32', name='serve',
                 watchdog_seconds=None):
        from .. import config as _config
        self.runner = runner
        self.name = name
        # ONE wire dtype for every request: a JSON body decodes to
        # int64 while warmup fed int32 — without normalization the
        # dtype (part of the pjit cache key) would recompile every
        # bucket the first time live traffic hits it
        self.dtype = onp.dtype(dtype)
        self.seq_buckets = parse_buckets(
            seq_buckets if seq_buckets is not None
            else _config.get('MXTPU_SERVE_BUCKETS'))
        self.batch_buckets = parse_buckets(
            batch_buckets if batch_buckets is not None
            else _config.get('MXTPU_SERVE_BATCH_BUCKETS'))
        self.max_batch = self.batch_buckets[-1]
        self.deadline_s = (float(
            _config.get('MXTPU_SERVE_BATCH_DEADLINE_MS'))
            if deadline_ms is None else float(deadline_ms)) / 1000.0
        self.queue_limit = int(
            _config.get('MXTPU_SERVE_QUEUE_LIMIT')
            if queue_limit is None else queue_limit)
        self.admission = admission
        self.pad_value = pad_value
        self._cv = threading.Condition()
        self._pending = {s: collections.deque() for s in self.seq_buckets}
        self._n_pending = 0
        self._running = True
        self._latencies = collections.deque(maxlen=4096)
        self.requests = 0
        self.batches = 0
        self.shed = 0
        self._watchdog = None
        if watchdog_seconds is None:
            watchdog_seconds = _config.get('MXTPU_SERVE_WATCHDOG_SECONDS')
        if watchdog_seconds and float(watchdog_seconds) > 0:
            # classifies a wedged dispatch (device hang, compile storm):
            # the beat is per completed batch, so a stall report names
            # COMPILING vs EXECUTING via the PR 15 compile window
            from ..resilience.watchdog import StepWatchdog

            def _stuck(report):
                _flight.note('serving.stuck', engine=self.name)

            self._watchdog = StepWatchdog(
                deadline_seconds=float(watchdog_seconds),
                on_stall=_stuck)
            self._watchdog.start()
        self._worker = threading.Thread(
            target=self._loop, daemon=True,
            name=f'mxtpu-serve-batcher-{name}')
        self._worker.start()

    # -- client side -------------------------------------------------------

    def submit(self, seq, timeout=30.0):
        """One request in, its (sliced) output out. Raises
        ``RequestShed``/``RequestTooLarge`` per the admission rules."""
        return self.result(self.submit_async(seq), timeout)

    def submit_async(self, seq):
        """Enqueue one request and return its handle (``result()``
        collects) — a multi-sequence HTTP request enqueues all its
        sequences first so they share one batch-formation deadline."""
        data = onp.asarray(seq, self.dtype)
        if data.ndim != 1:
            raise MXNetError(
                f"predict request must be one 1-D sequence, got shape "
                f"{data.shape}")
        s = seq_bucket_for(data.shape[0], self.seq_buckets)
        if s is None:
            raise RequestTooLarge(
                f"request length {data.shape[0]} exceeds the largest "
                f"compiled bucket {self.seq_buckets[-1]}")
        if self.admission is not None:
            reason = self.admission()
            if reason:
                self._shed(1, reason)
                raise RequestShed(f"admission refused: {reason}")
        req = _Request(data)
        with self._cv:
            if not self._running:
                self._shed(1, 'draining')
                raise RequestShed("replica draining")
            if self._n_pending >= self.queue_limit:
                self._shed(1, 'queue_full')
                raise RequestShed(
                    f"queue full ({self.queue_limit} pending)")
            self._pending[s].append(req)
            self._n_pending += 1
            self.requests += 1
            if _telem['on']:
                self._gauge_depth()
            self._cv.notify()
        return req

    def result(self, req, timeout=30.0):
        if not req.event.wait(timeout):
            # the batch never came back (wedged dispatch): abandon the
            # slot — the worker will still fill the result, but nobody
            # is waiting. The watchdog classifies the underlying stall.
            raise RequestShed(f"request timed out after {timeout:.1f}s")
        if req.error is not None:
            raise req.error
        return req.result

    # -- warmup / drain ----------------------------------------------------

    def bucket_grid(self):
        """Every compiled shape the steady state can draw, largest
        first (the expensive compiles land before the cheap ones)."""
        return [(b, s) for s in reversed(self.seq_buckets)
                for b in reversed(self.batch_buckets)]

    def run_bucket(self, batch, seq):
        """Dispatch one dummy batch of an exact bucket shape straight
        through the pjit program (the AOT warmup path — no queue)."""
        mat = onp.full((batch, seq), self.pad_value, self.dtype)
        with _trace.span('serving.dispatch', engine=self.name,
                         batch=batch, seq=seq, warmup=True), \
                _memory.oom_guard('serving.dispatch'):
            self.runner(mat)

    def drain(self, timeout=None):
        """Stop admitting, finish every in-flight request, park the
        worker. Returns the number of requests flushed."""
        from .. import config as _config
        if timeout is None:
            timeout = float(_config.get('MXTPU_SERVE_DRAIN_SECONDS'))
        with self._cv:
            if not self._running:
                return 0
            flushed = self._n_pending
            self._running = False
            self._cv.notify_all()
        self._worker.join(timeout=timeout)
        if self._watchdog is not None:
            self._watchdog.stop()
        return flushed

    close = drain

    # -- stats -------------------------------------------------------------

    def stats(self):
        with self._cv:
            lat = sorted(self._latencies)
            depth = self._n_pending
            requests, batches, shed = self.requests, self.batches, self.shed

        def pct(p):
            return round(lat[min(len(lat) - 1,
                                 int(p / 100.0 * len(lat)))] * 1e3, 3) \
                if lat else None
        return {'requests': requests, 'batches': batches,
                'shed': shed, 'queue_depth': depth,
                'p50_ms': pct(50), 'p99_ms': pct(99),
                'seq_buckets': list(self.seq_buckets),
                'batch_buckets': list(self.batch_buckets),
                'deadline_ms': round(self.deadline_s * 1e3, 3)}

    # -- worker ------------------------------------------------------------

    def _gauge_depth(self):
        from .. import telemetry as _telemetry
        _telemetry.set_gauge('mxnet_tpu_serving_queue_depth',
                             self._n_pending, engine=self.name)

    def _shed(self, n, reason):
        with self._cv:              # re-entrant: some callers hold it
            self.shed += n
        _flight.note('serving.shed', engine=self.name, count=n,
                     reason=reason)
        if _telem['on']:
            from .. import telemetry as _telemetry
            _telemetry.counter('mxnet_tpu_serving_shed_total').inc(
                n, engine=self.name, reason=reason)

    def _pick_locked(self, now):
        """The bucket to dispatch now, or (None, wait_seconds)."""
        wait = None
        for s, dq in self._pending.items():
            if not dq:
                continue
            if len(dq) >= self.max_batch:
                return s, None                       # fill wins
            remaining = self.deadline_s - (now - dq[0].enqueued)
            if remaining <= 0 or not self._running:
                return s, None                       # deadline (or drain)
            wait = remaining if wait is None else min(wait, remaining)
        return None, wait

    def _loop(self):
        while True:
            with self._cv:
                while True:
                    s, wait = self._pick_locked(_time.monotonic())
                    if s is not None:
                        break
                    if not self._running and self._n_pending == 0:
                        return
                    self._cv.wait(timeout=wait if wait is not None
                                  else 0.2)
                reqs = []
                dq = self._pending[s]
                while dq and len(reqs) < self.max_batch:
                    reqs.append(dq.popleft())
                self._n_pending -= len(reqs)
                if _telem['on']:
                    self._gauge_depth()
            self._dispatch(s, reqs)

    def _dispatch(self, s, reqs):
        b = batch_bucket_for(len(reqs), self.batch_buckets)
        mat = onp.full((b, s), self.pad_value, self.dtype)
        for i, r in enumerate(reqs):
            mat[i, :r.length] = r.data
        try:
            with _trace.span('serving.dispatch', engine=self.name,
                             batch=b, seq=s, fill=len(reqs)), \
                    _memory.oom_guard('serving.dispatch'):
                out = onp.asarray(self.runner(mat))
        except BaseException as e:                  # noqa: BLE001
            if _memory.is_oom_error(e):
                # the replica survives allocator exhaustion: the dump
                # was written by the guard; the batch sheds with 503
                self._shed(len(reqs), 'oom')
                err = RequestShed(f"out of device memory: {e!r}")
            else:
                err = e if isinstance(e, Exception) else ServeError(repr(e))
            for r in reqs:
                r.error = err
                r.event.set()
            return
        now = _time.monotonic()
        per_position = out.ndim >= 2 and out.shape[1] == s
        for i, r in enumerate(reqs):
            r.result = out[i, :r.length] if per_position else out[i]
            r.event.set()
        with self._cv:
            for r in reqs:
                self._latencies.append(now - r.enqueued)
            self.batches += 1
        if self._watchdog is not None:
            self._watchdog.beat(self.batches)
        if _telem['on']:
            from .. import telemetry as _telemetry
            _telemetry.counter('mxnet_tpu_serving_requests_total').inc(
                len(reqs), engine=self.name)
            _telemetry.counter('mxnet_tpu_serving_batches_total').inc(
                1, engine=self.name)
            _telemetry.counter('mxnet_tpu_serving_bucket_hits_total').inc(
                1, engine=self.name, batch=b, seq=s)
            _telemetry.observe('mxnet_tpu_serving_batch_fill_ratio',
                               len(reqs) / float(b), engine=self.name)
            for r in reqs:
                _telemetry.observe('mxnet_tpu_serving_latency_seconds',
                                   now - r.enqueued, engine=self.name)
