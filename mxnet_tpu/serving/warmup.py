"""AOT warmup: pre-compile every serving bucket before the first
request arrives.

A replica that compiles lazily pays each bucket's XLA compile on the
first unlucky request — seconds of p99 at the worst possible time.
The warmup pass walks the engine's full ``(batch, seq)`` bucket grid
at startup and dispatches one dummy batch per shape:

- with ``MXTPU_COMPILE_CACHE_DIR`` set, the compiles go through the
  persistent XLA cache — the FIRST replica on a machine pays the cold
  compile, every later one (and every restart) replays it in
  milliseconds;
- each bucket's cold-start seconds are ledgered through the PR 15
  compile ledger (``serving:warmup_b{B}_s{S}`` sites) via
  ``compile.watching`` — a bucket served from cache records nothing,
  so the ledger is exactly the list of compiles this process paid for;
- after warmup the steady state replays compiled programs only: the
  recompile detector staying silent is asserted by
  ``tests/test_serving.py`` and the dryrun serving stage.
"""
from __future__ import annotations

import time as _time

from ..base import telem_flags as _telem
from ..telemetry import compile as _compile

__all__ = ['warmup']


def warmup(engine):
    """Pre-build every bucket shape; returns the per-bucket report::

        {'buckets': {'b4_s64': seconds, ...},
         'total_seconds': ..., 'compiles': <ledger entries written>,
         'cache': <persistent_cache_stats() delta-free snapshot>}
    """
    from ..telemetry import metrics as _metrics
    t0 = _time.perf_counter()
    before = len(_compile.ledger()) if _compile.enabled() else 0
    report = {}
    # the recompile detector counts per-site compiles — warmup compiles
    # the whole bucket grid at each site ON PURPOSE, so mute the
    # threshold for the pass. It restores right after: the very next
    # steady-state compile (a bucketing bug) warns immediately, because
    # the episode counter already sits above the threshold.
    prev = _metrics._recompile_threshold
    _metrics.set_recompile_threshold(1 << 30)
    try:
        for b, s in engine.bucket_grid():
            site = f'serving:warmup_b{b}_s{s}'
            tb = _time.perf_counter()
            with _compile.watching(site, sig_fn=lambda b=b, s=s:
                                   _compile.signature(args=[
                                       _compile.arg_sig('batch', (b, s),
                                                        str(engine.dtype))],
                                       flags={'engine': engine.name})):
                engine.run_bucket(b, s)
            report[f'b{b}_s{s}'] = round(_time.perf_counter() - tb, 4)
    finally:
        _metrics.set_recompile_threshold(prev)
    total = _time.perf_counter() - t0
    compiles = (len(_compile.ledger()) - before) if _compile.enabled() \
        else None
    out = {'buckets': report, 'total_seconds': round(total, 4),
           'compiles': compiles,
           'cache': _compile.persistent_cache_stats()}
    if _telem['on']:
        from .. import telemetry as _telemetry
        _telemetry.set_gauge('mxnet_tpu_serving_warmup_buckets',
                             len(report), engine=engine.name)
        _telemetry.set_gauge('mxnet_tpu_serving_warmup_seconds',
                             round(total, 4), engine=engine.name)
    return out
