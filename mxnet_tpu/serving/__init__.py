"""Production inference serving (ref: c_predict_api.h — PAPER.md
layer 8; ROADMAP item 1, the "millions of users" axis).

Four layers, each reusing a subsystem from PRs 8–15:

- ``batcher``  — continuous batching onto a fixed bucket grid of
  compiled shapes (zero steady-state recompiles);
- ``warmup``   — AOT pre-compilation of every bucket through the
  persistent XLA cache, ledgered per bucket;
- ``server``   — the replica's HTTP front: POST /predict + the PR 12
  /metrics//healthz, admission control + OOM shedding, hot weight
  reload, graceful drain;
- ``fleet``    — membership-discovered replicas behind a round-robin
  router with ejection/failover, and checkpoint weight-push over the
  replica transport.
"""
from .batcher import (BlockRunner, InferenceEngine, RequestShed,
                      RequestTooLarge, ServeError, batch_bucket_for,
                      parse_buckets, seq_bucket_for)
from .fleet import (NoReplicasError, Router, discover_replicas,
                    http_json, push_weights)
from .server import PredictServer, memory_admission, quantize_weights
from .warmup import warmup

__all__ = [
    'BlockRunner', 'InferenceEngine', 'RequestShed', 'RequestTooLarge',
    'ServeError', 'batch_bucket_for', 'parse_buckets', 'seq_bucket_for',
    'warmup', 'PredictServer', 'memory_admission', 'quantize_weights',
    'Router', 'NoReplicasError', 'discover_replicas', 'http_json',
    'push_weights',
]
