"""Fleet front: replica discovery, health-steered routing, weight push.

The "millions of users" axis is horizontal: N identical replica
processes behind a router. This module is the router half:

- **discovery** — replicas are the ranks alive in the PR 8 membership
  view (each serves on a base port + rank, the same scheme every other
  side channel here uses), or an explicit endpoint list;
- **routing** — round-robin with ejection: a replica that fails
  ``MXTPU_SERVE_EJECT_FAILURES`` consecutive predicts (connect refused,
  5xx, shed) is ejected for ``MXTPU_SERVE_READMIT_SECONDS`` and then
  probed back in via ``/healthz`` — the same health document the PR 12
  FleetMonitor builds, so a rank the monitor calls a straggler degrades
  its own /healthz and the router backs off without new machinery;
  a failed predict FAILS OVER to the next live replica inside one
  ``predict()`` call, so a draining replica costs a retry, never an
  error;
- **weight push** — a new checkpoint reaches replicas over the PR 9
  replica transport (``dist.file_put`` + ``replica_commit`` into each
  replica's hosted store, hash-verified and atomically published),
  then ``POST /reload`` swaps it in with zero recompiles.
"""
from __future__ import annotations

import http.client
import json
import threading
import time as _time

from ..base import MXNetError, telem_flags as _telem
from ..telemetry import flight as _flight

__all__ = ['Router', 'discover_replicas', 'http_json', 'push_weights',
           'NoReplicasError']


class NoReplicasError(MXNetError):
    """Every replica is ejected/unreachable — the fleet is down."""


def http_json(host, port, path, doc=None, timeout=10.0):
    """One JSON round trip: GET when ``doc`` is None, else POST.
    Returns (status_code, parsed_body_or_None)."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        if doc is None:
            conn.request('GET', path)
        else:
            body = json.dumps(doc).encode()
            conn.request('POST', path, body=body,
                         headers={'Content-Type': 'application/json',
                                  'Content-Length': str(len(body))})
        resp = conn.getresponse()
        raw = resp.read()
        try:
            parsed = json.loads(raw.decode('utf-8')) if raw else None
        except ValueError:
            parsed = None
        return resp.status, parsed
    finally:
        conn.close()


def discover_replicas(membership, serve_port_base, host='127.0.0.1'):
    """Alive ranks -> [(rank, host, port)] on base + rank, excluding the
    membership's OWN rank (a router that joined the view as an observer
    rank never routes to itself). The drill's replicas all live on one
    host; a real fleet swaps in per-rank hosts from its scheduler here."""
    view = membership.view() if membership is not None else None
    if not view:
        return []
    self_rank = getattr(membership, 'rank', None)
    return [(r, host, int(serve_port_base) + r) for r in view['alive']
            if r != self_rank]


class _Replica:
    __slots__ = ('rank', 'host', 'port', 'fails', 'ejected_until')

    def __init__(self, rank, host, port):
        self.rank = rank
        self.host = host
        self.port = port
        self.fails = 0
        self.ejected_until = 0.0


class Router:
    """Round-robin with ejection over a replica set. Thread-safe; one
    router instance fronts any number of client threads."""

    def __init__(self, endpoints=None, membership=None,
                 serve_port_base=None, eject_failures=None,
                 readmit_seconds=None, timeout=10.0):
        from .. import config as _config
        self.membership = membership
        self.serve_port_base = serve_port_base
        self.timeout = float(timeout)
        self.eject_failures = int(
            _config.get('MXTPU_SERVE_EJECT_FAILURES')
            if eject_failures is None else eject_failures)
        self.readmit_seconds = float(
            _config.get('MXTPU_SERVE_READMIT_SECONDS')
            if readmit_seconds is None else readmit_seconds)
        self._lock = threading.Lock()
        self._replicas = {}
        self._rr = 0
        self.requests = 0
        self.failovers = 0
        if endpoints:
            for i, (host, port) in enumerate(endpoints):
                self._replicas[i] = _Replica(i, host, int(port))
        self.refresh()

    # -- membership --------------------------------------------------------

    def refresh(self):
        """Re-derive the replica set from the membership view: joined
        ranks appear, departed/lost ranks drop (a drained replica left
        the membership — the router stops routing to it without waiting
        for its ejection threshold)."""
        if self.membership is None or self.serve_port_base is None:
            return
        found = discover_replicas(self.membership, self.serve_port_base)
        with self._lock:
            alive = set()
            for rank, host, port in found:
                alive.add(rank)
                if rank not in self._replicas:
                    self._replicas[rank] = _Replica(rank, host, port)
            for rank in list(self._replicas):
                if rank not in alive:
                    del self._replicas[rank]

    # -- routing -----------------------------------------------------------

    def _candidates(self):
        """Live-first candidate order starting at the round-robin
        cursor; ejected replicas past their readmit time re-enter at
        the back (the next predict is their probe)."""
        now = _time.monotonic()
        with self._lock:
            reps = list(self._replicas.values())
            self._rr += 1
            start = self._rr
        if not reps:
            return []
        reps = reps[start % len(reps):] + reps[:start % len(reps)]
        live = [r for r in reps if r.ejected_until <= now]
        stale = [r for r in reps if r.ejected_until > now
                 and now + self.readmit_seconds >= r.ejected_until]
        return live + stale

    def _mark(self, rep, ok, reason=''):
        with self._lock:
            if ok:
                rep.fails = 0
                rep.ejected_until = 0.0
                return
            rep.fails += 1
            if rep.fails < self.eject_failures:
                return
            rep.ejected_until = _time.monotonic() + self.readmit_seconds
        _flight.note('serving.eject', rank=rep.rank, port=rep.port,
                     reason=reason)
        if _telem['on']:
            from .. import telemetry as _telemetry
            _telemetry.counter('mxnet_tpu_serving_ejections_total').inc(
                1, rank=rep.rank)

    def eject(self, rank, reason='external'):
        """Explicit ejection (a FleetMonitor detector naming a rank,
        an operator pulling a replica)."""
        with self._lock:
            rep = self._replicas.get(rank)
            if rep is None:
                return
            rep.fails = self.eject_failures
            rep.ejected_until = _time.monotonic() + self.readmit_seconds
        _flight.note('serving.eject', rank=rank, reason=reason)

    def ejected(self):
        now = _time.monotonic()
        with self._lock:
            return sorted(r.rank for r in self._replicas.values()
                          if r.ejected_until > now)

    def predict(self, inputs, timeout=None):
        """Route one predict, failing over across replicas: a shed
        (503), connect failure or 5xx tries the next candidate; only a
        definitive client error (4xx) or total exhaustion surfaces."""
        self.refresh()
        timeout = self.timeout if timeout is None else timeout
        errors = []
        for rep in self._candidates():
            try:
                status, doc = http_json(rep.host, rep.port, '/predict',
                                        {'inputs': inputs},
                                        timeout=timeout)
            except OSError as e:
                self._mark(rep, False, f'connect: {e!r}')
                errors.append(f'rank{rep.rank}: {e!r}')
                self.failovers += 1
                continue
            if status == 200:
                self._mark(rep, True)
                self.requests += 1
                return doc['outputs']
            if 400 <= status < 500:
                # our fault, not the replica's — no ejection credit
                raise MXNetError(
                    f"predict rejected ({status}): {doc}")
            self._mark(rep, False, f'status {status}')
            errors.append(f'rank{rep.rank}: status {status} {doc}')
            self.failovers += 1
        raise NoReplicasError(
            "no replica could serve the request: " + '; '.join(errors)
            if errors else "no replicas registered")


def push_weights(block, step, replicas, ns='serving', timeout=10.0):
    """Ship a new checkpoint to every replica and hot-swap it in.

    ``replicas``: [{'host', 'replica_port', 'serve_port'}]. The payload
    travels the PR 9 replica transport — staged ``file_put`` (hash
    verified on receipt), manifest-validated ``replica_commit`` (atomic
    publish) — and then ``POST /reload`` points the replica's engine at
    the committed step. Returns per-replica results."""
    import os
    import tempfile

    from ..checkpoint import manifest as mf
    from ..parallel import dist as _dist
    fd, tmp = tempfile.mkstemp(suffix='.params')
    os.close(fd)
    try:
        # re-open by path: save_parameters publishes via atomic replace,
        # so a pre-opened fd would keep reading the original empty inode
        block.save_parameters(tmp)
        with open(tmp, 'rb') as f:
            data = f.read()
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass
    manifest = json.dumps({
        'format_version': mf.FORMAT_VERSION, 'step': int(step),
        'blobs': [{'name': 'weights', 'file': 'weights.params',
                   'bytes': len(data),
                   'sha256': mf.sha256_bytes(data)}],
    }).encode()
    results = {}
    for rep in replicas:
        host = rep.get('host', '127.0.0.1')
        try:
            _dist.file_put(host, rep['replica_port'], ns, step,
                           'weights.params', data, timeout=timeout)
            _dist.file_put(host, rep['replica_port'], ns, step,
                           mf.MANIFEST_NAME, manifest, timeout=timeout)
            _dist.replica_commit(host, rep['replica_port'], ns, step,
                                 timeout=timeout)
            status, doc = http_json(host, rep['serve_port'], '/reload',
                                    {'ns': ns, 'step': int(step)},
                                    timeout=timeout)
            results[rep['serve_port']] = {'status': status, 'doc': doc}
        except Exception as e:                        # noqa: BLE001
            results[rep['serve_port']] = {'error': repr(e)}
    _flight.note('serving.weight_push', step=int(step),
                 replicas=len(replicas))
    return results
