"""Test helpers (ref: python/mxnet/test_utils.py — 95 helpers)."""
from __future__ import annotations

import os

import numpy as onp

from .context import Context, cpu, current_context
from .ndarray.ndarray import NDArray, array
from . import autograd


def default_context() -> Context:
    """Context under test; override with MXNET_TEST_DEVICE (ref:
    test_utils.py default_context)."""
    dev = os.environ.get('MXNET_TEST_DEVICE', 'cpu')
    if dev.startswith('gpu') or dev.startswith('tpu'):
        from .context import gpu
        return gpu(0)
    return cpu(0)


def default_dtype():
    return onp.float32


def _as_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return onp.asarray(x)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=('a', 'b'),
                        equal_nan=False):
    a = _as_np(a)
    b = _as_np(b)
    onp.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                                equal_nan=equal_nan,
                                err_msg=f"{names[0]} != {names[1]}")


def almost_equal(a, b, rtol=1e-5, atol=1e-20, equal_nan=False):
    try:
        assert_almost_equal(a, b, rtol, atol, equal_nan=equal_nan)
        return True
    except AssertionError:
        return False


def same(a, b):
    return onp.array_equal(_as_np(a), _as_np(b))


def rand_ndarray(shape, stype='default', density=None, dtype=None, ctx=None):
    data = onp.random.uniform(-1, 1, size=shape).astype(dtype or onp.float32)
    arr = array(data, ctx=ctx)
    if stype != 'default':
        from .ndarray import sparse
        return sparse.cast_storage(arr, stype)
    return arr


def rand_shape_2d(dim0=10, dim1=10):
    return (onp.random.randint(1, dim0 + 1), onp.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (onp.random.randint(1, dim0 + 1), onp.random.randint(1, dim1 + 1),
            onp.random.randint(1, dim2 + 1))


def rand_shape_nd(num_dim, dim=10):
    return tuple(onp.random.randint(1, dim + 1, size=num_dim))


def check_numeric_gradient(f, inputs, eps=1e-4, rtol=1e-2, atol=1e-4):
    """Finite-difference gradient check for a scalar-output function over
    NDArray inputs (ref: test_utils.py check_numeric_gradient, adapted to the
    functional API: f takes NDArrays, returns a scalar NDArray)."""
    inputs = [x if isinstance(x, NDArray) else array(x) for x in inputs]
    for x in inputs:
        x.attach_grad()
    with autograd.record():
        y = f(*inputs)
    y.backward()
    analytic = [x.grad.asnumpy().copy() for x in inputs]

    for xi, x in enumerate(inputs):
        xv = x.asnumpy().astype(onp.float64)
        num_grad = onp.zeros_like(xv)
        flat = xv.ravel()
        ng_flat = num_grad.ravel()
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            xp = array(xv.astype(onp.float32))
            yp = f(*[xp if j == xi else inputs[j] for j in range(len(inputs))])
            flat[i] = orig - eps
            xm = array(xv.astype(onp.float32))
            ym = f(*[xm if j == xi else inputs[j] for j in range(len(inputs))])
            flat[i] = orig
            ng_flat[i] = (yp.asscalar() - ym.asscalar()) / (2 * eps)
        onp.testing.assert_allclose(analytic[xi], num_grad, rtol=rtol, atol=atol,
                                    err_msg=f"gradient mismatch for input {xi}")


def check_consistency(fn, inputs, ctx_list=None, rtol=1e-3, atol=1e-4):
    """Run fn on multiple contexts and compare outputs (ref:
    test_utils.py check_consistency)."""
    if ctx_list is None:
        ctx_list = [cpu(0)]
    results = []
    for ctx in ctx_list:
        ctx_inputs = [x.as_in_context(ctx) for x in inputs]
        results.append(_as_np(fn(*ctx_inputs)))
    for r in results[1:]:
        onp.testing.assert_allclose(results[0], r, rtol=rtol, atol=atol)
    return results


def discard_stderr():
    import contextlib
    import sys

    @contextlib.contextmanager
    def _ctx():
        with open(os.devnull, 'w') as devnull:
            old = sys.stderr
            sys.stderr = devnull
            try:
                yield
            finally:
                sys.stderr = old
    return _ctx()


class EnvManager:
    def __init__(self, key, val):
        self._key = key
        self._next_val = val
        self._prev_val = None

    def __enter__(self):
        self._prev_val = os.environ.get(self._key)
        os.environ[self._key] = self._next_val

    def __exit__(self, *exc):
        if self._prev_val:
            os.environ[self._key] = self._prev_val
        elif self._key in os.environ:
            del os.environ[self._key]


# ---------------------------------------------------------------------------
# tolerance tiers, generators, comparison and measurement helpers
# (ref: python/mxnet/test_utils.py get_atol/get_rtol/random_arrays/
#  numeric_grad/check_symbolic_forward/compare_optimizer/...)
# ---------------------------------------------------------------------------

_RTOLS = {onp.dtype('float16'): 1e-2, onp.dtype('float32'): 1e-4,
          onp.dtype('float64'): 1e-6}
_ATOLS = {onp.dtype('float16'): 1e-2, onp.dtype('float32'): 1e-5,
          onp.dtype('float64'): 1e-8}


def _bf16_dtype():
    import jax.numpy as jnp
    return jnp.bfloat16


def get_rtol(dtype=None, rtol=None):
    """Per-dtype default relative tolerance; bf16 (the TPU compute dtype)
    gets the loosest tier (8-bit mantissa ~= 2^-8)."""
    if rtol is not None:
        return rtol
    if dtype is not None and onp.dtype(dtype).name == 'bfloat16':
        return 2e-2
    return _RTOLS.get(onp.dtype(dtype) if dtype is not None else
                      onp.dtype('float32'), 1e-4)


def get_atol(dtype=None, atol=None):
    if atol is not None:
        return atol
    if dtype is not None and onp.dtype(dtype).name == 'bfloat16':
        return 2e-2
    return _ATOLS.get(onp.dtype(dtype) if dtype is not None else
                      onp.dtype('float32'), 1e-5)


def get_tolerance(arr, rtol=None, atol=None):
    dt = getattr(arr, 'dtype', onp.float32)
    return get_rtol(dt, rtol), get_atol(dt, atol)


def random_arrays(*shapes):
    """List of random float32 numpy arrays (scalars for () shapes)."""
    arrays = [onp.random.randn(*s).astype(onp.float32) if s else
              onp.float32(onp.random.randn()) for s in shapes]
    return arrays if len(arrays) > 1 else arrays[0]


def random_uniform_arrays(*shapes, low=0.0, high=1.0, dtype='float32'):
    return [onp.random.uniform(low, high, size=s).astype(dtype)
            for s in shapes]


def random_sample(population, k):
    """Sample without replacement preserving population order."""
    idx = sorted(onp.random.permutation(len(population))[:k].tolist())
    return [population[i] for i in idx]


def rand_coord_2d(x_low, x_high, y_low, y_high):
    x = onp.random.randint(x_low, x_high)
    y = onp.random.randint(y_low, y_high)
    return x, y


def create_2d_tensor(rows, columns, dtype=onp.int64):
    return onp.arange(rows * columns, dtype=dtype).reshape(rows, columns)


def create_vector(size, dtype=onp.int64):
    return onp.arange(size, dtype=dtype)


def assign_each(input_, fn):
    return onp.vectorize(fn)(input_) if fn is not None else input_.copy()


def assign_each2(input1, input2, fn):
    return onp.vectorize(fn)(input1, input2) if fn is not None \
        else input1.copy()


def np_reduce(dat, axis, keepdims, numpy_reduce_func):
    """Reference-style reduce wrapper handling axis tuples + keepdims
    (ref: test_utils.py np_reduce)."""
    if isinstance(axis, int):
        axis = (axis,)
    axes = axis if axis is not None else tuple(range(dat.ndim))
    ret = dat
    for a in reversed(sorted(axes)):
        ret = numpy_reduce_func(ret, axis=a)
    if keepdims:
        keepdims_shape = list(dat.shape)
        for a in axes:
            keepdims_shape[a] = 1
        ret = ret.reshape(tuple(keepdims_shape))
    return ret


def find_max_violation(a, b, rtol=1e-5, atol=1e-8):
    """Location and value of the worst |a-b| vs tolerance violation."""
    a, b = _as_np(a), _as_np(b)
    diff = onp.abs(a - b)
    tol = atol + rtol * onp.abs(b)
    violation = diff - tol
    idx = onp.unravel_index(onp.argmax(violation), violation.shape) \
        if violation.ndim else ()
    return idx, float(diff[idx] if violation.ndim else diff)


def assert_allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    assert_almost_equal(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)


def assert_almost_equal_with_err(a, b, rtol=1e-5, atol=1e-8, etol=0.0,
                                 names=('a', 'b')):
    """Allow a fraction etol of elements to violate tolerance
    (ref: test_utils.py assert_almost_equal_with_err)."""
    a, b = _as_np(a), _as_np(b)
    bad = onp.abs(a - b) > atol + rtol * onp.abs(b)
    frac = float(onp.mean(bad)) if bad.size else 0.0
    if frac > etol:
        idx, worst = find_max_violation(a, b, rtol, atol)
        raise AssertionError(
            f"{names[0]} != {names[1]}: {frac * 100:.2f}% elements exceed "
            f"tol (allowed {etol * 100:.2f}%); worst at {idx}: {worst}")


def almost_equal_ignore_nan(a, b, rtol=1e-5, atol=1e-8):
    a, b = _as_np(a).copy(), _as_np(b).copy()
    nan_mask = onp.logical_or(onp.isnan(a), onp.isnan(b))
    a[nan_mask] = 0
    b[nan_mask] = 0
    return almost_equal(a, b, rtol, atol)


def assert_almost_equal_ignore_nan(a, b, rtol=1e-5, atol=1e-8,
                                   names=('a', 'b')):
    if not almost_equal_ignore_nan(a, b, rtol, atol):
        raise AssertionError(f"{names[0]} != {names[1]} (ignoring NaN)")


def assert_exception(f, exception_type, *args, **kwargs):
    """f(*args, **kwargs) must raise exception_type
    (ref: test_utils.py assert_exception)."""
    try:
        f(*args, **kwargs)
    except exception_type:
        return
    raise AssertionError(f"did not raise {exception_type.__name__}")


def retry(n):
    """Retry a flaky (probabilistic) test up to n times (ref:
    test_utils.py retry)."""
    assert n > 0

    def decorate(f):
        import functools

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            for i in range(n):
                try:
                    return f(*args, **kwargs)
                except AssertionError:
                    if i == n - 1:
                        raise
            return None
        return wrapper
    return decorate


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    """Execute a symbol with numpy inputs, return numpy outputs
    (ref: test_utils.py simple_forward)."""
    inp = {k: array(v) for k, v in inputs.items()}
    exe = sym.bind(ctx or default_context(), inp)
    outputs = [o.asnumpy() for o in exe.forward(is_train=is_train)]
    return outputs[0] if len(outputs) == 1 else outputs


def numeric_grad(f, inputs, eps=1e-4):
    """Central finite differences of scalar-valued f at numpy inputs."""
    base = [onp.asarray(a, onp.float64).copy() for a in inputs]
    grads = []
    for i, x in enumerate(base):
        g = onp.zeros_like(x)
        it = onp.nditer(x, flags=['multi_index'])
        while not it.finished:
            idx = it.multi_index
            orig = x[idx]
            x[idx] = orig + eps
            fp = float(f(*base))
            x[idx] = orig - eps
            fm = float(f(*base))
            x[idx] = orig
            g[idx] = (fp - fm) / (2 * eps)
            it.iternext()
        grads.append(g)
    return grads


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=1e-5,
                           ctx=None):
    """Bind a symbol, run forward, compare each output against `expected`
    (ref: test_utils.py check_symbolic_forward)."""
    args = {k: array(v) for k, v in location.items()} \
        if isinstance(location, dict) else \
        {n: array(v) for n, v in zip(sym.list_arguments(), location)}
    exe = sym.bind(ctx or default_context(), args)
    outs = exe.forward(is_train=False)
    if not isinstance(expected, (list, tuple)):
        expected = [expected]
    for o, e in zip(outs, expected):
        assert_almost_equal(o, e, rtol=rtol, atol=atol)
    return [o.asnumpy() for o in outs]


def check_symbolic_backward(sym, location, out_grads, expected,
                            rtol=1e-4, atol=1e-5, ctx=None):
    """Bind with gradient buffers, run forward+backward, compare input
    grads (ref: test_utils.py check_symbolic_backward)."""
    names = sym.list_arguments()
    loc = location if isinstance(location, dict) else \
        dict(zip(names, location))
    args = {k: array(v) for k, v in loc.items()}
    grad_bufs = {k: array(onp.zeros_like(_as_np(v)))
                 for k, v in args.items()}
    exe = sym.bind(ctx or default_context(), args, args_grad=grad_bufs)
    exe.forward(is_train=True)
    exe.backward([array(g) for g in (
        out_grads if isinstance(out_grads, (list, tuple)) else [out_grads])])
    exp = expected if isinstance(expected, dict) else \
        dict(zip(names, expected))
    for k, e in exp.items():
        assert_almost_equal(grad_bufs[k], e, rtol=rtol, atol=atol,
                            names=(f'grad({k})', 'expected'))
    return {k: v.asnumpy() for k, v in grad_bufs.items()}


def check_speed(f, n=20, warmup=3):
    """Median wall-clock seconds per call after warmup."""
    import time
    for _ in range(warmup):
        f()
    times = []
    for _ in range(n):
        t0 = time.time()
        f()
        times.append(time.time() - t0)
    return float(onp.median(times))


def same_array(a, b):
    """True when two NDArrays share the same device buffer."""
    da = a._data if isinstance(a, NDArray) else a
    db = b._data if isinstance(b, NDArray) else b
    return da is db


class DummyIter:
    """Repeats one batch forever (ref: test_utils.py DummyIter)."""

    def __init__(self, batch):
        self.batch = batch

    def __iter__(self):
        return self

    def __next__(self):
        return self.batch


def gen_buckets_probs_with_ppf(ppf, nbuckets):
    """Equal-probability buckets from a percent-point function (ref:
    test_utils.py gen_buckets_probs_with_ppf)."""
    probs = [1.0 / nbuckets] * nbuckets
    buckets = [(ppf(i / nbuckets), ppf((i + 1) / nbuckets))
               for i in range(nbuckets)]
    return buckets, probs


def mean_check(generator, mu, sigma, nsamples=1000000, nrepeat=5):
    """Z-test that sample means are consistent with mu
    (ref: test_utils.py mean_check)."""
    ok = 0
    for _ in range(nrepeat):
        samples = onp.asarray(generator(nsamples), onp.float64)
        z = (samples.mean() - mu) / (sigma / onp.sqrt(nsamples))
        ok += abs(z) < 3.0
    return ok >= nrepeat - 1


def var_check(generator, sigma, nsamples=1000000, nrepeat=5):
    ok = 0
    for _ in range(nrepeat):
        samples = onp.asarray(generator(nsamples), onp.float64)
        ratio = samples.var() / (sigma ** 2)
        ok += 0.9 < ratio < 1.1
    return ok >= nrepeat - 1


def verify_generator(generator, buckets, probs, nsamples=100000,
                     nrepeat=3, success_rate=0.25):
    """Chi-square bucket test for samplers (ref: test_utils.py
    verify_generator / chi_square_check)."""
    successes = 0
    for _ in range(nrepeat):
        samples = onp.asarray(generator(nsamples), onp.float64).ravel()
        counts = onp.array(
            [onp.sum((samples >= lo) & (samples < hi))
             for lo, hi in buckets], onp.float64)
        expected = onp.array(probs, onp.float64) * samples.size
        chi2 = onp.sum((counts - expected) ** 2 / onp.maximum(expected, 1))
        # dof = nbuckets-1; 99.9th percentile approx via Wilson-Hilferty
        dof = len(buckets) - 1
        crit = dof * (1 - 2 / (9 * dof) + 3.09 * onp.sqrt(2 / (9 * dof))) ** 3
        successes += chi2 < crit
    return successes >= max(1, int(nrepeat * success_rate))


def compare_ndarray_tuple(t1, t2, rtol=1e-5, atol=1e-8):
    """Elementwise compare (nested) tuples of NDArrays (ref: test_utils.py
    compare_ndarray_tuple)."""
    if t1 is None or t2 is None:
        return
    if isinstance(t1, tuple):
        for a, b in zip(t1, t2):
            compare_ndarray_tuple(a, b, rtol, atol)
    else:
        assert_almost_equal(t1, t2, rtol=rtol, atol=atol)


def compare_optimizer(opt1, opt2, shapes, dtype, w_stype='default',
                      g_stype='default', rtol=1e-4, atol=1e-5, ntrials=3):
    """Run two optimizer implementations over identical weight/grad
    streams and require identical trajectories + states (ref:
    test_utils.py compare_optimizer)."""
    from .ndarray import zeros
    for _ in range(ntrials):
        w1, w2, g1, g2, s1, s2 = [], [], [], [], [], []
        for i, shape in enumerate(shapes):
            w = onp.random.uniform(-1, 1, shape).astype(dtype)
            g = onp.random.uniform(-1, 1, shape).astype(dtype)
            w1.append(array(w)); w2.append(array(w.copy()))
            g1.append(array(g)); g2.append(array(g.copy()))
            s1.append(opt1.create_state_multi_precision(i, w1[-1]))
            s2.append(opt2.create_state_multi_precision(i, w2[-1]))
        for i in range(len(shapes)):
            opt1.update_multi_precision(i, w1[i], g1[i], s1[i])
            opt2.update_multi_precision(i, w2[i], g2[i], s2[i])
            compare_ndarray_tuple(tuple(s1[i]) if isinstance(s1[i], tuple)
                                  else (s1[i],) if s1[i] is not None else (),
                                  tuple(s2[i]) if isinstance(s2[i], tuple)
                                  else (s2[i],) if s2[i] is not None else (),
                                  rtol, atol)
            assert_almost_equal(w1[i], w2[i], rtol=rtol, atol=atol)


def collapse_sum_like(a, shape):
    """Sum-reduce `a` down to `shape` following broadcast rules (ref:
    test_utils.py collapse_sum_like)."""
    a = _as_np(a)
    assert len(a.shape) >= len(shape)
    if onp.prod(shape) == 0 or a.size == 0:
        return onp.zeros(shape, a.dtype)
    axes = list(range(len(a.shape) - len(shape)))
    for i, s in enumerate(shape):
        if s != a.shape[len(a.shape) - len(shape) + i]:
            assert s == 1
            axes.append(len(a.shape) - len(shape) + i)
    return a.sum(axis=tuple(axes), keepdims=True).reshape(shape) \
        if axes else a.reshape(shape)


def check_gluon_hybridize_consistency(net_builder, data_l, numpy_func=None,
                                      test_grad=True, rtol=1e-4, atol=1e-5):
    """Eager vs hybridized forward (and backward) parity for a Gluon block
    (ref: test_utils.py check_gluon_hybridize_consistency)."""
    saved_out_np = None
    saved_grad_np_l = None
    for hybridize in (False, True):
        net = net_builder()
        net.initialize()
        if hybridize:
            net.hybridize()
        in_data_l = [array(_as_np(x)) for x in data_l]
        if test_grad:
            for x in in_data_l:
                x.attach_grad()
            with autograd.record():
                out = net(*in_data_l)
            out.backward()
            grad_np_l = [x.grad.asnumpy() for x in in_data_l]
        else:
            out = net(*in_data_l)
            grad_np_l = None
        out_np = out.asnumpy()
        if saved_out_np is None:
            saved_out_np = out_np
            saved_grad_np_l = grad_np_l
        else:
            assert_almost_equal(out_np, saved_out_np, rtol=rtol, atol=atol)
            if test_grad:
                for g, sg in zip(grad_np_l, saved_grad_np_l):
                    assert_almost_equal(g, sg, rtol=rtol, atol=atol)
    if numpy_func is not None:
        assert_almost_equal(saved_out_np,
                            numpy_func(*[_as_np(x) for x in data_l]),
                            rtol=rtol, atol=atol)


def new_sym_matrix_with_real_eigvals_nd(n):
    """Random symmetric matrix batch with real eigenvalues (ref:
    test_utils.py new_sym_matrix_with_real_eigvals_nd)."""
    a = onp.random.randn(n, n).astype(onp.float32)
    return (a + a.T) / 2


def new_matrix_with_real_eigvals_2d(n):
    """Random matrix with real eigenvalues: D + small symmetric noise via
    similarity transform (ref: test_utils.py)."""
    d = onp.diag(onp.random.uniform(1.0, 2.0, n))
    q, _ = onp.linalg.qr(onp.random.randn(n, n))
    return (q @ d @ q.T).astype(onp.float32)


# ---------------------------------------------------------------------------
# sparse generators (ref: test_utils.py rand_sparse_ndarray and the CSR
# dataset builders used by tests/python/unittest/test_sparse_operator.py)
# ---------------------------------------------------------------------------

def _validate_csr_generation_inputs(num_rows, num_cols, density,
                                    distribution="uniform"):
    total = num_rows * num_cols
    if density < 0 or density > 1:
        raise ValueError("density must be in [0, 1]")
    if total < 10:
        raise ValueError("matrix is too small; csr generators need >= 10 "
                         "elements")
    if distribution == "powerlaw" and int(density * num_cols) < 1:
        raise ValueError("powerlaw distribution needs at least one "
                         "nonzero per row; raise density")


def shuffle_csr_column_indices(csr):
    """API-parity shim (ref: test_utils.py shuffle_csr_column_indices).
    The reference shuffles per-row index order to exercise unsorted-index
    kernels; this framework's CSRNDArray is dense-backed (index order is
    canonical by construction), so there is nothing to shuffle — the
    array is returned unchanged and unsorted-index handling is a
    non-concern by design."""
    return csr


def _get_uniform_dataset_csr(num_rows, num_cols, density=0.1, dtype=None,
                             data_init=None, shuffle_csr_indices=False):
    """Uniformly-distributed CSR dataset (ref: test_utils.py)."""
    dtype = dtype or default_dtype()
    _validate_csr_generation_inputs(num_rows, num_cols, density)
    dense = onp.random.rand(num_rows, num_cols)
    dense = (dense < density).astype(dtype)
    if data_init is not None:
        dense *= data_init
    else:
        dense *= onp.random.rand(num_rows, num_cols).astype(dtype)
    from .ndarray import sparse as _sp
    csr = _sp.csr_matrix(dense, dtype=dtype)
    if shuffle_csr_indices:
        csr = shuffle_csr_column_indices(csr)
    return csr


def _get_powerlaw_dataset_csr(num_rows, num_cols, density=0.1, dtype=None):
    """Power-law row-popularity CSR dataset (ref: test_utils.py): row i
    has ~2x the nonzeros of row i+1 until the budget runs out."""
    dtype = dtype or default_dtype()
    _validate_csr_generation_inputs(num_rows, num_cols, density,
                                    "powerlaw")
    total_nnz = int(num_rows * num_cols * density)
    dense = onp.zeros((num_rows, num_cols), dtype)
    unused = total_nnz
    nnz_row = 1
    for i in range(num_rows):
        n = min(unused, nnz_row, num_cols)
        if n <= 0:
            break
        cols = onp.random.choice(num_cols, n, replace=False)
        dense[i, cols] = onp.random.rand(n).astype(dtype) + 0.1
        unused -= n
        nnz_row *= 2
    from .ndarray import sparse as _sp
    return _sp.csr_matrix(dense, dtype=dtype)


def rand_sparse_ndarray(shape, stype, density=None, dtype=None,
                        distribution=None, data_init=None,
                        rsp_indices=None, shuffle_csr_indices=False):
    """Random sparse ndarray + its dense numpy value
    (ref: test_utils.py rand_sparse_ndarray). Returns (arr, (value,...))
    matching the reference's (arr, (data, indices...)) contract loosely:
    the second element is the dense numpy array."""
    density = onp.random.rand() if density is None else density
    dtype = dtype or default_dtype()
    distribution = distribution or "uniform"
    from .ndarray import sparse as _sp
    if stype == 'row_sparse':
        dense = onp.zeros(shape, dtype)
        if rsp_indices is not None:
            idx = onp.asarray(rsp_indices, onp.int64)
        else:
            n = max(1, int(shape[0] * density))
            idx = onp.sort(onp.random.choice(shape[0], n, replace=False))
        dense[idx] = onp.random.rand(len(idx), *shape[1:]).astype(dtype) \
            if len(shape) > 1 else onp.random.rand(len(idx)).astype(dtype)
        return _sp.row_sparse_array(dense, dtype=dtype), dense
    elif stype == 'csr':
        assert len(shape) == 2
        if distribution == "powerlaw":
            csr = _get_powerlaw_dataset_csr(shape[0], shape[1],
                                            density=density, dtype=dtype)
        else:
            csr = _get_uniform_dataset_csr(
                shape[0], shape[1], density=density, dtype=dtype,
                data_init=data_init,
                shuffle_csr_indices=shuffle_csr_indices)
        return csr, csr.asnumpy()
    raise ValueError(f"unknown sparse stype {stype!r}")


def create_sparse_array(shape, stype, data_init=None, rsp_indices=None,
                        dtype=None, modifier_func=None, density=0.5,
                        shuffle_csr_indices=False):
    """Sparse array with optional per-element modifier (ref:
    test_utils.py create_sparse_array)."""
    arr, dense = rand_sparse_ndarray(
        shape, stype, density=density, dtype=dtype, data_init=data_init,
        rsp_indices=rsp_indices, shuffle_csr_indices=shuffle_csr_indices)
    if modifier_func is not None:
        vec = onp.vectorize(modifier_func)
        dense = onp.where(dense != 0, vec(dense).astype(dense.dtype), dense)
        from .ndarray import sparse as _sp
        arr = (_sp.csr_matrix(dense, dtype=dense.dtype)
               if stype == 'csr'
               else _sp.row_sparse_array(dense, dtype=dense.dtype))
    return arr


def create_sparse_array_zd(shape, stype, density, data_init=None,
                           rsp_indices=None, dtype=None,
                           modifier_func=None, shuffle_csr_indices=False):
    """Sparse array that may have zero density (all-zero array)
    (ref: test_utils.py create_sparse_array_zd)."""
    if density == 0:
        from .ndarray import sparse as _sp
        dense = onp.zeros(shape, dtype or default_dtype())
        return (_sp.csr_matrix(dense, dtype=dense.dtype)
                if stype == 'csr'
                else _sp.row_sparse_array(dense, dtype=dense.dtype))
    return create_sparse_array(shape, stype, data_init=data_init,
                               rsp_indices=rsp_indices, dtype=dtype,
                               modifier_func=modifier_func, density=density,
                               shuffle_csr_indices=shuffle_csr_indices)


# ---------------------------------------------------------------------------
# location/shape plumbing shared by the check_symbolic_* helpers
# (ref: test_utils.py _parse_location, checkShapes, locationError)
# ---------------------------------------------------------------------------

def _parse_location(sym, location, ctx=None, dtype=None):
    """Normalize a list/dict of inputs into a name->NDArray dict for
    `sym`'s arguments (ref: test_utils.py _parse_location)."""
    assert isinstance(location, (dict, list, tuple))
    names = sym.list_arguments() if hasattr(sym, 'list_arguments') else None
    if isinstance(location, dict):
        if names is not None:
            missing = set(location) - set(names)
            if missing:
                raise ValueError(f"location keys {sorted(missing)} not in "
                                 f"symbol arguments {names}")
        return {k: array(_as_np(v)) for k, v in location.items()}
    if names is None:
        names = [f"arg{i}" for i in range(len(location))]
    if len(names) != len(location):
        raise ValueError(
            f"expected {len(names)} inputs for arguments {names}, "
            f"got {len(location)}")
    return {n: array(_as_np(v)) for n, v in zip(names, location)}


def check_shapes(expected, actual):
    """Shape-tuple list equality with a readable error
    (ref: test_utils.py checkShapes)."""
    if tuple(expected) != tuple(actual):
        raise AssertionError(f"shape mismatch: expected {expected}, "
                             f"got {actual}")


def location_error(expected, got, name):
    """Standard message for input-mismatch errors
    (ref: test_utils.py locationError)."""
    return (f"location {name!r}: expected {expected}, got {got}")


# ---------------------------------------------------------------------------
# statistical checks (ref: test_utils.py chi_square_check/verify_generator)
# ---------------------------------------------------------------------------

def chi_square_check(generator, buckets, probs, nsamples=1000000):
    """Chi-square goodness-of-fit of `generator(n)` samples against
    bucket probabilities (ref: test_utils.py chi_square_check).
    Returns (chi2_statistic, bucket_counts)."""
    samples = onp.asarray(generator(nsamples)).reshape(-1)
    expected = onp.asarray(probs, onp.float64) * len(samples)
    counts = onp.zeros(len(buckets))
    if isinstance(buckets[0], (list, tuple)):
        for i, (lo, hi) in enumerate(buckets):
            counts[i] = onp.sum((samples >= lo) & (samples < hi))
    else:
        for i, v in enumerate(buckets):
            counts[i] = onp.sum(samples == v)
    chi2 = onp.sum((counts - expected) ** 2 / onp.maximum(expected, 1e-9))
    return float(chi2), counts


# ---------------------------------------------------------------------------
# environment / dataset utilities (ref: test_utils.py)
# ---------------------------------------------------------------------------

def set_default_context(ctx):
    """Set the thread default context (ref: test_utils.py
    set_default_context) — pushes onto the same stack the
    `with ctx:` form uses."""
    from .context import Context
    if not hasattr(Context._default_ctx, 'stack'):
        Context._default_ctx.stack = []
    Context._default_ctx.stack.append(ctx)


def get_etol(etol=None):
    """Permitted element-mismatch fraction (ref: test_utils.py get_etol)."""
    return 0.0 if etol is None else etol


def list_gpus():
    """Indices of visible GPU/TPU accelerators (ref: test_utils.py
    list_gpus — CUDA there, any non-CPU jax device here)."""
    import jax
    try:
        return list(range(len([d for d in jax.devices()
                               if d.platform != 'cpu'])))
    except Exception:
        return []


def set_env_var(key, val, default_val=""):
    """Set env var, returning its previous value
    (ref: test_utils.py set_env_var)."""
    prev = os.environ.get(key, default_val)
    os.environ[key] = val
    return prev


def get_mnist(path=None):
    """MNIST as numpy dicts. Reads the idx files from `path` (or
    MXNET_TPU_MNIST_DIR); falls back to a deterministic synthetic set in
    airgapped environments (ref: test_utils.py get_mnist, which
    downloads — zero-egress images can't)."""
    from . import config as _tu_config
    path = path or _tu_config.get('MXNET_TPU_MNIST_DIR')
    if path and os.path.exists(os.path.join(path,
                                            'train-images-idx3-ubyte')):
        def read_idx(p):  # pragma: no cover - needs real files
            import struct
            with open(p, 'rb') as f:
                magic = struct.unpack('>I', f.read(4))[0]
                ndim = magic & 0xFF
                dims = struct.unpack('>' + 'I' * ndim, f.read(4 * ndim))
                return onp.frombuffer(f.read(), onp.uint8).reshape(dims)
        # same dtypes as the synthetic fallback: float32 images in [0,1]
        # (jax x64 is disabled), int32 labels
        return {
            'train_data': (read_idx(os.path.join(
                path, 'train-images-idx3-ubyte'))[:, None]
                / onp.float32(255.0)).astype(onp.float32),
            'train_label': read_idx(os.path.join(
                path, 'train-labels-idx1-ubyte')).astype(onp.int32),
            'test_data': (read_idx(os.path.join(
                path, 't10k-images-idx3-ubyte'))[:, None]
                / onp.float32(255.0)).astype(onp.float32),
            'test_label': read_idx(os.path.join(
                path, 't10k-labels-idx1-ubyte')).astype(onp.int32),
        }
    rng = onp.random.RandomState(42)
    def synth(n):
        labels = rng.randint(0, 10, n).astype(onp.int32)
        imgs = rng.rand(n, 1, 28, 28).astype(onp.float32) * 0.1
        for i, l in enumerate(labels):  # class-dependent blob
            imgs[i, 0, l:l + 10, l:l + 10] += 0.8
        return imgs, labels
    td, tl = synth(1024)
    vd, vl = synth(256)
    return {'train_data': td, 'train_label': tl,
            'test_data': vd, 'test_label': vl}


def get_mnist_iterator(batch_size, input_shape=(1, 28, 28), num_parts=1,
                       part_index=0):
    """(train_iter, val_iter) over get_mnist; num_parts/part_index give
    each data-parallel worker a disjoint contiguous shard of the train
    set (ref: test_utils.py get_mnist_iterator)."""
    from .io import NDArrayIter
    m = get_mnist()
    shape = (-1,) + tuple(input_shape)
    td = m['train_data'].reshape(shape)
    tl = m['train_label']
    if num_parts > 1:
        n = len(td) // num_parts
        td = td[part_index * n:(part_index + 1) * n]
        tl = tl[part_index * n:(part_index + 1) * n]
    train = NDArrayIter(td, tl, batch_size, shuffle=True)
    val = NDArrayIter(m['test_data'].reshape(shape), m['test_label'],
                      batch_size)
    return train, val


def get_zip_data(data_dir, url, data_origin_name):
    """Unpack a local zip (download step is a copy in airgapped setups;
    ref: test_utils.py get_zip_data)."""
    import zipfile
    path = os.path.join(data_dir, data_origin_name)
    if os.path.exists(path):
        with zipfile.ZipFile(path) as z:
            z.extractall(data_dir)


def get_bz2_data(data_dir, data_name, url, data_origin_name):
    """Unpack a local .bz2 (ref: test_utils.py get_bz2_data)."""
    import bz2
    import shutil
    out = os.path.join(data_dir, data_name)
    src = os.path.join(data_dir, data_origin_name)
    if not os.path.exists(out) and os.path.exists(src):
        with bz2.BZ2File(src) as fin, open(out, 'wb') as fout:
            shutil.copyfileobj(fin, fout)


def same_symbol_structure(sym1, sym2):
    """Whether two Symbols have the same graph structure (op sequence and
    arity; ref: test_utils.py same_symbol_structure)."""
    def sig(sym):
        import json
        g = json.loads(sym.tojson())
        return [(n.get('op'), len(n.get('inputs', [])))
                for n in g.get('nodes', [])]
    return sig(sym1) == sig(sym2)


def is_cd_run():
    """Whether running in a continuous-delivery pipeline
    (ref: test_utils.py is_cd_run)."""
    return os.environ.get("CD_JOB", "0") == "1"


def has_tvm_ops():
    """TVM-compiled operators are never present in the TPU build — XLA is
    the backend (ref: test_utils.py has_tvm_ops)."""
    return False


def is_op_runnable():
    """Reference gate for large-tensor/TVM ops; always runnable here
    (ref: test_utils.py is_op_runnable)."""
    return True


def new_matrix_with_real_eigvals_nd(n, ndim=3):
    """Batched random matrices with real eigenvalues
    (ref: test_utils.py new_matrix_with_real_eigvals_nd)."""
    return onp.stack([new_matrix_with_real_eigvals_2d(n)
                      for _ in range(ndim)])


def new_orthonormal_matrix_2d(n):
    """Random orthonormal matrix via QR (ref: test_utils.py)."""
    q, _ = onp.linalg.qr(onp.random.randn(n, n))
    return q.astype(onp.float32)


def new_sym_matrix_with_real_eigvals_2d(n):
    """Random symmetric matrix (real eigenvalues by construction;
    ref: test_utils.py new_sym_matrix_with_real_eigvals_2d)."""
    a = onp.random.randn(n, n).astype(onp.float32)
    return (a + a.T) / 2
