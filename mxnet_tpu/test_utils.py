"""Test helpers (ref: python/mxnet/test_utils.py — 95 helpers)."""
from __future__ import annotations

import os

import numpy as onp

from .context import Context, cpu, current_context
from .ndarray.ndarray import NDArray, array
from . import autograd


def default_context() -> Context:
    """Context under test; override with MXNET_TEST_DEVICE (ref:
    test_utils.py default_context)."""
    dev = os.environ.get('MXNET_TEST_DEVICE', 'cpu')
    if dev.startswith('gpu') or dev.startswith('tpu'):
        from .context import gpu
        return gpu(0)
    return cpu(0)


def default_dtype():
    return onp.float32


def _as_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return onp.asarray(x)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=('a', 'b'),
                        equal_nan=False):
    a = _as_np(a)
    b = _as_np(b)
    onp.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                                equal_nan=equal_nan,
                                err_msg=f"{names[0]} != {names[1]}")


def almost_equal(a, b, rtol=1e-5, atol=1e-20, equal_nan=False):
    try:
        assert_almost_equal(a, b, rtol, atol, equal_nan=equal_nan)
        return True
    except AssertionError:
        return False


def same(a, b):
    return onp.array_equal(_as_np(a), _as_np(b))


def rand_ndarray(shape, stype='default', density=None, dtype=None, ctx=None):
    data = onp.random.uniform(-1, 1, size=shape).astype(dtype or onp.float32)
    arr = array(data, ctx=ctx)
    if stype != 'default':
        from .ndarray import sparse
        return sparse.cast_storage(arr, stype)
    return arr


def rand_shape_2d(dim0=10, dim1=10):
    return (onp.random.randint(1, dim0 + 1), onp.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (onp.random.randint(1, dim0 + 1), onp.random.randint(1, dim1 + 1),
            onp.random.randint(1, dim2 + 1))


def rand_shape_nd(num_dim, dim=10):
    return tuple(onp.random.randint(1, dim + 1, size=num_dim))


def check_numeric_gradient(f, inputs, eps=1e-4, rtol=1e-2, atol=1e-4):
    """Finite-difference gradient check for a scalar-output function over
    NDArray inputs (ref: test_utils.py check_numeric_gradient, adapted to the
    functional API: f takes NDArrays, returns a scalar NDArray)."""
    inputs = [x if isinstance(x, NDArray) else array(x) for x in inputs]
    for x in inputs:
        x.attach_grad()
    with autograd.record():
        y = f(*inputs)
    y.backward()
    analytic = [x.grad.asnumpy().copy() for x in inputs]

    for xi, x in enumerate(inputs):
        xv = x.asnumpy().astype(onp.float64)
        num_grad = onp.zeros_like(xv)
        flat = xv.ravel()
        ng_flat = num_grad.ravel()
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            xp = array(xv.astype(onp.float32))
            yp = f(*[xp if j == xi else inputs[j] for j in range(len(inputs))])
            flat[i] = orig - eps
            xm = array(xv.astype(onp.float32))
            ym = f(*[xm if j == xi else inputs[j] for j in range(len(inputs))])
            flat[i] = orig
            ng_flat[i] = (yp.asscalar() - ym.asscalar()) / (2 * eps)
        onp.testing.assert_allclose(analytic[xi], num_grad, rtol=rtol, atol=atol,
                                    err_msg=f"gradient mismatch for input {xi}")


def check_consistency(fn, inputs, ctx_list=None, rtol=1e-3, atol=1e-4):
    """Run fn on multiple contexts and compare outputs (ref:
    test_utils.py check_consistency)."""
    if ctx_list is None:
        ctx_list = [cpu(0)]
    results = []
    for ctx in ctx_list:
        ctx_inputs = [x.as_in_context(ctx) for x in inputs]
        results.append(_as_np(fn(*ctx_inputs)))
    for r in results[1:]:
        onp.testing.assert_allclose(results[0], r, rtol=rtol, atol=atol)
    return results


def discard_stderr():
    import contextlib
    import sys

    @contextlib.contextmanager
    def _ctx():
        with open(os.devnull, 'w') as devnull:
            old = sys.stderr
            sys.stderr = devnull
            try:
                yield
            finally:
                sys.stderr = old
    return _ctx()


class EnvManager:
    def __init__(self, key, val):
        self._key = key
        self._next_val = val
        self._prev_val = None

    def __enter__(self):
        self._prev_val = os.environ.get(self._key)
        os.environ[self._key] = self._next_val

    def __exit__(self, *exc):
        if self._prev_val:
            os.environ[self._key] = self._prev_val
        elif self._key in os.environ:
            del os.environ[self._key]
