"""Test helpers (ref: python/mxnet/test_utils.py — 95 helpers)."""
from __future__ import annotations

import os

import numpy as onp

from .context import Context, cpu, current_context
from .ndarray.ndarray import NDArray, array
from . import autograd


def default_context() -> Context:
    """Context under test; override with MXNET_TEST_DEVICE (ref:
    test_utils.py default_context)."""
    dev = os.environ.get('MXNET_TEST_DEVICE', 'cpu')
    if dev.startswith('gpu') or dev.startswith('tpu'):
        from .context import gpu
        return gpu(0)
    return cpu(0)


def default_dtype():
    return onp.float32


def _as_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return onp.asarray(x)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=('a', 'b'),
                        equal_nan=False):
    a = _as_np(a)
    b = _as_np(b)
    onp.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                                equal_nan=equal_nan,
                                err_msg=f"{names[0]} != {names[1]}")


def almost_equal(a, b, rtol=1e-5, atol=1e-20, equal_nan=False):
    try:
        assert_almost_equal(a, b, rtol, atol, equal_nan=equal_nan)
        return True
    except AssertionError:
        return False


def same(a, b):
    return onp.array_equal(_as_np(a), _as_np(b))


def rand_ndarray(shape, stype='default', density=None, dtype=None, ctx=None):
    data = onp.random.uniform(-1, 1, size=shape).astype(dtype or onp.float32)
    arr = array(data, ctx=ctx)
    if stype != 'default':
        from .ndarray import sparse
        return sparse.cast_storage(arr, stype)
    return arr


def rand_shape_2d(dim0=10, dim1=10):
    return (onp.random.randint(1, dim0 + 1), onp.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (onp.random.randint(1, dim0 + 1), onp.random.randint(1, dim1 + 1),
            onp.random.randint(1, dim2 + 1))


def rand_shape_nd(num_dim, dim=10):
    return tuple(onp.random.randint(1, dim + 1, size=num_dim))


def check_numeric_gradient(f, inputs, eps=1e-4, rtol=1e-2, atol=1e-4):
    """Finite-difference gradient check for a scalar-output function over
    NDArray inputs (ref: test_utils.py check_numeric_gradient, adapted to the
    functional API: f takes NDArrays, returns a scalar NDArray)."""
    inputs = [x if isinstance(x, NDArray) else array(x) for x in inputs]
    for x in inputs:
        x.attach_grad()
    with autograd.record():
        y = f(*inputs)
    y.backward()
    analytic = [x.grad.asnumpy().copy() for x in inputs]

    for xi, x in enumerate(inputs):
        xv = x.asnumpy().astype(onp.float64)
        num_grad = onp.zeros_like(xv)
        flat = xv.ravel()
        ng_flat = num_grad.ravel()
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            xp = array(xv.astype(onp.float32))
            yp = f(*[xp if j == xi else inputs[j] for j in range(len(inputs))])
            flat[i] = orig - eps
            xm = array(xv.astype(onp.float32))
            ym = f(*[xm if j == xi else inputs[j] for j in range(len(inputs))])
            flat[i] = orig
            ng_flat[i] = (yp.asscalar() - ym.asscalar()) / (2 * eps)
        onp.testing.assert_allclose(analytic[xi], num_grad, rtol=rtol, atol=atol,
                                    err_msg=f"gradient mismatch for input {xi}")


def check_consistency(fn, inputs, ctx_list=None, rtol=1e-3, atol=1e-4):
    """Run fn on multiple contexts and compare outputs (ref:
    test_utils.py check_consistency)."""
    if ctx_list is None:
        ctx_list = [cpu(0)]
    results = []
    for ctx in ctx_list:
        ctx_inputs = [x.as_in_context(ctx) for x in inputs]
        results.append(_as_np(fn(*ctx_inputs)))
    for r in results[1:]:
        onp.testing.assert_allclose(results[0], r, rtol=rtol, atol=atol)
    return results


def discard_stderr():
    import contextlib
    import sys

    @contextlib.contextmanager
    def _ctx():
        with open(os.devnull, 'w') as devnull:
            old = sys.stderr
            sys.stderr = devnull
            try:
                yield
            finally:
                sys.stderr = old
    return _ctx()


class EnvManager:
    def __init__(self, key, val):
        self._key = key
        self._next_val = val
        self._prev_val = None

    def __enter__(self):
        self._prev_val = os.environ.get(self._key)
        os.environ[self._key] = self._next_val

    def __exit__(self, *exc):
        if self._prev_val:
            os.environ[self._key] = self._prev_val
        elif self._key in os.environ:
            del os.environ[self._key]


# ---------------------------------------------------------------------------
# tolerance tiers, generators, comparison and measurement helpers
# (ref: python/mxnet/test_utils.py get_atol/get_rtol/random_arrays/
#  numeric_grad/check_symbolic_forward/compare_optimizer/...)
# ---------------------------------------------------------------------------

_RTOLS = {onp.dtype('float16'): 1e-2, onp.dtype('float32'): 1e-4,
          onp.dtype('float64'): 1e-6}
_ATOLS = {onp.dtype('float16'): 1e-2, onp.dtype('float32'): 1e-5,
          onp.dtype('float64'): 1e-8}


def _bf16_dtype():
    import jax.numpy as jnp
    return jnp.bfloat16


def get_rtol(dtype=None, rtol=None):
    """Per-dtype default relative tolerance; bf16 (the TPU compute dtype)
    gets the loosest tier (8-bit mantissa ~= 2^-8)."""
    if rtol is not None:
        return rtol
    if dtype is not None and onp.dtype(dtype).name == 'bfloat16':
        return 2e-2
    return _RTOLS.get(onp.dtype(dtype) if dtype is not None else
                      onp.dtype('float32'), 1e-4)


def get_atol(dtype=None, atol=None):
    if atol is not None:
        return atol
    if dtype is not None and onp.dtype(dtype).name == 'bfloat16':
        return 2e-2
    return _ATOLS.get(onp.dtype(dtype) if dtype is not None else
                      onp.dtype('float32'), 1e-5)


def get_tolerance(arr, rtol=None, atol=None):
    dt = getattr(arr, 'dtype', onp.float32)
    return get_rtol(dt, rtol), get_atol(dt, atol)


def random_arrays(*shapes):
    """List of random float32 numpy arrays (scalars for () shapes)."""
    arrays = [onp.random.randn(*s).astype(onp.float32) if s else
              onp.float32(onp.random.randn()) for s in shapes]
    return arrays if len(arrays) > 1 else arrays[0]


def random_uniform_arrays(*shapes, low=0.0, high=1.0, dtype='float32'):
    return [onp.random.uniform(low, high, size=s).astype(dtype)
            for s in shapes]


def random_sample(population, k):
    """Sample without replacement preserving population order."""
    idx = sorted(onp.random.permutation(len(population))[:k].tolist())
    return [population[i] for i in idx]


def rand_coord_2d(x_low, x_high, y_low, y_high):
    x = onp.random.randint(x_low, x_high)
    y = onp.random.randint(y_low, y_high)
    return x, y


def create_2d_tensor(rows, columns, dtype=onp.int64):
    return onp.arange(rows * columns, dtype=dtype).reshape(rows, columns)


def create_vector(size, dtype=onp.int64):
    return onp.arange(size, dtype=dtype)


def assign_each(input_, fn):
    return onp.vectorize(fn)(input_) if fn is not None else input_.copy()


def assign_each2(input1, input2, fn):
    return onp.vectorize(fn)(input1, input2) if fn is not None \
        else input1.copy()


def np_reduce(dat, axis, keepdims, numpy_reduce_func):
    """Reference-style reduce wrapper handling axis tuples + keepdims
    (ref: test_utils.py np_reduce)."""
    if isinstance(axis, int):
        axis = (axis,)
    axes = axis if axis is not None else tuple(range(dat.ndim))
    ret = dat
    for a in reversed(sorted(axes)):
        ret = numpy_reduce_func(ret, axis=a)
    if keepdims:
        keepdims_shape = list(dat.shape)
        for a in axes:
            keepdims_shape[a] = 1
        ret = ret.reshape(tuple(keepdims_shape))
    return ret


def find_max_violation(a, b, rtol=1e-5, atol=1e-8):
    """Location and value of the worst |a-b| vs tolerance violation."""
    a, b = _as_np(a), _as_np(b)
    diff = onp.abs(a - b)
    tol = atol + rtol * onp.abs(b)
    violation = diff - tol
    idx = onp.unravel_index(onp.argmax(violation), violation.shape) \
        if violation.ndim else ()
    return idx, float(diff[idx] if violation.ndim else diff)


def assert_allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    assert_almost_equal(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)


def assert_almost_equal_with_err(a, b, rtol=1e-5, atol=1e-8, etol=0.0,
                                 names=('a', 'b')):
    """Allow a fraction etol of elements to violate tolerance
    (ref: test_utils.py assert_almost_equal_with_err)."""
    a, b = _as_np(a), _as_np(b)
    bad = onp.abs(a - b) > atol + rtol * onp.abs(b)
    frac = float(onp.mean(bad)) if bad.size else 0.0
    if frac > etol:
        idx, worst = find_max_violation(a, b, rtol, atol)
        raise AssertionError(
            f"{names[0]} != {names[1]}: {frac * 100:.2f}% elements exceed "
            f"tol (allowed {etol * 100:.2f}%); worst at {idx}: {worst}")


def almost_equal_ignore_nan(a, b, rtol=1e-5, atol=1e-8):
    a, b = _as_np(a).copy(), _as_np(b).copy()
    nan_mask = onp.logical_or(onp.isnan(a), onp.isnan(b))
    a[nan_mask] = 0
    b[nan_mask] = 0
    return almost_equal(a, b, rtol, atol)


def assert_almost_equal_ignore_nan(a, b, rtol=1e-5, atol=1e-8,
                                   names=('a', 'b')):
    if not almost_equal_ignore_nan(a, b, rtol, atol):
        raise AssertionError(f"{names[0]} != {names[1]} (ignoring NaN)")


def assert_exception(f, exception_type, *args, **kwargs):
    """f(*args, **kwargs) must raise exception_type
    (ref: test_utils.py assert_exception)."""
    try:
        f(*args, **kwargs)
    except exception_type:
        return
    raise AssertionError(f"did not raise {exception_type.__name__}")


def retry(n):
    """Retry a flaky (probabilistic) test up to n times (ref:
    test_utils.py retry)."""
    assert n > 0

    def decorate(f):
        import functools

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            for i in range(n):
                try:
                    return f(*args, **kwargs)
                except AssertionError:
                    if i == n - 1:
                        raise
            return None
        return wrapper
    return decorate


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    """Execute a symbol with numpy inputs, return numpy outputs
    (ref: test_utils.py simple_forward)."""
    inp = {k: array(v) for k, v in inputs.items()}
    exe = sym.bind(ctx or default_context(), inp)
    outputs = [o.asnumpy() for o in exe.forward(is_train=is_train)]
    return outputs[0] if len(outputs) == 1 else outputs


def numeric_grad(f, inputs, eps=1e-4):
    """Central finite differences of scalar-valued f at numpy inputs."""
    base = [onp.asarray(a, onp.float64).copy() for a in inputs]
    grads = []
    for i, x in enumerate(base):
        g = onp.zeros_like(x)
        it = onp.nditer(x, flags=['multi_index'])
        while not it.finished:
            idx = it.multi_index
            orig = x[idx]
            x[idx] = orig + eps
            fp = float(f(*base))
            x[idx] = orig - eps
            fm = float(f(*base))
            x[idx] = orig
            g[idx] = (fp - fm) / (2 * eps)
            it.iternext()
        grads.append(g)
    return grads


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=1e-5,
                           ctx=None):
    """Bind a symbol, run forward, compare each output against `expected`
    (ref: test_utils.py check_symbolic_forward)."""
    args = {k: array(v) for k, v in location.items()} \
        if isinstance(location, dict) else \
        {n: array(v) for n, v in zip(sym.list_arguments(), location)}
    exe = sym.bind(ctx or default_context(), args)
    outs = exe.forward(is_train=False)
    if not isinstance(expected, (list, tuple)):
        expected = [expected]
    for o, e in zip(outs, expected):
        assert_almost_equal(o, e, rtol=rtol, atol=atol)
    return [o.asnumpy() for o in outs]


def check_symbolic_backward(sym, location, out_grads, expected,
                            rtol=1e-4, atol=1e-5, ctx=None):
    """Bind with gradient buffers, run forward+backward, compare input
    grads (ref: test_utils.py check_symbolic_backward)."""
    names = sym.list_arguments()
    loc = location if isinstance(location, dict) else \
        dict(zip(names, location))
    args = {k: array(v) for k, v in loc.items()}
    grad_bufs = {k: array(onp.zeros_like(_as_np(v)))
                 for k, v in args.items()}
    exe = sym.bind(ctx or default_context(), args, args_grad=grad_bufs)
    exe.forward(is_train=True)
    exe.backward([array(g) for g in (
        out_grads if isinstance(out_grads, (list, tuple)) else [out_grads])])
    exp = expected if isinstance(expected, dict) else \
        dict(zip(names, expected))
    for k, e in exp.items():
        assert_almost_equal(grad_bufs[k], e, rtol=rtol, atol=atol,
                            names=(f'grad({k})', 'expected'))
    return {k: v.asnumpy() for k, v in grad_bufs.items()}


def check_speed(f, n=20, warmup=3):
    """Median wall-clock seconds per call after warmup."""
    import time
    for _ in range(warmup):
        f()
    times = []
    for _ in range(n):
        t0 = time.time()
        f()
        times.append(time.time() - t0)
    return float(onp.median(times))


def same_array(a, b):
    """True when two NDArrays share the same device buffer."""
    da = a._data if isinstance(a, NDArray) else a
    db = b._data if isinstance(b, NDArray) else b
    return da is db


class DummyIter:
    """Repeats one batch forever (ref: test_utils.py DummyIter)."""

    def __init__(self, batch):
        self.batch = batch

    def __iter__(self):
        return self

    def __next__(self):
        return self.batch


def gen_buckets_probs_with_ppf(ppf, nbuckets):
    """Equal-probability buckets from a percent-point function (ref:
    test_utils.py gen_buckets_probs_with_ppf)."""
    probs = [1.0 / nbuckets] * nbuckets
    buckets = [(ppf(i / nbuckets), ppf((i + 1) / nbuckets))
               for i in range(nbuckets)]
    return buckets, probs


def mean_check(generator, mu, sigma, nsamples=1000000, nrepeat=5):
    """Z-test that sample means are consistent with mu
    (ref: test_utils.py mean_check)."""
    ok = 0
    for _ in range(nrepeat):
        samples = onp.asarray(generator(nsamples), onp.float64)
        z = (samples.mean() - mu) / (sigma / onp.sqrt(nsamples))
        ok += abs(z) < 3.0
    return ok >= nrepeat - 1


def var_check(generator, sigma, nsamples=1000000, nrepeat=5):
    ok = 0
    for _ in range(nrepeat):
        samples = onp.asarray(generator(nsamples), onp.float64)
        ratio = samples.var() / (sigma ** 2)
        ok += 0.9 < ratio < 1.1
    return ok >= nrepeat - 1


def verify_generator(generator, buckets, probs, nsamples=100000,
                     nrepeat=3, success_rate=0.25):
    """Chi-square bucket test for samplers (ref: test_utils.py
    verify_generator / chi_square_check)."""
    successes = 0
    for _ in range(nrepeat):
        samples = onp.asarray(generator(nsamples), onp.float64).ravel()
        counts = onp.array(
            [onp.sum((samples >= lo) & (samples < hi))
             for lo, hi in buckets], onp.float64)
        expected = onp.array(probs, onp.float64) * samples.size
        chi2 = onp.sum((counts - expected) ** 2 / onp.maximum(expected, 1))
        # dof = nbuckets-1; 99.9th percentile approx via Wilson-Hilferty
        dof = len(buckets) - 1
        crit = dof * (1 - 2 / (9 * dof) + 3.09 * onp.sqrt(2 / (9 * dof))) ** 3
        successes += chi2 < crit
    return successes >= max(1, int(nrepeat * success_rate))


def compare_ndarray_tuple(t1, t2, rtol=1e-5, atol=1e-8):
    """Elementwise compare (nested) tuples of NDArrays (ref: test_utils.py
    compare_ndarray_tuple)."""
    if t1 is None or t2 is None:
        return
    if isinstance(t1, tuple):
        for a, b in zip(t1, t2):
            compare_ndarray_tuple(a, b, rtol, atol)
    else:
        assert_almost_equal(t1, t2, rtol=rtol, atol=atol)


def compare_optimizer(opt1, opt2, shapes, dtype, w_stype='default',
                      g_stype='default', rtol=1e-4, atol=1e-5, ntrials=3):
    """Run two optimizer implementations over identical weight/grad
    streams and require identical trajectories + states (ref:
    test_utils.py compare_optimizer)."""
    from .ndarray import zeros
    for _ in range(ntrials):
        w1, w2, g1, g2, s1, s2 = [], [], [], [], [], []
        for i, shape in enumerate(shapes):
            w = onp.random.uniform(-1, 1, shape).astype(dtype)
            g = onp.random.uniform(-1, 1, shape).astype(dtype)
            w1.append(array(w)); w2.append(array(w.copy()))
            g1.append(array(g)); g2.append(array(g.copy()))
            s1.append(opt1.create_state_multi_precision(i, w1[-1]))
            s2.append(opt2.create_state_multi_precision(i, w2[-1]))
        for i in range(len(shapes)):
            opt1.update_multi_precision(i, w1[i], g1[i], s1[i])
            opt2.update_multi_precision(i, w2[i], g2[i], s2[i])
            compare_ndarray_tuple(tuple(s1[i]) if isinstance(s1[i], tuple)
                                  else (s1[i],) if s1[i] is not None else (),
                                  tuple(s2[i]) if isinstance(s2[i], tuple)
                                  else (s2[i],) if s2[i] is not None else (),
                                  rtol, atol)
            assert_almost_equal(w1[i], w2[i], rtol=rtol, atol=atol)


def collapse_sum_like(a, shape):
    """Sum-reduce `a` down to `shape` following broadcast rules (ref:
    test_utils.py collapse_sum_like)."""
    a = _as_np(a)
    assert len(a.shape) >= len(shape)
    if onp.prod(shape) == 0 or a.size == 0:
        return onp.zeros(shape, a.dtype)
    axes = list(range(len(a.shape) - len(shape)))
    for i, s in enumerate(shape):
        if s != a.shape[len(a.shape) - len(shape) + i]:
            assert s == 1
            axes.append(len(a.shape) - len(shape) + i)
    return a.sum(axis=tuple(axes), keepdims=True).reshape(shape) \
        if axes else a.reshape(shape)


def check_gluon_hybridize_consistency(net_builder, data_l, numpy_func=None,
                                      test_grad=True, rtol=1e-4, atol=1e-5):
    """Eager vs hybridized forward (and backward) parity for a Gluon block
    (ref: test_utils.py check_gluon_hybridize_consistency)."""
    saved_out_np = None
    saved_grad_np_l = None
    for hybridize in (False, True):
        net = net_builder()
        net.initialize()
        if hybridize:
            net.hybridize()
        in_data_l = [array(_as_np(x)) for x in data_l]
        if test_grad:
            for x in in_data_l:
                x.attach_grad()
            with autograd.record():
                out = net(*in_data_l)
            out.backward()
            grad_np_l = [x.grad.asnumpy() for x in in_data_l]
        else:
            out = net(*in_data_l)
            grad_np_l = None
        out_np = out.asnumpy()
        if saved_out_np is None:
            saved_out_np = out_np
            saved_grad_np_l = grad_np_l
        else:
            assert_almost_equal(out_np, saved_out_np, rtol=rtol, atol=atol)
            if test_grad:
                for g, sg in zip(grad_np_l, saved_grad_np_l):
                    assert_almost_equal(g, sg, rtol=rtol, atol=atol)
    if numpy_func is not None:
        assert_almost_equal(saved_out_np,
                            numpy_func(*[_as_np(x) for x in data_l]),
                            rtol=rtol, atol=atol)


def new_sym_matrix_with_real_eigvals_nd(n):
    """Random symmetric matrix batch with real eigenvalues (ref:
    test_utils.py new_sym_matrix_with_real_eigvals_nd)."""
    a = onp.random.randn(n, n).astype(onp.float32)
    return (a + a.T) / 2


def new_matrix_with_real_eigvals_2d(n):
    """Random matrix with real eigenvalues: D + small symmetric noise via
    similarity transform (ref: test_utils.py)."""
    d = onp.diag(onp.random.uniform(1.0, 2.0, n))
    q, _ = onp.linalg.qr(onp.random.randn(n, n))
    return (q @ d @ q.T).astype(onp.float32)
