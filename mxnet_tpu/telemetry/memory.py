"""Memory observability: HBM/host watermarks, residency pools, OOM forensics.

The time half of the attribution pipeline (PR 6 spans + PR 12 fleet
snapshots) says nothing about the single most common way a TPU training
run dies: RESOURCE_EXHAUSTED. The ZeRO "8.00x state shrink" numbers are
analytic byte-counting, the remat-policy sweep has no way to measure
the headroom it intends to spend, and an OOM today is a raw backend
error with zero attribution. This module is the memory half:

- **Watermarks** — per-step live/peak device-memory sampling into a
  bounded ring. ``jax device.memory_stats()`` where the backend exposes
  it (TPU/GPU), with a deterministic **fallback** that sums the
  per-device bytes of every *tracked* live array — params, fp32
  masters, optimizer moments, compression residuals, device-prefetch
  lease buffers — registered as named **pools** by their owners
  (``ShardedTrainStep``, ``gluon.Trainer``, ``DevicePrefetchIter``).
  Host RSS rides along. Samples export as ``mxnet_tpu_memory_*``
  gauges, land in the flight-recorder step records, and piggyback on
  the PR 12 fleet snapshots so the coordinator can flag per-rank HBM
  imbalance.
- **Leak detection** — ``MXTPU_MEMORY_LEAK_STEPS`` consecutive steps of
  monotonic live-bytes growth past ``MXTPU_MEMORY_LEAK_BYTES`` latch a
  ``memory.leak_suspected`` flight note (cleared when growth stops).
- **OOM forensics** — ``oom_guard(site)`` wraps the dispatch sites that
  actually allocate (step dispatch, h2d batch/param placement,
  checkpoint-restore re-place). A RESOURCE_EXHAUSTED caught there dumps
  ONE atomic JSON post-mortem: the watermark ring, the registered
  step's ``memory_analysis()`` bucket table, the top live arrays by
  bytes (shape/dtype/sharding), the active ZeRO/compression config and
  a computed "what would fit" hint — then re-raises. The deterministic
  fault site ``alloc.oom`` injects a synthetic RESOURCE_EXHAUSTED
  through the same guard, so the drill needs no real 16 GB chip
  (``resilience.drill.run_oom_drill``).

Armed with ``MXTPU_MEMORY=1`` (or ``memory.enable()``); sampling
cadence is ``MXTPU_MEMORY_EVERY`` steps. Disarmed, every step-path hook
costs one dict check and allocates nothing (the same discipline as
``telemetry.trace``); the OOM guard is always armed — catching a fatal
allocator error costs nothing until it fires.
"""
from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time as _time
import weakref

from ..base import telem_flags as _telem

__all__ = [
    'enable', 'disable', 'enabled', 'clear',
    'register_pool', 'register_provider', 'pool_nbytes', 'entry_nbytes',
    'pools', 'live_bytes', 'pool_bytes_by_name',
    'device_memory_stats', 'host_rss_bytes',
    'on_step', 'sample', 'step_fields', 'snapshot_fields',
    'health_fields', 'watermarks', 'peak_bytes', 'leak_state',
    'is_oom_error', 'oom_guard', 'dump_oom', 'default_oom_path',
    'validate_oom_dump', 'set_analysis_provider', 'top_arrays',
]

_state = {'on': False}

# sampling/ring/leak configuration, resolved lazily from config (tests
# override via clear(ring=...) or the module attrs)
_cfg = {'ring': None, 'every': None, 'leak_steps': None,
        'leak_bytes': None}

# pool registry: key -> (pool name, provider, weakref-to-owner|None).
# A provider is either a zero-arg callable returning {array_name: entry}
# (entry = array-like or plain byte count) or an OWNER object exposing
# .memory_pools() -> {pool: {array_name: entry}}. Owner-keyed entries
# auto-retire when the owner is garbage collected, so a rebuilt step
# never double-counts its predecessor's arrays.
# RLock: sampling runs on the step thread and the registry is readable
# from crash-time dump paths (same signal-safety rationale as
# flight._recorder_lock).
_pools_lock = threading.RLock()
_pools = {}

_ring_lock = threading.RLock()
_ring = None                  # collections.deque of sample records
_last = {'fields': None}      # newest sample's compact per-step fields
_peak = {'device': 0, 'stats_peak': None}
_every_count = [0]
_leak = {'prev': None, 'streak': 0, 'growth': 0, 'latched': False,
         'latched_step': None}
_analysis = {'ref': None}     # weakref to the newest memory_analysis owner

OOM_SCHEMA = 'mxtpu_oom_v1'


def enable():
    _state['on'] = True


def disable():
    _state['on'] = False


def enabled() -> bool:
    return _state['on']


def _ring_capacity():
    if _cfg['ring'] is None:
        from .. import config as _config
        _cfg['ring'] = max(4, int(_config.get('MXTPU_MEMORY_RING')))
    return _cfg['ring']


def _every():
    if _cfg['every'] is None:
        from .. import config as _config
        _cfg['every'] = max(1, int(_config.get('MXTPU_MEMORY_EVERY')))
    return _cfg['every']


def _leak_cfg():
    if _cfg['leak_steps'] is None:
        from .. import config as _config
        _cfg['leak_steps'] = max(2, int(
            _config.get('MXTPU_MEMORY_LEAK_STEPS')))
        _cfg['leak_bytes'] = max(1, int(
            _config.get('MXTPU_MEMORY_LEAK_BYTES')))
    return _cfg['leak_steps'], _cfg['leak_bytes']


def clear(ring=None, every=None, leak_steps=None, leak_bytes=None,
          pools=False):
    """Drop every sample and latched state. Optional overrides pin the
    ring capacity / cadence / leak thresholds for rings created after
    this call (None restores the config defaults).

    Pool/analysis registrations SURVIVE by default: owners register
    exactly once (a step at first build, a trainer at kvstore init),
    so a mid-run reset — bench's ``_memory_report``, the oom drill —
    must not zero the rest of the run's residency telemetry. They are
    weakref'd and self-cleaning; ``pools=True`` (test fixtures) wipes
    them too."""
    global _ring
    with _ring_lock:
        _ring = None
        _cfg['ring'] = ring
        _cfg['every'] = every
        _cfg['leak_steps'] = leak_steps
        _cfg['leak_bytes'] = leak_bytes
        _last['fields'] = None
        _peak['device'] = 0
        _peak['stats_peak'] = None
        _every_count[0] = 0
        _leak.update(prev=None, streak=0, growth=0, latched=False,
                     latched_step=None)
    if pools:
        with _pools_lock:
            _pools.clear()
            _analysis['ref'] = None


# ---------------------------------------------------------------------------
# residency pools (the deterministic fallback's array registry)
# ---------------------------------------------------------------------------

def entry_nbytes(x):
    """Bytes ONE device physically holds for a tracked entry: the local
    shard for a sharded global array, the full buffer for replicated or
    host arrays, the value itself for plain byte counts — the same
    per-device unit as ``parallel.step.device_nbytes`` (kept local so
    the telemetry package never imports jax)."""
    if isinstance(x, (int, float)):
        return int(x)
    try:
        shards = getattr(x, 'addressable_shards', None)
        if shards:
            return int(shards[0].data.nbytes)
        nb = getattr(x, 'nbytes', None)
        if nb is not None:
            return int(nb)
    except Exception:
        # a DELETED buffer — the compiled step invalidates its donated
        # inputs (params/masters/moments/residuals, exactly the tracked
        # pools) before a real RESOURCE_EXHAUSTED surfaces, and jax
        # raises RuntimeError on any access — holds no device bytes;
        # the OOM dump must survive it, not die inside its own
        # accounting
        return 0
    return 0


def pool_nbytes(pool):
    """Per-device bytes of one ``{array_name: entry}`` pool dict."""
    return sum(entry_nbytes(v) for v in (pool or {}).values())


def register_pool(name, provider, owner=None):
    """Register a named pool of live arrays for the fallback watermark.
    ``provider()`` returns ``{array_name: array-or-bytes}``. With an
    ``owner``, the registration auto-retires when the owner is garbage
    collected (a rebuilt step must not double-count its predecessor)."""
    key = name if owner is None else (name, id(owner))
    ref = weakref.ref(owner) if owner is not None else None
    with _pools_lock:
        _pools[key] = (name, provider, ref)
    return key


def register_provider(owner):
    """Register an object exposing ``memory_pools() ->
    {pool: {array_name: entry}}`` (ShardedTrainStep, Trainer,
    DevicePrefetchIter). Weakly referenced; re-registration of the same
    object is idempotent."""
    key = ('provider', id(owner))
    ref = weakref.ref(owner)
    with _pools_lock:
        _pools[key] = (None, None, ref)
    return key


def unregister(key):
    with _pools_lock:
        _pools.pop(key, None)


def pools():
    """Merged live pools: ``{pool: {array_name: entry}}`` across every
    registered provider (dead owners pruned)."""
    with _pools_lock:
        items = list(_pools.items())
    merged = {}
    dead = []
    for key, (name, provider, ref) in items:
        owner = None
        if ref is not None:
            owner = ref()
            if owner is None:
                dead.append(key)
                continue
        try:
            if name is None:                    # .memory_pools() provider
                groups = owner.memory_pools() or {}
            else:
                groups = {name: provider() or {}}
        except Exception:
            continue                            # never break sampling
        for pool, entries in groups.items():
            dst = merged.setdefault(pool, {})
            for aname, entry in (entries or {}).items():
                dst[aname] = entry
    if dead:
        with _pools_lock:
            for key in dead:
                _pools.pop(key, None)
    return merged


def live_bytes():
    """(total per-device bytes, {pool: bytes}) over every live tracked
    array — the deterministic fallback watermark."""
    by_pool = {pool: pool_nbytes(entries)
               for pool, entries in pools().items()}
    return sum(by_pool.values()), by_pool


def pool_bytes_by_name(name):
    """Per-device bytes of one named pool (0 when absent)."""
    return pool_nbytes(pools().get(name))


def top_arrays(limit=16):
    """The largest tracked live arrays, descending:
    ``[{'pool', 'name', 'nbytes', 'shape', 'dtype', 'sharding'}]`` —
    what the OOM post-mortem names as prime suspects."""
    rows = []
    for pool, entries in pools().items():
        for aname, entry in entries.items():
            nb = entry_nbytes(entry)
            if nb <= 0:
                continue
            row = {'pool': pool, 'name': aname, 'nbytes': nb}
            try:
                shape = getattr(entry, 'shape', None)
                if shape is not None:
                    row['shape'] = [int(s) for s in shape]
                dt = getattr(entry, 'dtype', None)
                if dt is not None:
                    row['dtype'] = str(dt)
                sh = getattr(entry, 'sharding', None)
                if sh is not None:
                    row['sharding'] = str(sh)
            except Exception:
                pass                   # metadata of a deleted buffer
            rows.append(row)
    rows.sort(key=lambda r: (-r['nbytes'], r['pool'], r['name']))
    return rows[:int(limit)]


# ---------------------------------------------------------------------------
# device / host sources
# ---------------------------------------------------------------------------

def device_memory_stats(device=None):
    """{'bytes_in_use', 'peak_bytes_in_use', ...} from the backend's
    own allocator (local device 0 by default), or None where the
    backend exposes nothing (jax CPU) — the fallback pools then carry
    the watermark."""
    try:
        if device is None:
            import jax
            device = jax.local_devices()[0]
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats or stats.get('bytes_in_use') is None:
        return None
    return dict(stats)


def host_rss_bytes():
    """Current resident set size of this process (bytes); peak RSS as
    the fallback where /proc is unavailable."""
    try:
        with open('/proc/self/statm') as f:
            return int(f.read().split()[1]) * os.sysconf('SC_PAGE_SIZE')
    except Exception:
        try:
            import resource
            return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss \
                * 1024
        except Exception:
            return 0


# ---------------------------------------------------------------------------
# sampling (the step-path hook)
# ---------------------------------------------------------------------------

def on_step(step=None):
    """Per-step hook on the dispatch paths. Disarmed: one dict check,
    no allocation. Armed: every ``MXTPU_MEMORY_EVERY``-th call records
    one watermark sample (gauges + ring + leak detector) and refreshes
    the compact fields the flight recorder attaches to its step
    record."""
    if not _state['on']:
        return None
    _every_count[0] += 1
    if _every_count[0] % _every():
        return None
    return sample(step=step)


def sample(step=None):
    """Record one watermark sample now; returns the ring record."""
    stats = device_memory_stats()
    fb_total, by_pool = live_bytes()
    if stats is not None:
        live = int(stats['bytes_in_use'])
        source = 'memory_stats'
    else:
        live = fb_total
        source = 'fallback'
    rec = {'time': round(_time.time(), 3), 'source': source,
           'device_bytes': live, 'fallback_bytes': fb_total,
           'host_rss_bytes': host_rss_bytes()}
    if step is not None:
        rec['step'] = int(step)
    if by_pool:
        rec['pools'] = by_pool
    with _ring_lock:
        global _ring
        if _ring is None:
            _ring = collections.deque(maxlen=_ring_capacity())
        if stats is not None and stats.get('peak_bytes_in_use'):
            _peak['stats_peak'] = max(_peak['stats_peak'] or 0,
                                      int(stats['peak_bytes_in_use']))
        _peak['device'] = max(_peak['device'], live)
        rec['peak_bytes'] = peak_bytes()
        _ring.append(rec)
        # the compact per-step fields flight.record_step attaches: a
        # fresh small dict per SAMPLE (never per step — the read path
        # hands out the same object until the next sample)
        _last['fields'] = {'device_bytes': live,
                           'peak_bytes': rec['peak_bytes'],
                           'host_rss_bytes': rec['host_rss_bytes'],
                           'source': source}
    _leak_observe(step, live)
    if _telem['on']:
        from . import metrics as _metrics
        _metrics.set_gauge('mxnet_tpu_memory_device_bytes', live,
                           source=source)
        _metrics.set_gauge('mxnet_tpu_memory_device_peak_bytes',
                           rec['peak_bytes'], source=source)
        _metrics.set_gauge('mxnet_tpu_memory_host_rss_bytes',
                           rec['host_rss_bytes'])
        for pool, nb in by_pool.items():
            _metrics.set_gauge('mxnet_tpu_memory_pool_bytes', nb,
                               pool=pool)
        _metrics.inc('mxnet_tpu_memory_samples_total')
    return rec


def step_fields():
    """The newest sample's compact fields for the flight-recorder step
    record, or None while disarmed / before the first sample. One dict
    check disarmed; the armed path returns the prebuilt dict (no
    per-step allocation on the recording path)."""
    if not _state['on']:
        return None
    return _last['fields']


def snapshot_fields():
    """The fleet-snapshot payload: ``{'live', 'peak', 'rss'}`` bytes or
    None while disarmed / unsampled — a few tens of JSON bytes on the
    heartbeat."""
    f = step_fields()
    if f is None:
        return None
    return {'live': f['device_bytes'], 'peak': f['peak_bytes'],
            'rss': f['host_rss_bytes']}


def health_fields():
    """The /healthz memory document — computed on demand (cold path),
    so a fleet operator sees pressure even on a run that never armed
    MXTPU_MEMORY."""
    stats = device_memory_stats()
    fb_total, by_pool = live_bytes()
    out = {'live_bytes': int(stats['bytes_in_use']) if stats is not None
           else fb_total,
           'source': 'memory_stats' if stats is not None else 'fallback',
           'tracked_bytes': fb_total,
           'host_rss_bytes': host_rss_bytes()}
    pk = peak_bytes()
    out['peak_bytes'] = max(pk, out['live_bytes'])
    if stats is not None and stats.get('bytes_limit'):
        out['limit_bytes'] = int(stats['bytes_limit'])
    if by_pool:
        out['pools'] = by_pool
    if _leak['latched']:
        out['leak_suspected'] = True
    return out


def watermarks():
    """Snapshot of the bounded watermark ring (oldest first)."""
    with _ring_lock:
        return [dict(r) for r in (_ring or ())]


def peak_bytes():
    """The high-water mark so far: the allocator's own peak where
    exposed, else the max fallback sample (0 before any sample)."""
    with _ring_lock:
        if _peak['stats_peak'] is not None:
            return max(_peak['stats_peak'], _peak['device'])
        return _peak['device']


# ---------------------------------------------------------------------------
# leak detector
# ---------------------------------------------------------------------------

def _leak_observe(step, live):
    """Step-over-step growth detector: ``leak_steps`` consecutive
    samples of monotonic growth totalling >= ``leak_bytes`` latch ONE
    ``memory.leak_suspected`` flight note; a non-growing sample clears
    the latch (so a later, separate leak fires again)."""
    leak_steps, leak_bytes = _leak_cfg()
    prev = _leak['prev']
    _leak['prev'] = live
    if prev is None:
        return
    if live > prev:
        _leak['streak'] += 1
        _leak['growth'] += live - prev
    else:
        _leak['streak'] = 0
        _leak['growth'] = 0
        if _leak['latched']:
            _leak['latched'] = False
            _leak['latched_step'] = None
        return
    if _leak['streak'] >= leak_steps and _leak['growth'] >= leak_bytes \
            and not _leak['latched']:
        _leak['latched'] = True
        _leak['latched_step'] = step
        from . import flight as _flight
        _flight.note('memory.leak_suspected',
                     step=step, growth_bytes=int(_leak['growth']),
                     steps=int(_leak['streak']), live_bytes=int(live))
        if _telem['on']:
            from . import metrics as _metrics
            _metrics.inc('mxnet_tpu_memory_leaks_suspected_total')


def leak_state():
    """{'latched', 'streak', 'growth_bytes', 'latched_step'} — the
    detector's current view (tests + the OOM dump)."""
    return {'latched': _leak['latched'], 'streak': _leak['streak'],
            'growth_bytes': _leak['growth'],
            'latched_step': _leak['latched_step']}


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------

def set_analysis_provider(fn, owner=None):
    """Register the newest step's ``memory_analysis`` callable so the
    OOM post-mortem can embed the bucket table. Weakly referenced (a
    bound method is held as a WeakMethod): a dead step must never be
    pinned by its own observability hook."""
    try:
        _analysis['ref'] = weakref.WeakMethod(fn)
        return
    except TypeError:
        pass                              # plain function / lambda
    if owner is not None:
        ref = weakref.ref(owner)
        _analysis['ref'] = lambda: (fn if ref() is not None else None)
    else:
        _analysis['ref'] = lambda: fn


def _analysis_fn():
    getter = _analysis['ref']
    return getter() if getter is not None else None


def is_oom_error(e):
    """Is this exception a device-allocator exhaustion? Matches the
    backend's RESOURCE_EXHAUSTED surface (jaxlib XlaRuntimeError text)
    and the injected ``alloc.oom`` fault, never ordinary errors."""
    try:
        from ..resilience import faults as _faults
        if isinstance(e, _faults.InjectedFault) \
                and getattr(e, 'site', None) == 'alloc.oom':
            return True
    except Exception:
        pass
    msg = str(e)
    return ('RESOURCE_EXHAUSTED' in msg or 'Resource exhausted' in msg
            or 'Out of memory' in msg or 'out of memory' in msg)


class _OomGuard:
    """Reusable per-site context manager (no allocation per step): fires
    the deterministic ``alloc.oom`` fault on entry and, when the guarded
    block dies of RESOURCE_EXHAUSTED (real or injected), writes the
    forensics dump before re-raising."""

    __slots__ = ('site',)

    def __init__(self, site):
        self.site = site

    def __enter__(self):
        from ..resilience import faults as _faults
        try:
            _faults.fire('alloc.oom')
        except _faults.InjectedFault as e:
            # an injected raise surfaces HERE (before the body runs),
            # where __exit__ never sees it — dump and re-raise so the
            # drill leaves exactly the post-mortem a real OOM would
            if is_oom_error(e):
                try:
                    dump_oom(self.site, e)
                except Exception:
                    pass
            raise
        return self

    def __exit__(self, etype, e, tb):
        if e is not None and is_oom_error(e):
            try:
                dump_oom(self.site, e)
            except Exception:
                pass                    # forensics must never mask the OOM
        return False


_guards = {}


def oom_guard(site):
    """The shared guard for one dispatch site — always armed (the cost
    until an OOM fires is one dict check from the fault registry's
    disarmed fast path)."""
    g = _guards.get(site)
    if g is None:
        g = _guards[site] = _OomGuard(site)
    return g


def default_oom_path():
    """Where the forensics dump lands: the PR 12 ``MXTPU_FLIGHT_DIR``
    convention (default: the system temp directory, never the CWD),
    ``mxtpu_oom-<pid>.json`` — pid-suffixed so multi-process ranks never
    clobber each other's post-mortem."""
    from .. import config as _config
    d = _config.get('MXTPU_FLIGHT_DIR')
    if not d:
        import tempfile
        d = tempfile.gettempdir()
    return os.path.join(d, f'mxtpu_oom-{os.getpid()}.json')


def _fit_hints(analysis):
    """The "what would fit" computation: projected per-device savings
    from the knobs the stack already ships, ranked by bytes freed."""
    hints = []
    if not analysis:
        return hints
    buckets = analysis.get('buckets_bytes') or {}
    dp = int(analysis.get('dp') or 1)
    stage = int(analysis.get('zero_stage') or 0)
    params = int(buckets.get('params') or 0)
    state = int(buckets.get('optimizer_state') or 0)
    temp = int(buckets.get('activations_temp') or 0)
    if dp > 1 and stage == 0 and state:
        hints.append({
            'action': 'MXTPU_ZERO=1',
            'projected_savings_bytes': int(state * (1 - 1 / dp)),
            'detail': f'shard fp32 masters + moments 1/{dp} over dp'})
    if dp > 1 and stage < 3 and params:
        hints.append({
            'action': 'MXTPU_ZERO=3',
            'projected_savings_bytes': int(params * (1 - 1 / dp)),
            'detail': f'shard persistent params 1/{dp}; per-layer '
                      f'all-gather on use (adds regather wire bytes)'})
    if temp:
        hints.append({
            'action': 'remat',
            'projected_savings_bytes': temp,
            'detail': 'activations-temp bucket is reclaimable via '
                      'jax.checkpoint remat policies at recompute cost'})
    comp = analysis.get('compression')
    res = int(buckets.get('residuals') or 0)
    if comp and res:
        hints.append({
            'action': 'compression off',
            'projected_savings_bytes': res,
            'detail': f'drop the {comp} error-feedback residual state'})
    hints.sort(key=lambda h: -h['projected_savings_bytes'])
    return hints


def dump_oom(site, error, path=None):
    """Write the OOM post-mortem JSON atomically; returns the path.
    Reads only tracked host-side state — never a device sync (the
    device just refused an allocation; asking it for more is how a
    post-mortem hangs)."""
    stats = device_memory_stats()
    fb_total, by_pool = live_bytes()
    analysis = None
    fn = _analysis_fn()
    if fn is not None:
        try:
            analysis = fn()
        except Exception:
            analysis = None
    from .. import config as _config
    doc = {
        'schema': OOM_SCHEMA,
        'pid': os.getpid(),
        'time': round(_time.time(), 3),
        'site': site,
        'error_type': type(error).__name__,
        'error': str(error)[:2000],
        'device_bytes': int(stats['bytes_in_use']) if stats is not None
        else fb_total,
        'source': 'memory_stats' if stats is not None else 'fallback',
        'peak_bytes': max(peak_bytes(), fb_total),
        'host_rss_bytes': host_rss_bytes(),
        'pools_bytes': by_pool,
        'top_arrays': top_arrays(),
        'watermarks': watermarks(),
        'memory_analysis': analysis,
        'leak': leak_state(),
        'config': {
            'MXTPU_ZERO': str(_config.get('MXTPU_ZERO')),
            'MXTPU_COMPRESSION': _config.get('MXTPU_COMPRESSION'),
            'MXTPU_MEMORY': bool(_state['on']),
        },
        'hints': _fit_hints(analysis),
    }
    if stats is not None and stats.get('bytes_limit'):
        doc['limit_bytes'] = int(stats['bytes_limit'])
    if path is None:
        path = default_oom_path()
    d = os.path.dirname(path)
    if d:
        # a fresh MXTPU_FLIGHT_DIR must not silently lose the one
        # artifact that explains the crash
        os.makedirs(d, exist_ok=True)
    from ..serialization import atomic_write_file
    atomic_write_file(path, json.dumps(doc, default=str).encode())
    from . import flight as _flight
    _flight.note('memory.oom', site=site, path=path,
                 device_bytes=doc['device_bytes'],
                 top=doc['top_arrays'][0]['name']
                 if doc['top_arrays'] else None)
    if _telem['on']:
        from . import metrics as _metrics
        _metrics.inc('mxnet_tpu_memory_oom_dumps_total', site=site)
    return path


_REQUIRED_OOM_KEYS = (
    'schema', 'pid', 'time', 'site', 'error', 'error_type',
    'device_bytes', 'source', 'peak_bytes', 'host_rss_bytes',
    'pools_bytes', 'top_arrays', 'watermarks', 'config', 'hints',
)


def validate_oom_dump(doc):
    """Schema check of an OOM post-mortem document; returns a list of
    problems (empty = valid). The drill and tests gate on this, so the
    dump format cannot drift silently."""
    problems = []
    if not isinstance(doc, dict):
        return ['not a JSON object']
    for k in _REQUIRED_OOM_KEYS:
        if k not in doc:
            problems.append(f'missing key {k!r}')
    if doc.get('schema') != OOM_SCHEMA:
        problems.append(f"schema {doc.get('schema')!r} != {OOM_SCHEMA!r}")
    if not isinstance(doc.get('watermarks'), list):
        problems.append('watermarks is not a list')
    tops = doc.get('top_arrays')
    if not isinstance(tops, list):
        problems.append('top_arrays is not a list')
    else:
        prev = None
        for i, row in enumerate(tops):
            for k in ('pool', 'name', 'nbytes'):
                if k not in row:
                    problems.append(f'top_arrays[{i}] missing {k!r}')
            nb = row.get('nbytes')
            if prev is not None and nb is not None and nb > prev:
                problems.append('top_arrays not sorted by nbytes desc')
            prev = nb if nb is not None else prev
    for h in doc.get('hints') or []:
        if 'action' not in h or 'projected_savings_bytes' not in h:
            problems.append(f'malformed hint {h!r}')
    if not isinstance(doc.get('pools_bytes'), dict):
        problems.append('pools_bytes is not a dict')
    return problems


# config gate (read at import; declared in config.py)
from .. import config as _config_mod  # noqa: E402

if _config_mod.get('MXTPU_MEMORY'):
    enable()
