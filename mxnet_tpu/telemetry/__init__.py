"""Telemetry package: metrics registry + span tracing + attribution.

Grown from the original single-module registry (PR 1) into three
cooperating layers:

- ``telemetry.metrics`` — the process-global counter/gauge/histogram
  registry, Prometheus/JSON/chrome-'C' exports and the recompile
  detector. Its entire API is re-exported here unchanged, so every
  existing ``telemetry.inc(...)`` / ``telemetry.report()`` call site
  (and ``MXNET_TPU_TELEMETRY=1``) keeps working.
- ``telemetry.trace`` — nested ``span()`` scopes over the step
  lifecycle, per-thread lock-free rings, chrome-trace B/E export
  (``MXTPU_TRACE=1``).
- ``telemetry.flight`` — the crash-time flight recorder: last-N-steps
  span summaries + loss + guard flags + fault events, dumped as one
  atomic JSON on stall/rollback/exit.
- ``telemetry.attribution`` — joins measured spans with XLA
  cost_analysis into the per-step input/h2d/compute/collective/
  host-sync breakdown bench.py and tools/tune_bert_step.py report.
- ``telemetry.memory`` — the memory half of attribution
  (``MXTPU_MEMORY``): HBM/host watermark sampling (device
  ``memory_stats`` or the deterministic tracked-array fallback) into a
  bounded ring + ``mxnet_tpu_memory_*`` gauges, a step-over-step leak
  detector, and the always-armed OOM forensics guard that dumps one
  atomic post-mortem (watermarks, bucket table, top live arrays,
  what-would-fit hints) when RESOURCE_EXHAUSTED hits a dispatch site.
- ``telemetry.fleet`` — cross-rank aggregation: per-step snapshots
  piggybacked on membership heartbeats, merged into a coordinator
  fleet view with per-rank skew, clock-offset estimation for trace
  stitching, and streaming straggler/regression/loss-spike/imbalance
  detectors.
- ``telemetry.server`` — the per-process /metrics + /healthz +
  /flight HTTP endpoint (``MXTPU_METRICS_PORT``, off by default).
"""
from .metrics import *  # noqa: F401,F403  (the PR-1 registry API, unchanged)
from .metrics import (  # noqa: F401  (non-__all__ names used by tests/tools)
    DEFAULT_BUCKETS, Metric, _label_key, _metrics, _snapshot,
)
from .metrics import __all__ as _metrics_all
from . import trace          # noqa: F401
from . import memory         # noqa: F401
from . import compile        # noqa: F401  (shadows the builtin only here)
from . import flight         # noqa: F401
from . import attribution    # noqa: F401
from . import fleet          # noqa: F401
from . import server         # noqa: F401

__all__ = list(_metrics_all) + ['trace', 'memory', 'compile', 'flight',
                                'attribution', 'fleet', 'server']
