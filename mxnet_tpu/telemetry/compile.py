"""Compilation observability: compile ledger, recompile forensics, and
persistent-cache telemetry.

Every jit/pjit build site (the ShardedTrainStep step program, gluon
CachedOp per block, the Trainer fused update, the io normalize program)
wraps its build in a :func:`begin`/:func:`end` pair.  While the pair is
open, ``jax.monitoring`` duration events attribute the compile's phase
wall time — ``compile.trace`` (jaxpr trace), ``compile.lower`` (MLIR
lowering), ``compile.backend`` (XLA backend compile) — to that site, and
the phases land as chrome complete events in the PR 6 trace rings.  On
:func:`end` a structured ledger entry (per-arg shape/dtype/sharding/
donation signature + flag knobs + phase seconds, keyed by a signature
fingerprint and the device kind) is appended to a bounded in-memory ring
and, when ``MXTPU_COMPILE_LEDGER`` names a path, to an on-disk JSONL
ledger written with the MXTPU_FLIGHT_DIR atomic-write convention (read,
append, bound, ``os.replace``) — a kill mid-write leaves the previous
ledger, never a truncated hybrid.

Recompile forensics: a second compile at a logically-same site diffs the
new signature against the ledger's last entry and names the churning
axis ("arg 3 `data`: shape (32, 128)→(32, 131)") in the
RecompileWarning, the ``compile.recompiled`` flight note, and the
``mxnet_tpu_compile_churn_axes`` metric.

Persistent cache: ``MXTPU_COMPILE_CACHE_DIR`` wires jax's compilation
cache through config; hit/miss/saved-seconds are counted from jax's own
cache events, with saved-seconds additionally estimated from the
ledger's recorded compile time for the hit fingerprint.

Disarmed (the default), every entry point is a single flag/dict check
and allocates nothing.  Validate a ledger file with
``tools/check_compile_ledger.py``.
"""

import collections
import hashlib
import json
import os
import tempfile
import threading
import time as _time

from . import metrics as _metrics
from . import trace as _trace
from .. import config as _config_mod

__all__ = [
    'enable', 'disable', 'enabled', 'clear',
    'begin', 'set_signature', 'end', 'abort', 'watching',
    'signature', 'arg_sig', 'array_sig', 'fingerprint', 'diff_signatures',
    'ledger', 'ledger_path', 'default_ledger_path',
    'in_flight', 'step_fields', 'snapshot_fields', 'health_fields',
    'persistent_cache_stats', 'enable_persistent_cache',
    'validate_ledger_entry', 'validate_ledger',
    'LEDGER_SCHEMA',
]

LEDGER_SCHEMA = 'mxtpu_compile_ledger_v1'

# required keys of one ledger entry (validate_ledger_entry enforces)
LEDGER_REQUIRED = ('schema', 'time', 'pid', 'site', 'nth', 'fingerprint',
                   'device_kind', 'signature', 'seconds')

_DEFAULT_RING = 256
_LEDGER_MAX_LINES = 512     # on-disk bound: keep the newest entries

_UNSET = object()

_state = {'on': False}
_lock = threading.RLock()
_cfg = {'ring': None, 'ledger': _UNSET, 'cache_dir': _UNSET}

_ring = collections.deque()              # ledger entries, oldest first
_sites = {}          # site -> {'n', 'signature', 'fingerprint'}
_inflight = {}       # tid -> {'site', 'phase', 'since', 'phase_since'}
_tls = threading.local()                 # .ctx: the open build context
_totals = {'n': 0, 'seconds': 0.0}
_last = {'fields': None, 'fresh': False}
_fp_seconds = {}     # fingerprint -> last recorded total compile seconds
_pcache = {'hits': 0, 'misses': 0, 'requests': 0,
           'saved': 0.0, 'saved_est': 0.0}
_hooks = {'armed': False}
_cache_state = {'applied': None}
_device = {'kind': None, 'backend': None}
_seed = {'done': False}
_ledger_err = {'warned': False}

# inferred in-flight phase after each jax.monitoring duration event: the
# event marks the END of its phase, so what runs NEXT is what a stuck
# rank is stuck in.
_EVT_PHASE = {
    '/jax/core/compile/jaxpr_trace_duration': 'trace',
    '/jax/core/compile/jaxpr_to_mlir_module_duration': 'lower',
    '/jax/core/compile/backend_compile_duration': 'backend',
}
_NEXT_PHASE = {'trace': 'lower', 'lower': 'backend', 'backend': 'done'}


# ---------------------------------------------------------------------------
# enable / configuration
# ---------------------------------------------------------------------------

def enable():
    _state['on'] = True


def disable():
    _state['on'] = False


def enabled() -> bool:
    return _state['on']


def clear(ring=None, ledger=_UNSET, cache_dir=_UNSET):
    """Drop every sample/site/counter and (optionally) override the ring
    depth, the ledger path ('' disables disk, None restores the
    MXTPU_COMPILE_LEDGER default) and the persistent-cache dir."""
    with _lock:
        _ring.clear()
        _sites.clear()
        _inflight.clear()
        _fp_seconds.clear()
        _pcache.update(hits=0, misses=0, requests=0, saved=0.0,
                       saved_est=0.0)
        _totals.update(n=0, seconds=0.0)
        _last['fields'] = None
        _last['fresh'] = False
        _seed['done'] = False
        _cfg['ring'] = ring
        if ledger is not _UNSET:
            _cfg['ledger'] = ledger
        if cache_dir is not _UNSET:
            _cfg['cache_dir'] = cache_dir
            # keep _cache_state['applied'] — _ensure_persistent_cache
            # compares it against the new dir to re-point (or, for '',
            # UN-point) jax's cache config
    if cache_dir is not _UNSET:
        _ensure_persistent_cache()


def _ring_cap() -> int:
    n = _cfg['ring']
    return _DEFAULT_RING if n is None else max(1, int(n))


def default_ledger_path() -> str:
    d = _config_mod.get('MXTPU_FLIGHT_DIR') or tempfile.gettempdir()
    return os.path.join(d, f'mxtpu_compile_ledger-{os.getpid()}.jsonl')


def ledger_path():
    """The on-disk JSONL ledger path, or None when disk logging is off."""
    if _cfg['ledger'] is not _UNSET:
        return _cfg['ledger'] or None
    raw = _config_mod.get('MXTPU_COMPILE_LEDGER')
    if not raw:
        return None
    if raw.strip().lower() in ('1', 'on', 'true', 'yes'):
        return default_ledger_path()
    return raw


def ledger():
    """Snapshot of the in-memory ledger ring (oldest first)."""
    with _lock:
        return [dict(e) for e in _ring]


# ---------------------------------------------------------------------------
# persistent compile cache
# ---------------------------------------------------------------------------

def _cache_dir():
    if _cfg['cache_dir'] is not _UNSET:
        return _cfg['cache_dir'] or ''
    return _config_mod.get('MXTPU_COMPILE_CACHE_DIR') or ''


def enable_persistent_cache(path):
    """Point jax's persistent compilation cache at `path` (overrides
    MXTPU_COMPILE_CACHE_DIR for this process) and apply it now."""
    with _lock:
        _cfg['cache_dir'] = path
        _cache_state['applied'] = None
    return _ensure_persistent_cache()


def _ensure_persistent_cache():
    d = _cache_dir()
    if _cache_state['applied'] == d:
        return d
    if not d:
        # a dir WAS applied and is now unset (often a TemporaryDirectory
        # that no longer exists): un-point jax or every later compile in
        # the process warns trying to write cache entries into the grave
        if _cache_state['applied']:
            try:
                import jax
                jax.config.update('jax_compilation_cache_dir', None)
                from jax.experimental.compilation_cache import (
                    compilation_cache as _cc)
                _cc.reset_cache()
            except Exception:
                pass
            _cache_state['applied'] = None
        return ''
    try:
        import jax
        os.makedirs(d, exist_ok=True)
        jax.config.update('jax_compilation_cache_dir', d)
        # drop jax's eligibility gates so every program (including the
        # tiny ones tests and cold-start smoke runs compile) is cached
        for knob, val in (('jax_persistent_cache_min_entry_size_bytes', -1),
                          ('jax_persistent_cache_min_compile_time_secs', 0.0)):
            try:
                jax.config.update(knob, val)
            except Exception:
                pass
        try:
            # jax latches the cache's initialized/disabled state at the
            # FIRST compile of the process — anything jitted before the
            # dir was set (import-time helpers, init ops) leaves it
            # permanently off without this re-init
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc)
            _cc.reset_cache()
        except Exception:
            pass
        _cache_state['applied'] = d
    except Exception:
        # jax absent or too old for the cache knobs: the plane still
        # works, the cache just stays cold
        _cache_state['applied'] = d
    return d


def persistent_cache_stats():
    """Hit/miss/saved-seconds counters plus the on-disk byte footprint
    of the persistent cache directory (0 when unset/empty)."""
    d = _cache_dir()
    nbytes = 0
    entries = 0
    if d and os.path.isdir(d):
        for root, _dirs, files in os.walk(d):
            for f in files:
                try:
                    nbytes += os.path.getsize(os.path.join(root, f))
                    entries += 1
                except OSError:
                    pass
    with _lock:
        out = {'dir': d or None,
               'hits': _pcache['hits'], 'misses': _pcache['misses'],
               'requests': _pcache['requests'],
               'saved_seconds': round(_pcache['saved'], 6),
               'saved_seconds_est': round(_pcache['saved_est'], 6),
               'bytes': nbytes, 'files': entries}
    if _metrics.enabled():
        _metrics.set_gauge('mxnet_tpu_compile_persistent_cache_bytes',
                           nbytes)
    return out


# ---------------------------------------------------------------------------
# jax.monitoring listeners
# ---------------------------------------------------------------------------

def _arm_hooks():
    if _hooks['armed']:
        return
    with _lock:
        if _hooks['armed']:
            return
        _hooks['armed'] = True     # one attempt; listeners are permanent
        try:
            from jax import monitoring as _mon
            _mon.register_event_duration_secs_listener(_on_duration)
            _mon.register_event_listener(_on_event)
        except Exception:
            pass


def _on_duration(event, duration, **_kw):
    # fires synchronously on the compiling thread at the END of a phase
    phase = _EVT_PHASE.get(event)
    ctx = getattr(_tls, 'ctx', None)
    if phase is None:
        if event == '/jax/compilation_cache/compile_time_saved_sec':
            with _lock:
                _pcache['saved'] += duration
            if ctx is not None:
                ctx['cache']['saved_seconds'] = round(
                    ctx['cache'].get('saved_seconds', 0.0) + duration, 6)
        return
    if ctx is None:
        return
    ctx['phases'][phase] = ctx['phases'].get(phase, 0.0) + duration
    now = _time.time()
    _trace.complete('compile.' + phase, (now - duration) * 1e6,
                    duration * 1e6, site=ctx['site'])
    fl = _inflight.get(ctx['tid'])
    if fl is not None:
        fl['phase'] = _NEXT_PHASE.get(phase, phase)
        fl['phase_since'] = now


def _on_event(event, **_kw):
    if event == '/jax/compilation_cache/cache_hits':
        with _lock:
            _pcache['hits'] += 1
        ctx = getattr(_tls, 'ctx', None)
        if ctx is not None:
            ctx['cache']['hits'] = ctx['cache'].get('hits', 0) + 1
        if _metrics.enabled():
            _metrics.inc('mxnet_tpu_compile_persistent_cache_hits_total')
    elif event == '/jax/compilation_cache/cache_misses':
        with _lock:
            _pcache['misses'] += 1
        ctx = getattr(_tls, 'ctx', None)
        if ctx is not None:
            ctx['cache']['misses'] = ctx['cache'].get('misses', 0) + 1
        if _metrics.enabled():
            _metrics.inc('mxnet_tpu_compile_persistent_cache_misses_total')
    elif event == '/jax/compilation_cache/compile_requests_use_cache':
        with _lock:
            _pcache['requests'] += 1


# ---------------------------------------------------------------------------
# signatures
# ---------------------------------------------------------------------------

def arg_sig(name, shape=None, dtype=None, sharding=None, donated=False):
    """One argument's signature row."""
    return {'name': str(name),
            'shape': None if shape is None else [int(s) for s in shape],
            'dtype': None if dtype is None else str(dtype),
            'sharding': None if sharding is None else str(sharding),
            'donated': bool(donated)}


def array_sig(name, x, donated=False):
    """Signature row read off a jax/numpy array (sharding included when
    the array carries one)."""
    sharding = None
    s = getattr(x, 'sharding', None)
    if s is not None:
        try:
            spec = getattr(s, 'spec', None)
            sharding = str(spec) if spec is not None else str(s)
        except Exception:
            sharding = None
    return arg_sig(name, getattr(x, 'shape', None),
                   getattr(x, 'dtype', None), sharding, donated)


def signature(args=(), flags=None):
    """A build site's structured signature: per-arg rows + flag knobs
    (ZeRO stage, compression codec, donation policy, ...)."""
    return {'args': list(args), 'flags': dict(flags or {})}


def fingerprint(sig) -> str:
    """16-hex-digit stable fingerprint of a structured signature."""
    blob = json.dumps(sig, sort_keys=True, separators=(',', ':'),
                      default=str)
    return hashlib.sha256(blob.encode('utf-8')).hexdigest()[:16]


def diff_signatures(old, new):
    """Name every churning axis between two signatures: a list of
    ``{'axis': shape|dtype|sharding|donation|flag|arity, 'detail': ...}``
    rows whose `detail` strings are human-grade ("arg 3 `data`: shape
    (32, 128)→(32, 131)")."""
    out = []
    oa = old.get('args', []) or []
    na = new.get('args', []) or []
    if len(oa) != len(na):
        out.append({'axis': 'arity',
                    'detail': f'arg count {len(oa)}→{len(na)}'})
    for i, (o, n) in enumerate(zip(oa, na)):
        name = n.get('name') or o.get('name') or str(i)
        for key, label in (('shape', 'shape'), ('dtype', 'dtype'),
                           ('sharding', 'sharding'),
                           ('donated', 'donation')):
            ov, nv = o.get(key), n.get(key)
            if ov == nv:
                continue
            if key == 'shape':
                ov = tuple(ov) if ov is not None else None
                nv = tuple(nv) if nv is not None else None
                detail = f'arg {i} `{name}`: shape {ov}→{nv}'
            elif key == 'donated':
                detail = (f'arg {i} `{name}`: donation '
                          f'{bool(ov)}→{bool(nv)}')
            else:
                detail = f'arg {i} `{name}`: {label} {ov}→{nv}'
            out.append({'axis': label, 'arg': i, 'name': name,
                        'detail': detail})
    of = old.get('flags', {}) or {}
    nf = new.get('flags', {}) or {}
    for k in sorted(set(of) | set(nf)):
        if of.get(k) != nf.get(k):
            out.append({'axis': 'flag', 'name': k,
                        'detail': f'flag `{k}`: {of.get(k)!r}→'
                                  f'{nf.get(k)!r}'})
    return out


def _sig_str(sig) -> str:
    try:
        return json.dumps(sig, sort_keys=True, default=str)
    except Exception:
        return repr(sig)


# ---------------------------------------------------------------------------
# build contexts
# ---------------------------------------------------------------------------

def begin(site, _span=True):
    """Open a compile window for `site`.  Returns an opaque ctx to hand
    to :func:`set_signature` / :func:`end` / :func:`abort`, or None when
    the plane is disarmed (the persistent-cache knob is still applied —
    caching must not depend on the ledger being on)."""
    cache_dir = _ensure_persistent_cache()
    armed = _state['on']
    if not armed and not cache_dir:
        return None
    _arm_hooks()
    if not armed:
        return None
    _seed_fp_seconds()
    now = _time.time()
    tid = threading.get_ident()
    ctx = {'site': site, 't0': now, 'mono0': _time.perf_counter(),
           'tid': tid, 'phases': {}, 'cache': {}, 'signature': None,
           'prev': getattr(_tls, 'ctx', None), 'span': None}
    if _span:
        ctx['span'] = _trace.span('compile.build', site=site)
        ctx['span'].__enter__()
    _tls.ctx = ctx
    with _lock:
        _inflight[tid] = {'site': site, 'phase': 'build', 'since': now,
                          'phase_since': now}
    return ctx


def set_signature(ctx, sig):
    if ctx is not None:
        ctx['signature'] = sig


def _close(ctx, exc=False):
    if ctx.get('closed'):
        return
    ctx['closed'] = True
    if ctx.get('span') is not None:
        ctx['span'].__exit__(None, None, None)
        ctx['span'] = None
    _tls.ctx = ctx.get('prev')
    tid = ctx['tid']
    with _lock:
        prev = ctx.get('prev')
        if prev is not None:
            _inflight[tid] = {'site': prev['site'], 'phase': 'build',
                              'since': prev['t0'],
                              'phase_since': _time.time()}
        else:
            _inflight.pop(tid, None)


def abort(ctx):
    """Close a compile window without a ledger entry (trace failed, the
    site fell back to eager, an exception unwound the build)."""
    if ctx is None:
        return
    _close(ctx, exc=True)


def end(ctx):
    """Close the compile window: ledger entry (ring + disk), recompile
    forensics against the site's previous signature, phase metrics, and
    the persistent-cache attribution.  Returns the ledger entry."""
    if ctx is None or ctx.get('closed'):
        return None
    total = _time.perf_counter() - ctx['mono0']
    _close(ctx)
    now = _time.time()
    site = ctx['site']
    sig = ctx['signature'] or signature()
    fp = fingerprint(sig)

    with _lock:
        st = _sites.get(site)
        prev_sig = st['signature'] if st else None
        nth = (st['n'] if st else 0) + 1
        _sites[site] = {'n': nth, 'signature': sig, 'fingerprint': fp}

    axes = diff_signatures(prev_sig, sig) if prev_sig is not None else []
    detail = '; '.join(a['detail'] for a in axes)

    phases = ctx['phases']
    seconds = {'trace': round(phases.get('trace', 0.0), 6),
               'lower': round(phases.get('lower', 0.0), 6),
               'backend': round(phases.get('backend', 0.0), 6),
               'total': round(total, 6)}
    entry = {'schema': LEDGER_SCHEMA, 'time': round(now, 6),
             'pid': os.getpid(), 'site': site, 'nth': nth,
             'fingerprint': fp, 'device_kind': _device_kind(),
             'backend': _backend_name(), 'signature': sig,
             'seconds': seconds}
    if ctx['cache']:
        cache = dict(ctx['cache'])
        # saved-seconds estimate: what this fingerprint cost to compile
        # the last time the (possibly shared cross-process) ledger saw
        # it actually built — jax's own compile_time_saved_sec can go
        # negative for tiny programs, so keep both numbers
        if cache.get('hits'):
            est = _fp_seconds.get(fp)
            if est is not None:
                cache['saved_seconds_est'] = round(est, 6)
                with _lock:
                    _pcache['saved_est'] += est
                if _metrics.enabled():
                    _metrics.counter(
                        'mxnet_tpu_compile_persistent_cache_'
                        'saved_seconds_total').inc(est)
        entry['cache'] = cache
    if axes:
        entry['churn_axes'] = [a['detail'] for a in axes]

    with _lock:
        _ring.append(entry)
        cap = _ring_cap()
        while len(_ring) > cap:
            _ring.popleft()
        _totals['n'] += 1
        _totals['seconds'] += total
        if not entry.get('cache', {}).get('hits'):
            _fp_seconds[fp] = total
        _last['fields'] = {'site': site, 'nth': nth, 'fingerprint': fp,
                           'seconds': seconds['total'],
                           'backend_seconds': seconds['backend']}
        _last['fresh'] = True

    if _metrics.enabled():
        for ph in ('trace', 'lower', 'backend'):
            if seconds[ph]:
                _metrics.counter(
                    'mxnet_tpu_compile_phase_seconds_total').inc(
                        seconds[ph], site=site, phase=ph)
        _metrics.set_gauge('mxnet_tpu_compile_ledger_entries', len(_ring))

    if nth > 1:
        if _metrics.enabled():
            for a in axes:
                _metrics.inc('mxnet_tpu_compile_churn_axes', site=site,
                             axis=a['axis'])
        try:
            from . import flight as _flight
            _flight.note('compile.recompiled', site=site, nth=nth,
                         fingerprint=fp, seconds=seconds['total'],
                         axes=[a['detail'] for a in axes] or
                         ['identical signature (new program instance)'])
        except Exception:
            pass
    if entry.get('cache', {}).get('hits'):
        try:
            from . import flight as _flight
            _flight.note('compile.cache_hit', site=site, fingerprint=fp,
                         hits=entry['cache']['hits'],
                         saved_seconds=entry['cache'].get(
                             'saved_seconds',
                             entry['cache'].get('saved_seconds_est')),
                         saved_seconds_est=entry['cache'].get(
                             'saved_seconds_est'))
        except Exception:
            pass

    # the existing per-site compile counters + the episode-latched
    # RecompileWarning, now naming the exact churning axis
    if _metrics.enabled():
        _metrics.record_compile(site, _sig_str(sig), total, detail=detail)

    path = ledger_path()
    if path:
        _append_ledger(path, entry)
    return entry


class _Watch:
    """Armed `watching` context: a compile window that only records a
    ledger entry when jax actually compiled inside the block (cache-hot
    batches discard for free — no span, no entry)."""
    __slots__ = ('site', 'sig_fn', 'ctx')

    def __init__(self, site, sig_fn):
        self.site = site
        self.sig_fn = sig_fn

    def __enter__(self):
        self.ctx = begin(self.site, _span=False)
        return self

    def __exit__(self, etype, evalue, tb):
        ctx, self.ctx = self.ctx, None
        if ctx is None:
            return False
        if etype is not None or not ctx['phases']:
            abort(ctx)
            return False
        if self.sig_fn is not None:
            try:
                ctx['signature'] = self.sig_fn()
            except Exception:
                pass
        end(ctx)
        return False


class _NullWatch:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_WATCH = _NullWatch()


def watching(site, sig_fn=None):
    """Hot-path compile window (the io normalize program dispatches
    every batch): disarmed it is a shared no-op context; armed it opens
    a window that records only if a compile occurred.  `sig_fn` is
    evaluated lazily, only when an entry is written."""
    if not _state['on']:
        return _NULL_WATCH
    return _Watch(site, sig_fn)


# ---------------------------------------------------------------------------
# ledger disk
# ---------------------------------------------------------------------------

def _append_ledger(path, entry):
    try:
        from ..serialization import atomic_write_file
        old = b''
        try:
            with open(path, 'rb') as f:
                old = f.read()
        except FileNotFoundError:
            pass
        lines = old.splitlines() if old else []
        lines.append(json.dumps(entry, sort_keys=True,
                                default=str).encode('utf-8'))
        if len(lines) > _LEDGER_MAX_LINES:
            lines = lines[-_LEDGER_MAX_LINES:]
        atomic_write_file(path, b'\n'.join(lines) + b'\n')
    except Exception as e:
        if _metrics.enabled():
            _metrics.inc('mxnet_tpu_compile_ledger_errors_total')
        if not _ledger_err['warned']:
            _ledger_err['warned'] = True
            import warnings
            warnings.warn(f'telemetry.compile: ledger append to {path!r} '
                          f'failed ({e!r}); further failures are counted '
                          f'silently', RuntimeWarning, stacklevel=2)


def _seed_fp_seconds():
    """Load fingerprint->seconds from a pre-existing ledger file once,
    so a warm process can estimate persistent-cache saved-seconds from
    the cold process's recorded compile times."""
    if _seed['done']:
        return
    _seed['done'] = True
    path = ledger_path()
    if not path or not os.path.exists(path):
        return
    try:
        with open(path, 'rb') as f:
            for line in f.read().splitlines():
                if not line.strip():
                    continue
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                fp = e.get('fingerprint')
                sec = (e.get('seconds') or {}).get('total')
                if fp and sec and not (e.get('cache') or {}).get('hits'):
                    _fp_seconds.setdefault(fp, float(sec))
    except OSError:
        pass


# ---------------------------------------------------------------------------
# plane integration (flight / fleet / healthz / verdict)
# ---------------------------------------------------------------------------

def in_flight():
    """The oldest open compile window as ``{'site', 'phase',
    'elapsed_seconds'}``, or None.  One dict check when nothing is
    compiling — safe on the watchdog/verdict path."""
    if not _inflight:
        return None
    with _lock:
        if not _inflight:
            return None
        fl = min(_inflight.values(), key=lambda f: f['since'])
        return {'site': fl['site'], 'phase': fl['phase'],
                'elapsed_seconds': round(_time.time() - fl['since'], 3)}


def step_fields():
    """Compact fields for the flight-recorder step record — only on the
    first step after a compile (consume-on-read), so steady-state steps
    carry no compile noise.  Disarmed: one dict check, no allocation."""
    if not _state['on']:
        return None
    if not _last['fresh']:
        return None
    _last['fresh'] = False
    return _last['fields']


def snapshot_fields():
    """The fleet-heartbeat payload: cumulative compile count/seconds and
    the in-flight window (a rank stuck in compile.backend shows up in
    every peer's snapshot table), or None while disarmed."""
    if not _state['on']:
        return None
    out = {'n': _totals['n'], 'seconds': round(_totals['seconds'], 3)}
    fl = in_flight()
    if fl is not None:
        out['in_flight'] = fl
    return out


def health_fields():
    """The /healthz compile document — cold path, computed on demand."""
    out = {'enabled': _state['on'], 'compiles': _totals['n'],
           'seconds': round(_totals['seconds'], 3)}
    with _lock:
        if _ring:
            e = _ring[-1]
            out['last'] = {'site': e['site'], 'nth': e['nth'],
                           'fingerprint': e['fingerprint'],
                           'seconds': e['seconds']['total'],
                           'time': e['time']}
    fl = in_flight()
    if fl is not None:
        out['in_flight'] = fl
    p = ledger_path()
    if p:
        out['ledger_path'] = p
    if _cache_dir():
        out['persistent_cache'] = persistent_cache_stats()
    return out


def _device_kind():
    if _device['kind'] is None:
        try:
            import jax
            _device['kind'] = str(jax.devices()[0].device_kind)
        except Exception:
            return 'unknown'
    return _device['kind']


def _backend_name():
    if _device['backend'] is None:
        try:
            import jax
            _device['backend'] = str(jax.default_backend())
        except Exception:
            return 'unknown'
    return _device['backend']


# ---------------------------------------------------------------------------
# ledger validation (tools/check_compile_ledger.py + tests)
# ---------------------------------------------------------------------------

def validate_ledger_entry(e):
    """Problems with one ledger entry (empty list = valid)."""
    problems = []
    if not isinstance(e, dict):
        return [f'entry is {type(e).__name__}, not an object']
    if e.get('schema') != LEDGER_SCHEMA:
        problems.append(f"schema {e.get('schema')!r} != {LEDGER_SCHEMA!r}")
    for k in LEDGER_REQUIRED:
        if k not in e:
            problems.append(f'missing key {k!r}')
    if problems:
        return problems
    if not isinstance(e['site'], str) or not e['site']:
        problems.append('site must be a non-empty string')
    if not isinstance(e['nth'], int) or e['nth'] < 1:
        problems.append(f"nth {e['nth']!r} must be an int >= 1")
    sec = e['seconds']
    if not isinstance(sec, dict):
        problems.append('seconds must be an object')
    else:
        for k in ('trace', 'lower', 'backend', 'total'):
            v = sec.get(k)
            if not isinstance(v, (int, float)) or v < 0:
                problems.append(f'seconds.{k} {v!r} must be a number >= 0')
    sig = e['signature']
    if not isinstance(sig, dict) or 'args' not in sig:
        problems.append('signature must be an object with an args list')
    else:
        fp = fingerprint(sig)
        if fp != e['fingerprint']:
            problems.append(f"fingerprint {e['fingerprint']!r} does not "
                            f'match its signature (recomputed {fp!r})')
    return problems


def validate_ledger(entries):
    """Problems with a whole ledger: per-entry shape, monotone
    timestamps and nth per (pid, site), and the same-fingerprint ⇒
    same-signature invariant."""
    problems = []
    last_time = {}
    last_nth = {}
    fp_sig = {}
    for i, e in enumerate(entries):
        for p in validate_ledger_entry(e):
            problems.append(f'entry {i}: {p}')
        if not isinstance(e, dict) or 'time' not in e:
            continue
        pid = e.get('pid')
        t = e.get('time')
        if isinstance(t, (int, float)):
            lt = last_time.get(pid)
            if lt is not None and t < lt:
                problems.append(f'entry {i}: time {t} went backwards '
                                f'(previous {lt}) for pid {pid}')
            last_time[pid] = t
        key = (pid, e.get('site'))
        nth = e.get('nth')
        if isinstance(nth, int):
            ln = last_nth.get(key)
            if ln is not None and nth <= ln:
                problems.append(f'entry {i}: nth {nth} not increasing '
                                f'(previous {ln}) for site {key[1]!r}')
            last_nth[key] = nth
        fp = e.get('fingerprint')
        sig = e.get('signature')
        if fp is not None and sig is not None:
            seen = fp_sig.get(fp)
            if seen is None:
                fp_sig[fp] = sig
            elif seen != sig:
                problems.append(f'entry {i}: fingerprint {fp!r} maps to '
                                f'two different signatures')
    return problems


# config gate: MXTPU_COMPILE_LEDGER arms the plane at import (listener
# registration and the jax.config cache wiring both stay lazy — the
# telemetry package never imports jax at module import time)
if _config_mod.get('MXTPU_COMPILE_LEDGER'):
    enable()
