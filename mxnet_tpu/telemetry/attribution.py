"""Per-step performance attribution: spans + XLA cost_analysis -> MFU.

Takes the flight recorder's per-step span summaries (measured wall
time, host side) and the compiled step's XLA ``cost_analysis`` (flops
and bytes, device side) and decomposes honest MFU into buckets:

- ``input``      — the consumer thread waiting on the input pipeline
                   (``io.*`` spans: batch production, prefetch stalls,
                   decode, record leases),
- ``h2d``        — host->device staging the consumer paid for
                   (``h2d.*`` spans: device_put, batch placement,
                   device-side normalize dispatch),
- ``collective`` — host-measured gradient reduction (``comm.*`` spans
                   on the kvstore path; on the GSPMD path collectives
                   run inside the compiled program — their analytic
                   byte plan rides in the report's ``collective_bytes``
                   instead of this bucket),
- ``host_sync``  — blocking device->host reads (``sync.*`` spans),
- ``compute``    — the residual: wall time minus everything above,
                   i.e. the compiled step program (fwd+bwd+optimizer,
                   and on GSPMD the in-program collectives).

Bucket arithmetic uses span SELF time (child spans subtracted by
``telemetry.trace``), so nesting never double-counts, and ``compute``
is defined as the residual, so the bucket sum always reconstructs the
measured wall time exactly — the report states what fraction of wall
was *measured* vs residual rather than pretending a sum.

Works on CPU today (the spans and cost_analysis are backend-agnostic);
when the chip is back, ``tools/tune_bert_step.py --trace`` captures an
xprof trace alongside this report so the residual's in-program split
(matmul vs collective vs elementwise) comes from the device timeline.
"""
from __future__ import annotations

__all__ = ['BUCKET_PREFIXES', 'bucket_of', 'subsystems', 'report',
           'format_table', 'format_memory_table', 'xla_cost',
           'MEMORY_BUCKETS']

# memory_analysis() bucket order (ShardedTrainStep.memory_analysis /
# telemetry.memory): persistent residency buckets, then the residual
# activations-temp bucket that makes the sum reconstruct the measured
# peak — the memory analog of the wall-time table above
MEMORY_BUCKETS = ('params', 'optimizer_state', 'residuals', 'io_leases',
                  'activations_temp')

# span-name prefix -> bucket; everything else is residual 'compute'
BUCKET_PREFIXES = (
    ('io.', 'input'),
    ('h2d.', 'h2d'),
    ('comm.', 'collective'),
    ('sync.', 'host_sync'),
)

# spans recorded on overlapped threads (workers, background writers):
# they never spend the consumer's step time, so they are reported in
# the span table but excluded from the wall-time buckets
OVERLAPPED_SPANS = frozenset((
    'io.worker_fetch', 'h2d.pin', 'checkpoint.write',
))


def bucket_of(name):
    """Bucket for a span name, or None for residual/overlapped work."""
    if name in OVERLAPPED_SPANS:
        return None
    for prefix, bucket in BUCKET_PREFIXES:
        if name.startswith(prefix):
            return bucket
    return None


def subsystems(names):
    """Sorted set of subsystem prefixes ('io', 'h2d', 'step', ...) a
    collection of span/event names covers."""
    out = set()
    for n in names:
        if '.' in n:
            out.add(n.split('.', 1)[0])
    return sorted(out)


def xla_cost(compiled):
    """{'flops', 'bytes'} from an XLA compiled executable's
    cost_analysis() (per-device; normalized across jax versions that
    return a list vs a dict). None when the backend exposes neither."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return None
    flops = ca.get('flops')
    nbytes = ca.get('bytes accessed')
    if flops is None and nbytes is None:
        return None
    return {'flops': float(flops) if flops is not None else None,
            'bytes': float(nbytes) if nbytes is not None else None}


def report(steps, flops_per_step=None, bytes_per_step=None,
           peak_flops=None, collective_bytes=None, gather_layers=None,
           skip_first=1):
    """Attribution over flight-recorder step records.

    ``steps`` — ``flight.get().steps()`` (each record carries
    ``interval_ms`` + ``spans_ms``). The first ``skip_first`` records
    are dropped (they carry compile time and have no interval).
    ``flops_per_step``/``bytes_per_step`` — XLA cost_analysis numbers
    (see ``xla_cost``); with ``peak_flops`` they turn the measured wall
    into an honest-MFU figure from the same timebase as the buckets.
    """
    used = [r for r in steps[skip_first:] if r.get('interval_ms')]
    if not used:
        return {'error': 'no step records with intervals '
                         '(need >= %d traced steps)' % (skip_first + 2)}
    n = len(used)
    wall_ms = sum(r['interval_ms'] for r in used) / n

    buckets_ms = {'input': 0.0, 'h2d': 0.0, 'collective': 0.0,
                  'host_sync': 0.0}
    span_table = {}
    for r in used:
        for name, st in r['spans_ms'].items():
            b = bucket_of(name)
            if b is not None:
                # bill only the consumer thread's self time against the
                # step wall when the drain recorded it (overlapped
                # producer/writer threads never spend step time);
                # name-based OVERLAPPED_SPANS covers synthetic records
                buckets_ms[b] += st.get('consumer_self_ms',
                                        st['self_ms']) / n
            row = span_table.setdefault(
                name, {'count': 0.0, 'total_ms': 0.0, 'self_ms': 0.0})
            row['count'] += st['count'] / n     # per-step, like the ms
            row['total_ms'] += st['total_ms'] / n
            row['self_ms'] += st['self_ms'] / n

    measured = sum(buckets_ms.values())
    buckets_ms['compute'] = max(0.0, wall_ms - measured)
    total = sum(buckets_ms.values())
    out = {
        'steps_used': n,
        'wall_ms_per_step': round(wall_ms, 3),
        'buckets_ms': {k: round(v, 3) for k, v in buckets_ms.items()},
        'bucket_fractions': {k: round(v / total, 4) if total else 0.0
                             for k, v in buckets_ms.items()},
        # how much of wall was measured by spans vs residual: the
        # honesty indicator (compute is defined as the residual, so the
        # bucket sum reconstructs wall whenever measured <= wall)
        'measured_fraction': round(min(measured, wall_ms)
                                   / wall_ms, 4) if wall_ms else 0.0,
        'bucket_sum_over_wall': round(total / wall_ms, 4) if wall_ms
        else 0.0,
        'spans_ms_per_step': {
            k: {kk: (round(vv, 3) if isinstance(vv, float) else vv)
                for kk, vv in v.items()}
            for k, v in sorted(span_table.items())},
    }
    if flops_per_step:
        out['flops_per_step'] = float(flops_per_step)
        if peak_flops:
            out['mfu_percent'] = round(
                100.0 * flops_per_step / (wall_ms / 1e3 * peak_flops), 2)
            out['peak_flops_assumed'] = float(peak_flops)
    if bytes_per_step:
        out['bytes_per_step'] = float(bytes_per_step)
    if collective_bytes:
        # GSPMD path: collectives run inside the compiled program; the
        # analytic ring-wire plan (mxnet_tpu_comm_* accounting) is the
        # only host-visible number for them
        out['collective_bytes_per_step'] = {
            k: int(v) for k, v in collective_bytes.items()}
    if gather_layers:
        # ZeRO-3 per-layer all-gather plan [(layer, bytes/step, count)]:
        # the unit of gather-vs-compute overlap the latency-hiding
        # scheduler works with (matches the comm.all_gather trace
        # instants' `layer` arg)
        out['gather_bytes_per_layer'] = {
            str(layer): int(nbytes) for layer, nbytes, _c in gather_layers}
    losses = [r['loss'] for r in used if r.get('loss') is not None]
    if losses:
        out['loss_last'] = losses[-1]
    return out


def _mb(nbytes):
    return nbytes / 1e6


def format_memory_table(rep):
    """Monospace table of a ``ShardedTrainStep.memory_analysis()`` dict
    (tools / PERF_NOTES) — the memory sibling of ``format_table``:
    per-device residency buckets whose sum reconstructs the measured
    peak (activations-temp is the explicit residual), the per-layer
    breakdown, and XLA's own compiled-program memory analysis when the
    backend exposes it."""
    if rep is None:
        return 'memory: no analysis (run at least one step first)'
    if 'error' in rep:
        return f"memory: {rep['error']}"
    lines = [
        f"peak {_mb(rep['peak_bytes_per_device']):.3f} MB/device "
        f"({rep['source']}; measured "
        f"{100 * rep['measured_fraction']:.1f}%, residual = "
        f"activations-temp) zero={rep['zero_stage']} dp={rep['dp']}"
        + (f" compression={rep['compression']}" if rep.get('compression')
           else ''),
        f"{'bucket':<18s}{'MB/device':>12s}{'fraction':>10s}",
    ]
    for b in MEMORY_BUCKETS:
        lines.append(f"{b:<18s}{_mb(rep['buckets_bytes'][b]):>12.3f}"
                     f"{100 * rep['bucket_fractions'][b]:>9.1f}%")
    if rep.get('pad_bytes'):
        lines.append(f"(zero3 flat pad slack "
                     f"{_mb(rep['pad_bytes']):.3f} MB/device)")
    xla = rep.get('xla')
    if xla:
        lines.append(
            "xla memory_analysis: "
            + ' '.join(f"{k.replace('_size_in_bytes', '')}="
                       f"{_mb(v):.3f}MB" for k, v in sorted(xla.items())))
    per_layer = rep.get('per_layer_bytes')
    if per_layer:
        lines.append('')
        lines.append(f"{'layer':<28s}{'persistent MB':>14s}"
                     f"{'gather MB/step':>15s}")
        gathers = rep.get('gather_bytes_per_layer') or {}
        rows = sorted(per_layer.items(), key=lambda kv: -kv[1])
        for layer, nb in rows:
            g = gathers.get(layer, 0)
            lines.append(f"{str(layer)[:27]:<28s}{_mb(nb):>14.3f}"
                         f"{_mb(g):>15.3f}")
    if rep.get('host_rss_bytes'):
        lines.append(f"host RSS {_mb(rep['host_rss_bytes']):.1f} MB")
    return '\n'.join(lines)


def format_table(rep):
    """Monospace table of a report() dict (tools / PERF_NOTES)."""
    if 'error' in rep:
        return f"attribution: {rep['error']}"
    lines = [
        f"step wall {rep['wall_ms_per_step']:.3f} ms over "
        f"{rep['steps_used']} steps "
        f"(measured {100 * rep['measured_fraction']:.1f}%, "
        f"residual = compute)",
        f"{'bucket':<12s}{'ms/step':>10s}{'fraction':>10s}",
    ]
    order = ('input', 'h2d', 'collective', 'host_sync', 'compute')
    for b in order:
        lines.append(f"{b:<12s}{rep['buckets_ms'][b]:>10.3f}"
                     f"{100 * rep['bucket_fractions'][b]:>9.1f}%")
    if 'mfu_percent' in rep:
        lines.append(f"honest MFU {rep['mfu_percent']:.2f}% "
                     f"({rep['flops_per_step']:.3e} flops/step @ "
                     f"{rep['peak_flops_assumed']:.0f} peak FLOP/s)")
    lines.append('')
    lines.append(f"{'span':<28s}{'calls/step':>11s}{'total ms':>10s}"
                 f"{'self ms':>10s}")
    rows = sorted(rep['spans_ms_per_step'].items(),
                  key=lambda kv: -kv[1]['self_ms'])
    for name, row in rows:
        lines.append(f"{name[:27]:<28s}{row['count']:>11.1f}"
                     f"{row['total_ms']:>10.3f}{row['self_ms']:>10.3f}")
    return '\n'.join(lines)
