"""Fleet observability: cross-rank telemetry aggregation + anomaly
detection (ISSUE 13).

Everything PRs 1 and 6 built — the metrics registry, span rings,
attribution, flight recorder — is *process-local*: since PR 8 the
system is an elastic multi-host fleet, yet no rank could see another
rank's health, step-time skew or comm imbalance. This module closes
that gap on the membership side channel the fleet already runs (NEVER
the ICI collectives, which are exactly what a wedged rank blocks):

- ``local_snapshot()`` builds a compact per-step telemetry snapshot
  (last step + wall interval, span-bucket self-times, cumulative comm
  bytes per mesh hop, guard/fault/rollback counters, the rank's clock
  offset estimate) from the flight recorder and the metrics registry.
- ``attach(membership)`` wires it as the membership layer's
  ``telemetry_provider``: every heartbeat piggybacks the snapshot (a
  few hundred bytes, one beat per ``MXTPU_HEARTBEAT_SECONDS``). The
  step path is untouched — a disarmed run records and allocates
  nothing extra.
- On the coordinator, ``FleetMonitor.ingest`` merges the snapshots
  into a fleet view with per-rank step skew, exports it as
  ``mxnet_tpu_fleet_*`` gauges/histograms, and runs the streaming
  anomaly detectors:

  - **step-time regression** — a rank's step wall above
    ``MXTPU_FLEET_REGRESSION_FACTOR`` x its own rolling baseline;
  - **straggler skew** — a rank above
    ``MXTPU_FLEET_STRAGGLER_FACTOR`` x the fleet median, or whose
    newest snapshot is older than ``MXTPU_FLEET_STALE_SECONDS``;
  - **loss spike** — a reported loss beyond
    ``MXTPU_FLEET_LOSS_SPIKE_SIGMA`` rolling standard deviations;
  - **comm imbalance** — per-rank comm bytes/step whose max/min ratio
    exceeds ``MXTPU_FLEET_IMBALANCE_FACTOR``.

  Each firing emits a ``fleet.*`` flight note and upgrades the
  watchdog verdict (``resilience.elastic.stall_verdict``) so a stall
  report names the suspected rank, not just "something is slow".
- ``dump_rank_trace()`` writes this rank's chrome trace stamped with
  its rank and clock offset, which ``tools/stitch_traces.py`` merges
  into one fleet-wide timeline (validated by ``tools/check_trace.py``).
"""
from __future__ import annotations

import collections
import json
import os
import statistics
import threading
import time as _time

from ..base import telem_flags as _telem
from . import compile as _compile
from . import flight as _flight
from . import memory as _memory
from . import metrics as _metrics
from . import trace as _trace
from .attribution import bucket_of

__all__ = ['local_snapshot', 'snapshot_bytes', 'comm_bytes_by_axis',
           'FleetMonitor', 'monitor', 'attach', 'detach',
           'dump_rank_trace', 'estimate_offset']

# resilience counters carried in each snapshot: {short key: metric}
_COUNTER_METRICS = {
    'faults': 'mxnet_tpu_resilience_faults_injected_total',
    'bad_steps': 'mxnet_tpu_resilience_bad_steps_total',
    'rollbacks': 'mxnet_tpu_resilience_rollbacks_total',
}


def comm_bytes_by_axis():
    """Cumulative analytic collective wire bytes by mesh hop axis
    ({'dp': ..., 'dph': ..., 'dpi': ...}) from the PR 11 per-hop
    accounting counters. Empty when telemetry is off or no sharded
    step has run."""
    out = {}
    for labels, v in _metrics.series(
            'mxnet_tpu_comm_collective_bytes_total'):
        axis = labels.get('axis', '?')
        out[axis] = out.get(axis, 0) + int(v)
    return out


def _counter_sums():
    out = {}
    for key, name in _COUNTER_METRICS.items():
        total = sum(v for _l, v in _metrics.series(name))
        if total:
            out[key] = int(total)
    return out


def local_snapshot():
    """Compact per-rank telemetry snapshot dict, or None when both the
    metrics registry and the tracer are disarmed (nothing to report —
    the heartbeat then carries no payload at all)."""
    if not _telem['on'] and not _trace._state['on']:
        return None
    snap = {'time': round(_time.time(), 3)}
    rec = _flight.get().last_step_record()
    if rec is not None:
        snap['step'] = rec.get('step')
        if rec.get('interval_ms') is not None:
            snap['wall_ms'] = rec['interval_ms']
        if rec.get('loss') is not None:
            snap['loss'] = rec['loss']
        buckets = {}
        for name, st in (rec.get('spans_ms') or {}).items():
            b = bucket_of(name) or 'other'
            buckets[b] = round(buckets.get(b, 0.0) + st['self_ms'], 3)
        if buckets:
            snap['spans_ms'] = buckets
    comm = comm_bytes_by_axis()
    if comm:
        snap['comm_bytes'] = comm
    # memory watermark (MXTPU_MEMORY): a few tens of bytes so the
    # coordinator can flag per-rank HBM imbalance before an OOM
    mem = _memory.snapshot_fields()
    if mem is not None:
        snap['mem'] = mem
    # compile plane (MXTPU_COMPILE_LEDGER): cumulative compile seconds
    # plus the in-flight window — a rank stuck in compile.backend shows
    # up in every peer's fleet table, not just its own logs
    comp = _compile.snapshot_fields()
    if comp is not None:
        snap['compile'] = comp
    counters = _counter_sums()
    if counters:
        snap['counters'] = counters
    step_val = _metrics.value('mxnet_tpu_steps_total')
    if 'step' not in snap and step_val is not None:
        snap['step'] = int(step_val)
    return snap


def snapshot_bytes(snap=None, membership=None):
    """Wire size of one snapshot as the heartbeat ACTUALLY carries it
    (JSON, including the clock-offset field the provider appends on
    ranks with an estimate) — the bytes/beat number PERF_NOTES tracks.
    With no explicit ``snap``, measures the provider output for the
    given (or process-global) membership."""
    if snap is None:
        if membership is None:
            from ..parallel import dist as _dist
            membership = _dist.membership()
        snap = _provider_for(membership)() if membership is not None \
            else local_snapshot()
    if snap is None:
        return 0
    return len(json.dumps(snap).encode())


def estimate_offset(samples):
    """(offset_seconds, rtt_seconds) from ``(t_send, t_reply_received,
    remote_clock_at_handling[, rtt])`` round-trip samples — the
    minimum-RTT sample wins (its asymmetry error is bounded by rtt/2,
    the tightest available bound; NTP's core intuition). None for no
    samples.

    Supply the optional 4th element from a MONOTONIC clock pair when
    recording live (``Membership`` does): a wall-clock rtt (the
    3-tuple fallback) is vulnerable to an NTP step between send and
    receive fabricating a near-zero rtt whose poisoned offset then
    wins the window. ``parallel.dist.Membership`` maintains this
    estimate incrementally per beat (``clock_offset()``); this
    standalone form is the testable kernel and what offline tools use
    on recorded samples."""
    best = None
    for sample in samples:
        t0, t1, remote = sample[0], sample[1], sample[2]
        rtt = float(sample[3]) if len(sample) > 3 else \
            float(t1) - float(t0)
        rtt = max(0.0, rtt)
        off = float(remote) - (float(t0) + float(t1)) / 2.0
        if best is None or rtt < best[1]:
            best = (off, rtt)
    return best


# ---------------------------------------------------------------------------
# coordinator-side fleet view + detectors
# ---------------------------------------------------------------------------

class _RankState:
    __slots__ = ('step', 'wall_ms', 'ewma_ms', 'loss', 'losses',
                 'comm_total', 'comm_rate', 'counters', 'offset',
                 'last_mono', 'last_time', 'snapshots', 'spans_ms',
                 'flags', 'mem_bytes', 'mem_peak', 'compile_seconds',
                 'compiling')

    def __init__(self):
        self.step = None
        self.wall_ms = None
        self.ewma_ms = None
        self.loss = None
        self.losses = None          # deque, sized by the monitor window
        self.comm_total = {}
        self.comm_rate = {}
        self.counters = {}
        self.offset = None
        self.last_mono = None
        self.last_time = None
        self.snapshots = 0
        self.spans_ms = {}
        self.flags = set()          # currently-raised anomaly kinds
        self.mem_bytes = None       # live device bytes (memory snapshot)
        self.mem_peak = None
        self.compile_seconds = None  # cumulative compile wall seconds
        self.compiling = None        # open compile window, or None


class FleetMonitor:
    """Merges per-rank snapshots into a fleet view and runs the
    streaming anomaly detectors. One process-global instance on the
    membership coordinator (``fleet.monitor()``); tests build their
    own. ``ingest(rank, snap)`` is the membership layer's
    ``on_snapshot`` hook — called outside the membership lock, takes
    only its own lock, and emits flight notes/metrics after releasing
    it (no cross-module lock nesting)."""

    def __init__(self, window=None, regression_factor=None,
                 straggler_factor=None, stale_seconds=None,
                 loss_spike_sigma=None, imbalance_factor=None,
                 heartbeat_seconds=None, memory_imbalance_factor=None):
        from .. import config as _config
        self.window = int(window if window is not None
                          else _config.get('MXTPU_FLEET_WINDOW'))
        self.regression_factor = float(
            regression_factor if regression_factor is not None
            else _config.get('MXTPU_FLEET_REGRESSION_FACTOR'))
        self.straggler_factor = float(
            straggler_factor if straggler_factor is not None
            else _config.get('MXTPU_FLEET_STRAGGLER_FACTOR'))
        if heartbeat_seconds is None:
            heartbeat_seconds = _config.get('MXTPU_HEARTBEAT_SECONDS')
        stale = (stale_seconds if stale_seconds is not None
                 else _config.get('MXTPU_FLEET_STALE_SECONDS'))
        # remembered so set_heartbeat (the attach() plumbing) can
        # re-derive the threshold for a membership whose heartbeat was
        # set by kwarg, not by the env knob
        self._stale_auto = not stale
        self.stale_seconds = float(stale) if stale else \
            3.0 * float(heartbeat_seconds)
        self.loss_spike_sigma = float(
            loss_spike_sigma if loss_spike_sigma is not None
            else _config.get('MXTPU_FLEET_LOSS_SPIKE_SIGMA'))
        self.imbalance_factor = float(
            imbalance_factor if imbalance_factor is not None
            else _config.get('MXTPU_FLEET_IMBALANCE_FACTOR'))
        self.memory_imbalance_factor = float(
            memory_imbalance_factor if memory_imbalance_factor is not None
            else _config.get('MXTPU_FLEET_MEMORY_IMBALANCE_FACTOR'))
        # RLock by the same signal-safety rationale as the flight
        # recorder: straggler()/view() are reachable from crash-time
        # reporting paths that may interrupt an ingest on this thread
        self._lock = threading.RLock()
        self.ranks = {}
        self.anomalies = collections.deque(maxlen=256)
        self.snapshots_total = 0

    def set_heartbeat(self, heartbeat_seconds):
        """Re-derive the auto stale threshold from the REAL heartbeat
        period (a membership built with ``heartbeat_seconds=10`` while
        the env knob sits at its 1.0 default would otherwise flag
        every healthy rank stale between beats). An explicit
        MXTPU_FLEET_STALE_SECONDS / stale_seconds wins unchanged."""
        if self._stale_auto:
            self.stale_seconds = 3.0 * float(heartbeat_seconds)
        return self

    # -- ingest ------------------------------------------------------------

    def ingest(self, rank, snap):
        """Merge one rank's snapshot; returns the anomaly firings
        ``[(kind, info), ...]`` of this round (also flight-noted)."""
        rank = int(rank)
        now = _time.monotonic()
        with self._lock:
            st = self.ranks.get(rank)
            if st is None:
                st = self.ranks[rank] = _RankState()
                st.losses = collections.deque(maxlen=self.window)
            stepped = (snap.get('step') is not None
                       and snap['step'] != st.step)
            st.last_mono = now
            st.last_time = snap.get('time')
            st.snapshots += 1
            self.snapshots_total += 1
            if snap.get('offset') is not None:
                st.offset = snap['offset']
            if snap.get('spans_ms'):
                st.spans_ms = dict(snap['spans_ms'])
            if snap.get('counters'):
                st.counters = dict(snap['counters'])
            fired = []
            mem = snap.get('mem')
            if mem and mem.get('live') is not None:
                st.mem_bytes = int(mem['live'])
                if mem.get('peak') is not None:
                    st.mem_peak = int(mem['peak'])
                fired += self._check_memory(now)
            comp = snap.get('compile')
            if comp:
                if comp.get('seconds') is not None:
                    st.compile_seconds = float(comp['seconds'])
                # in_flight present = the rank is mid-compile RIGHT NOW;
                # absent = clear the stale window from the last beat
                st.compiling = comp.get('in_flight')
            elif st.compiling is not None:
                st.compiling = None
            if stepped:
                dstep = snap['step'] - st.step if st.step is not None \
                    else None
                st.step = int(snap['step'])
                wall = snap.get('wall_ms')
                baseline = st.ewma_ms          # PRE-update: the rolling
                # baseline the regression detector compares against —
                # folding the current sample in first would raise the
                # effective trip point to 0.8f/(1-0.2f) x baseline and
                # make any factor >= 5 mathematically unfirable
                if wall is not None:
                    st.wall_ms = float(wall)
                    st.ewma_ms = wall if st.ewma_ms is None else \
                        0.8 * st.ewma_ms + 0.2 * wall
                if snap.get('comm_bytes'):
                    for axis, total in snap['comm_bytes'].items():
                        prev = st.comm_total.get(axis)
                        if prev is not None and dstep and total > prev:
                            st.comm_rate[axis] = \
                                (total - prev) / float(dstep)
                        st.comm_total[axis] = int(total)
                if snap.get('loss') is not None:
                    fired += self._check_loss(rank, st,
                                              float(snap['loss']))
                    st.loss = float(snap['loss'])
                    st.losses.append(st.loss)
                fired += self._check_step_time(rank, st, baseline)
                fired += self._check_imbalance()
            fired += self._check_stale(now)
            for kind, info in fired:
                self.anomalies.append(
                    {'kind': kind, 'time': _time.time(), **info})
        # notes + metrics OUTSIDE self._lock (flight recorder and
        # metrics registry take their own locks)
        for kind, info in fired:
            _flight.note(kind, **info)
        if _telem['on']:
            self._export(rank, snap.get('comm_bytes') or {}, fired,
                         stepped and snap.get('wall_ms') is not None)
        return fired

    # -- detectors (called with the lock held; pure state updates) ---------

    def _check_step_time(self, rank, st, baseline):
        fired = []
        if st.wall_ms is None:
            return fired
        # regression vs this rank's own rolling baseline — the EWMA as
        # it stood BEFORE this sample (the current excursion must not
        # contaminate the reference it is judged against)
        if baseline is not None and baseline > 0 and st.snapshots >= 4:
            if st.wall_ms > self.regression_factor * baseline:
                if 'fleet.step_regression' not in st.flags:
                    st.flags.add('fleet.step_regression')
                    fired.append(('fleet.step_regression', {
                        'rank': rank,
                        'wall_ms': round(st.wall_ms, 3),
                        'baseline_ms': round(baseline, 3),
                        'factor': round(st.wall_ms / baseline, 2)}))
            elif st.wall_ms < 1.1 * baseline:
                st.flags.discard('fleet.step_regression')
        # straggler skew vs the fleet median of the OTHER ranks
        others = [s.ewma_ms for r, s in self.ranks.items()
                  if r != rank and s.ewma_ms is not None]
        if others:
            med = _median(others)
            if med > 0 and st.wall_ms > self.straggler_factor * med:
                if 'fleet.straggler' not in st.flags:
                    st.flags.add('fleet.straggler')
                    fired.append(('fleet.straggler', {
                        'rank': rank, 'reason': 'slow',
                        'wall_ms': round(st.wall_ms, 3),
                        'fleet_median_ms': round(med, 3),
                        'skew': round(st.wall_ms / med, 2)}))
            elif st.wall_ms < 1.1 * med:
                st.flags.discard('fleet.straggler')
        return fired

    def _check_stale(self, now):
        """A rank whose snapshots stopped arriving is straggling even
        if its last reported step time was healthy (a wedged rank's
        heartbeat thread may still beat — but its step loop, and with
        it the advancing snapshot, is stuck)."""
        fired = []
        fresh = [s.last_mono for s in self.ranks.values()
                 if s.last_mono is not None]
        if len(fresh) < 2:
            return fired
        for rank, st in self.ranks.items():
            age = now - st.last_mono
            if age > self.stale_seconds:
                if 'fleet.stale' not in st.flags:
                    st.flags.add('fleet.stale')
                    fired.append(('fleet.straggler', {
                        'rank': rank, 'reason': 'stale',
                        'snapshot_age_seconds': round(age, 3),
                        'step': st.step}))
            else:
                st.flags.discard('fleet.stale')
        return fired

    def _check_loss(self, rank, st, loss):
        vals = list(st.losses)
        if len(vals) < 8:
            return []
        mean = sum(vals) / len(vals)
        var = sum((v - mean) ** 2 for v in vals) / len(vals)
        # epsilon floor: a perfectly flat window (std == 0) is the case
        # where ANY jump is most anomalous — a zero std must not make
        # the detector unfirable (and the missed spike would then
        # inflate the window and mask every later one too)
        std = max(var ** 0.5, abs(mean) * 1e-6, 1e-12)
        if loss <= mean + self.loss_spike_sigma * std:
            st.flags.discard('fleet.loss_spike')
            return []
        if 'fleet.loss_spike' in st.flags:
            return []
        st.flags.add('fleet.loss_spike')
        return [('fleet.loss_spike', {
            'rank': rank, 'loss': round(loss, 6),
            'mean': round(mean, 6), 'std': round(std, 6),
            'sigma': round((loss - mean) / std, 1)})]

    def _check_imbalance(self):
        rates = {r: sum(s.comm_rate.values())
                 for r, s in self.ranks.items() if s.comm_rate}
        live = {r: v for r, v in rates.items() if v > 0}
        if len(live) < 2:
            # fewer than 2 reporters is not "balanced" — it is
            # "uncomparable": clear any latched flag so a survivor
            # whose peer departed (or stopped reporting) is not stuck
            # flagged forever with its next offense latch-swallowed
            for st in self.ranks.values():
                st.flags.discard('fleet.comm_imbalance')
            return []
        hi_rank = max(live, key=live.get)
        ratio = live[hi_rank] / min(live.values())
        imbalanced = ratio > self.imbalance_factor
        fired = []
        # the flag lives ONLY on the current worst offender: a rank
        # that stops being the max must have its flag cleared, or its
        # next offense would be latch-swallowed forever
        for r, st in self.ranks.items():
            if r == hi_rank and imbalanced:
                if 'fleet.comm_imbalance' not in st.flags:
                    st.flags.add('fleet.comm_imbalance')
                    fired.append(('fleet.comm_imbalance', {
                        'rank': hi_rank, 'ratio': round(ratio, 2),
                        'bytes_per_step':
                            {r2: int(v) for r2, v in live.items()}}))
            else:
                st.flags.discard('fleet.comm_imbalance')
        return fired

    def _check_memory(self, _now):
        """HBM imbalance: per-rank live device bytes whose max/min
        ratio exceeds the factor flag the FATTEST rank — the one a
        shared-config fleet expects to OOM first (a rank quietly
        holding 1.5x its peers' memory is a layout bug or a leak, not
        load balancing). Same current-worst-offender flag discipline
        as the comm detector."""
        live = {r: s.mem_bytes for r, s in self.ranks.items()
                if s.mem_bytes}
        if len(live) < 2:
            # same unlatch-on-uncomparable rule as the comm detector:
            # a lone reporter must not keep a stale imbalance flag
            for st in self.ranks.values():
                st.flags.discard('fleet.memory_imbalance')
            return []
        hi_rank = max(live, key=live.get)
        ratio = live[hi_rank] / min(live.values())
        imbalanced = ratio > self.memory_imbalance_factor
        fired = []
        for r, st in self.ranks.items():
            if r == hi_rank and imbalanced:
                if 'fleet.memory_imbalance' not in st.flags:
                    st.flags.add('fleet.memory_imbalance')
                    fired.append(('fleet.memory_imbalance', {
                        'rank': hi_rank, 'ratio': round(ratio, 2),
                        'bytes': {r2: int(v) for r2, v in live.items()}}))
            else:
                st.flags.discard('fleet.memory_imbalance')
        return fired

    # -- exports -----------------------------------------------------------

    def _export(self, rank, comm_total, fired, stepped):
        """Gauge exports for ONE ingest. Only the ingesting rank's
        per-rank gauges are written (each rank refreshes its own row
        once per heartbeat — a fleet-wide rewrite here would be
        O(world^2) locked registry writes per heartbeat period, inside
        the coordinator's reply path); the fleet median for the skew
        gauge is a cheap O(world) read of in-memory state. Registry
        writes happen UNDER the monitor lock so a concurrent
        remove_ranks cannot interleave and resurrect a departed rank's
        rows after they were retired (the monitor->registry lock edge
        is one-directional — the registry never calls back)."""
        now = _time.monotonic()
        with self._lock:
            st = self.ranks.get(rank)
            if st is None:
                return
            n_ranks = len(self.ranks)
            walls = [s.wall_ms for s in self.ranks.values()
                     if s.wall_ms is not None]
            step, wall, loss = st.step, st.wall_ms, st.loss
            offset, mono = st.offset, st.last_mono
            med = _median(walls) if walls else None
            _metrics.set_gauge('mxnet_tpu_fleet_ranks', n_ranks)
            _metrics.inc('mxnet_tpu_fleet_snapshots_total', rank=rank)
            if step is not None:
                _metrics.set_gauge('mxnet_tpu_fleet_last_step', step,
                                   rank=rank)
            if wall is not None:
                _metrics.set_gauge('mxnet_tpu_fleet_step_ms', wall,
                                   rank=rank)
                if med is not None:
                    _metrics.set_gauge('mxnet_tpu_fleet_step_skew_ms',
                                       round(wall - med, 3), rank=rank)
                if stepped:
                    _metrics.observe('mxnet_tpu_fleet_step_seconds',
                                     wall / 1e3, rank=rank)
            if loss is not None:
                _metrics.set_gauge('mxnet_tpu_fleet_loss', loss,
                                   rank=rank)
            if offset:
                _metrics.set_gauge(
                    'mxnet_tpu_fleet_clock_offset_seconds', offset[0],
                    rank=rank)
            if mono is not None:
                _metrics.set_gauge(
                    'mxnet_tpu_fleet_snapshot_age_seconds',
                    round(now - mono, 3), rank=rank)
            if st.mem_bytes is not None:
                # mirrors the rank's own memory watermark (the same
                # exactly-agreeing-scrapes contract as the comm gauge)
                _metrics.set_gauge('mxnet_tpu_fleet_memory_bytes',
                                   st.mem_bytes, rank=rank)
            for axis, total in comm_total.items():
                # a gauge MIRRORING the rank's own cumulative per-hop
                # counter (not a local re-count): a fleet scrape of the
                # coordinator and a per-rank scrape of
                # mxnet_tpu_comm_collective_bytes_total must agree
                # exactly. Inside the lock like every _PER_RANK_METRICS
                # write — remove_ranks must not interleave and see
                # these rows resurrected.
                _metrics.set_gauge('mxnet_tpu_fleet_comm_bytes', total,
                                   rank=rank, axis=axis)
        for kind, info in fired:
            _metrics.inc('mxnet_tpu_fleet_anomalies_total', kind=kind,
                         rank=info.get('rank', rank))

    # -- queries -----------------------------------------------------------

    def view(self):
        """The merged fleet view: per-rank state + skew + the recent
        anomaly log — what /healthz embeds on the coordinator."""
        now = _time.monotonic()
        with self._lock:
            ranks = {}
            for r, st in self.ranks.items():
                ranks[r] = {
                    'step': st.step,
                    'wall_ms': st.wall_ms,
                    'ewma_ms': round(st.ewma_ms, 3)
                    if st.ewma_ms is not None else None,
                    'loss': st.loss,
                    'snapshot_age_seconds':
                        round(now - st.last_mono, 3)
                        if st.last_mono is not None else None,
                    'clock_offset': st.offset,
                    'comm_bytes_per_step':
                        {a: int(v) for a, v in st.comm_rate.items()},
                    'comm_bytes_total': dict(st.comm_total),
                    'memory_bytes': st.mem_bytes,
                    'memory_peak_bytes': st.mem_peak,
                    'counters': dict(st.counters),
                    'spans_ms': dict(st.spans_ms),
                    'snapshots': st.snapshots,
                    'flags': sorted(st.flags),
                }
            anomalies = list(self.anomalies)[-32:]
        walls = [v['wall_ms'] for v in ranks.values()
                 if v['wall_ms'] is not None]
        steps = [v['step'] for v in ranks.values()
                 if v['step'] is not None]
        med = _median(walls) if walls else None
        for v in ranks.values():
            v['skew_ms'] = round(v['wall_ms'] - med, 3) \
                if (med is not None and v['wall_ms'] is not None) \
                else None
        return {
            'ranks': ranks,
            'fleet': {
                'ranks': len(ranks),
                'max_step': max(steps) if steps else None,
                'min_step': min(steps) if steps else None,
                'median_wall_ms': round(med, 3)
                if med is not None else None,
                'snapshots_total': self.snapshots_total,
            },
            'anomalies': anomalies,
        }

    def straggler(self, worst=False):
        """The suspected straggler: the rank currently flagged by the
        skew/stale detectors (stale outranks slow — a silent rank is
        the stronger signal). With ``worst=True`` (the watchdog's stall
        path — SOMEBODY is suspect) falls back to the slowest/most-
        stale rank even when no detector threshold tripped. Returns
        ``{'rank', 'reason', 'snapshot_age_seconds', 'step',
        'max_step', 'wall_ms'}`` or None (fewer than 2 ranks)."""
        now = _time.monotonic()
        with self._lock:
            if len(self.ranks) < 2:
                return None
            items = list(self.ranks.items())
        steps = [st.step for _r, st in items if st.step is not None]
        max_step = max(steps) if steps else None

        def info(rank, st, reason, flagged):
            out = {
                'rank': rank, 'reason': reason, 'flagged': flagged,
                'snapshot_age_seconds': round(now - st.last_mono, 3)
                if st.last_mono is not None else None,
                'step': st.step, 'max_step': max_step,
                'wall_ms': st.wall_ms,
            }
            if st.compiling:
                # the rank's own heartbeat says it is mid-compile: the
                # verdict layer upgrades this straggler to COMPILING
                out['compiling'] = dict(st.compiling)
            return out

        stale = [(now - st.last_mono, r, st) for r, st in items
                 if 'fleet.stale' in st.flags]
        if stale:
            age, r, st = max(stale)
            return info(r, st, 'stale', True)
        slow = [(st.wall_ms, r, st) for r, st in items
                if 'fleet.straggler' in st.flags
                and st.wall_ms is not None]
        if slow:
            _w, r, st = max(slow)
            return info(r, st, 'slow', True)
        if not worst:
            return None
        # stall fallback (flagged=False: suspicion, not a tripped
        # detector): rank the fleet by staleness, then slowness
        aged = [(now - st.last_mono, r, st) for r, st in items
                if st.last_mono is not None]
        if aged:
            age, r, st = max(aged)
            med = _median([a for a, _r, _s in aged])
            if age > max(2.0 * med, 0.001):
                return info(r, st, 'stale', False)
        walls = [(st.wall_ms, r, st) for r, st in items
                 if st.wall_ms is not None]
        if walls:
            _w, r, st = max(walls)
            return info(r, st, 'slow', False)
        return None

    def refresh_gauges(self):
        """Re-export the staleness-sensitive gauges for EVERY rank —
        called at /metrics scrape time (O(world) per scrape). Ingest
        only writes the ingesting rank's row, so a rank that went
        SILENT would otherwise freeze at the ~0 age stamped by its own
        last beat — unalertable exactly when it matters."""
        if not _telem['on']:
            return
        now = _time.monotonic()
        # writes under the monitor lock: a concurrent remove_ranks
        # must not interleave between the state read and the gauge
        # write and have a departed rank's row resurrected
        with self._lock:
            _metrics.set_gauge('mxnet_tpu_fleet_ranks', len(self.ranks))
            for r, st in self.ranks.items():
                if st.last_mono is not None:
                    _metrics.set_gauge(
                        'mxnet_tpu_fleet_snapshot_age_seconds',
                        round(now - st.last_mono, 3), rank=r)

    # per-rank metric rows retired when their rank departs — a ghost
    # rank frozen at its last exported values would otherwise haunt
    # every /metrics scrape (and its never-growing snapshot age reads
    # as "perfectly fresh" to the very alert it should trip)
    _PER_RANK_METRICS = (
        'mxnet_tpu_fleet_last_step', 'mxnet_tpu_fleet_step_ms',
        'mxnet_tpu_fleet_step_skew_ms', 'mxnet_tpu_fleet_step_seconds',
        'mxnet_tpu_fleet_loss', 'mxnet_tpu_fleet_clock_offset_seconds',
        'mxnet_tpu_fleet_snapshot_age_seconds',
        'mxnet_tpu_fleet_comm_bytes', 'mxnet_tpu_fleet_memory_bytes',
    )

    def remove_ranks(self, ranks):
        """Evict departed ranks (the membership ``remove_peers``
        mirror, wired via ``on_peers_removed``): a preempted rank must
        not haunt the fleet view, skew the median, stay latched as the
        stale straggler in every future stall verdict, or linger as
        frozen gauge rows in the registry."""
        with self._lock:
            # registry retirement INSIDE the lock: an in-flight
            # _export/refresh_gauges serializes against this, so it
            # either finishes first (rows then removed here) or sees
            # the pruned rank dict (writes nothing) — never a
            # resurrected ghost row
            for r in ranks:
                self.ranks.pop(int(r), None)
                for name in self._PER_RANK_METRICS:
                    _metrics.remove_series(name, rank=int(r))
            if _telem['on']:
                _metrics.set_gauge('mxnet_tpu_fleet_ranks',
                                   len(self.ranks))

    def clear(self):
        with self._lock:
            self.ranks.clear()
            self.anomalies.clear()
            self.snapshots_total = 0


def _median(vals):
    return float(statistics.median(vals)) if vals else 0.0


# ---------------------------------------------------------------------------
# process-global wiring
# ---------------------------------------------------------------------------

_monitor = None
# RLock: monitor() is reachable from crash-time verdict paths (watchdog
# stall report via stall_verdict) — same re-entry rationale as
# flight._recorder_lock
_monitor_lock = threading.RLock()


def monitor(create=False):
    """The process-global FleetMonitor (the coordinator's merge +
    detector state). None until ``attach()`` — or ``create=True`` —
    built it."""
    global _monitor
    if _monitor is None and create:
        with _monitor_lock:
            if _monitor is None:
                _monitor = FleetMonitor()
    return _monitor


def _provider_for(ms):
    def provider():
        snap = local_snapshot()
        if snap is not None:
            off = ms.clock_offset()
            if off is not None:
                snap['offset'] = [round(off[0], 6), round(off[1], 6)]
        return snap
    return provider


def attach(membership=None):
    """Wire fleet telemetry onto the membership layer: this rank's
    heartbeats carry ``local_snapshot()``; on the coordinator the
    process-global ``FleetMonitor`` ingests every rank's snapshots.
    Idempotent; re-call after a ``become_coordinator`` promotion so the
    new coordinator starts merging. Returns the monitor (None on
    non-coordinator ranks), or None without a membership layer."""
    if membership is None:
        from ..parallel import dist as _dist
        membership = _dist.membership()
    if membership is None:
        return None
    membership.telemetry_provider = _provider_for(membership)
    if membership.is_coordinator:
        mon = monitor(create=True)
        # the REAL heartbeat period (kwarg or knob) drives the auto
        # stale threshold — the env default must not misjudge a
        # membership beating on a different cadence
        mon.set_heartbeat(membership.heartbeat_seconds)
        membership.on_snapshot = mon.ingest
        # remove_peers mirrors into the monitor: a departed rank must
        # not stay latched as the stale straggler forever
        membership.on_peers_removed = mon.remove_ranks
        # beat replies carry the flagged straggler summary, so WORKER
        # watchdogs (where (world-1)/world of wedges happen) can name
        # the suspect from their cached view — not just rank 0
        membership.verdict_provider = mon.straggler
        # the coordinator heartbeats too (short-circuited locally), so
        # its own snapshot lands in the view alongside the workers'
        return mon
    return None


def detach(membership=None):
    """Unhook the provider/monitor (tests; symmetric with attach)."""
    if membership is None:
        from ..parallel import dist as _dist
        membership = _dist.membership()
    if membership is not None:
        membership.telemetry_provider = None
        membership.on_snapshot = None
        membership.on_peers_removed = None
        membership.verdict_provider = None


def dump_rank_trace(path, membership=None):
    """One rank's chrome trace (balanced + thread metadata) stamped
    with ``rank`` and ``clock_offset_us`` — the per-rank input
    ``tools/stitch_traces.py`` merges into a fleet-wide timeline."""
    if membership is None:
        from ..parallel import dist as _dist
        membership = _dist.membership()
    doc = {'traceEvents': _trace.chrome_events(flush_open=True,
                                               metadata=True),
           'displayTimeUnit': 'ms',
           'pid': os.getpid(),
           'rank': membership.rank if membership is not None else 0}
    off = membership.clock_offset() if membership is not None else (0.0,
                                                                    0.0)
    if off is not None:
        doc['clock_offset_us'] = round(off[0] * 1e6, 3)
        doc['clock_rtt_us'] = round(off[1] * 1e6, 3)
    from ..serialization import atomic_write_file
    atomic_write_file(path, json.dumps(doc).encode())
    return path
