"""Per-process observability endpoint: /metrics, /healthz, /flight.

A fleet is only operable if every replica answers "how are you" over
plain HTTP — the ROADMAP's serving item needs per-replica health and
metrics endpoints, and a Prometheus scraper should not have to link
against the framework. This is a tiny stdlib TCP server in the same
idiom as the membership/replica side channels (``parallel.dist``): it
never touches the ICI collectives (a wedged collective must not make
the *diagnosis* port unreachable too), binds loopback-only by default,
and answers with a BOUNDED pool of handler threads — a scrape storm
degrades to refused connections, never to unbounded thread growth.

Endpoints (GET only):

- ``/metrics``  — the metrics registry in Prometheus text exposition
  format (exactly ``telemetry.prometheus()``; empty until
  ``MXNET_TPU_TELEMETRY=1`` arms the registry).
- ``/healthz``  — JSON health document: membership view, the
  classified stall verdict (``resilience.elastic.stall_verdict``),
  last completed + last committed step, and — on the membership
  coordinator — the merged fleet view with per-rank skew.
- ``/flight``   — the flight recorder's post-mortem document on
  demand (the same JSON a crash dump writes; loss reads skipped so a
  wedged device can never wedge the endpoint).

Armed by ``MXTPU_METRICS_PORT`` (0 = off; rank r serves on base + r so
multi-process hosts do not collide) — ``parallel.dist`` starts it
alongside the membership layer, or call ``start()`` directly.
"""
from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time as _time

__all__ = ['TelemetryServer', 'start', 'stop', 'get', 'maybe_start']

_log = logging.getLogger('mxnet_tpu.telemetry')

_MAX_REQUEST_BYTES = 8192


class TelemetryServer:
    """One process's observability endpoint. ``port=0`` picks a free
    port (tests); ``max_handlers`` bounds concurrent handler threads —
    excess connections are closed immediately (a scraper retries; the
    process never grows a thread per stuck client)."""

    def __init__(self, port=0, bind=None, membership=None,
                 max_handlers=4, start=True):
        from .. import config as _config
        self.bind = bind if bind is not None \
            else _config.get('MXTPU_METRICS_BIND')
        self.membership = membership
        self.max_handlers = int(max_handlers)
        self._slots = threading.Semaphore(self.max_handlers)
        self._stop = threading.Event()
        self._server = None
        self._thread = None
        self.port = int(port)
        # up to max_handlers handler threads bump the request counter
        # concurrently — a bare += would silently lose counts
        self._lock = threading.Lock()
        self.requests = 0
        if start:
            self.start()

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        if self._server is not None:
            return self
        self._stop.clear()
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self.bind, self.port))
        self.port = srv.getsockname()[1]
        srv.listen(16)
        srv.settimeout(0.2)
        self._server = srv
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name='mxtpu-telemetry-http')
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None
        # retire the socket under the lock: an accept loop that
        # outlived its join timeout reads the handle through the same
        # lock — live socket or None, never a torn in-between
        with self._lock:
            srv, self._server = self._server, None
        if srv is not None:
            try:
                srv.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- accept loop -------------------------------------------------------

    def _serve(self):
        with self._lock:
            srv = self._server
        while srv is not None and not self._stop.is_set():
            try:
                conn, _addr = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            if not self._slots.acquire(blocking=False):
                # at capacity: shed load instead of queueing threads —
                # the scraper sees a reset and retries next interval
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            try:
                t = threading.Thread(target=self._handle_conn,
                                     args=(conn,), daemon=True,
                                     name='mxtpu-telemetry-req')
                t.start()
            except Exception:
                # thread exhaustion: give the slot BACK (the release
                # lives in _handle_conn, which never ran — leaking here
                # would brick the endpoint after max_handlers failures)
                # and keep accepting; the client retries next interval
                self._slots.release()
                try:
                    conn.close()
                except OSError:
                    pass

    # bodies a subclass accepts on POST (0 = GET-only, the telemetry
    # default: a scraper has no business sending us bytes)
    max_body_bytes = 0

    def _handle_conn(self, conn):
        try:
            conn.settimeout(5.0)
            with conn:
                req = self._read_request(conn)
                if req is None:
                    return
                method, path, body = req
                with self._lock:
                    self.requests += 1
                status, ctype, resp = self._route(path, method, body)
                head = (f'HTTP/1.0 {status}\r\n'
                        f'Content-Type: {ctype}\r\n'
                        f'Content-Length: {len(resp)}\r\n'
                        f'Connection: close\r\n\r\n')
                conn.sendall(head.encode() + resp)
        except (OSError, ValueError):
            pass
        finally:
            self._slots.release()

    def _read_request(self, conn, deadline_seconds=5.0):
        """(method, path, body) of a GET/POST request, or None for
        anything malformed. Reads at most _MAX_REQUEST_BYTES of header
        plus ``max_body_bytes`` of declared body within ONE overall
        wall deadline — a trickling client (one byte per recv, each
        resetting the socket timeout) cannot hold a handler slot past
        the deadline. A body larger than the bound returns body=None
        (413 upstream) instead of buffering unboundedly."""
        deadline = _time.monotonic() + deadline_seconds
        data = b''
        while b'\r\n\r\n' not in data and len(data) < _MAX_REQUEST_BYTES:
            if _time.monotonic() > deadline:
                return None
            b = conn.recv(4096)
            if not b:
                break
            data += b
        head, _, rest = data.partition(b'\r\n\r\n')
        lines = head.split(b'\r\n')
        parts = lines[0].decode('latin-1', 'replace').split()
        if len(parts) < 2 or parts[0] not in ('GET', 'POST'):
            return None
        method, path = parts[0], parts[1].split('?', 1)[0]
        if method == 'GET':
            return method, path, b''
        length = 0
        for ln in lines[1:]:
            k, _, v = ln.decode('latin-1', 'replace').partition(':')
            if k.strip().lower() == 'content-length':
                try:
                    length = int(v.strip())
                except ValueError:
                    return None
        if length > self.max_body_bytes:
            return method, path, None
        body = rest[:length]
        while len(body) < length:
            if _time.monotonic() > deadline:
                return None
            b = conn.recv(min(65536, length - len(body)))
            if not b:
                break
            body += b
        return method, path, body

    # -- routing -----------------------------------------------------------

    def _route(self, path, method='GET', body=b''):
        if method != 'GET':
            return ('405 Method Not Allowed', 'text/plain',
                    b'GET only\n')
        try:
            if path == '/metrics':
                from . import fleet as _fleet
                from . import metrics as _metrics
                mon = _fleet.monitor()
                if mon is not None:
                    # snapshot-age gauges refresh at scrape time: a
                    # SILENT rank's age must keep growing even though
                    # its own ingests (the only per-rank writers)
                    # stopped — that growing age is the alert signal
                    mon.refresh_gauges()
                return ('200 OK',
                        'text/plain; version=0.0.4; charset=utf-8',
                        _metrics.prometheus().encode())
            if path == '/healthz':
                doc = self.health()
                status = '200 OK' if doc.get('status') == 'ok' \
                    else '503 Service Unavailable'
                return (status, 'application/json',
                        json.dumps(doc, default=str).encode())
            if path == '/flight':
                from . import flight as _flight
                doc = _flight.get().snapshot(resolve_loss=False)
                return ('200 OK', 'application/json',
                        json.dumps(doc, default=str).encode())
            return ('404 Not Found', 'text/plain',
                    b'endpoints: /metrics /healthz /flight\n')
        except Exception as e:
            _log.exception("telemetry endpoint %s failed", path)
            return ('500 Internal Server Error', 'text/plain',
                    repr(e).encode())

    def health(self):
        """The /healthz document (also callable in-process). Reads only
        local state — membership views, the flight recorder, checkpoint
        bookkeeping — never a collective or a device sync."""
        from . import fleet as _fleet, flight as _flight
        from . import metrics as _metrics
        from ..base import telem_flags as _telem
        from . import trace as _trace
        doc = {'status': 'ok', 'pid': os.getpid(),
               'time': round(_time.time(), 3),
               'telemetry': bool(_telem['on']),
               'trace': bool(_trace.enabled())}
        ms = self.membership
        if ms is None:
            from ..parallel import dist as _dist
            ms = _dist.membership()
        if ms is not None:
            doc['rank'] = ms.rank
            doc['membership'] = ms.view()
            off = ms.clock_offset()
            if off is not None:
                doc['clock_offset_seconds'] = round(off[0], 6)
        rec = _flight.get().last_step_record()
        if rec is not None:
            doc['last_step'] = rec.get('step')
            doc['last_step_wall_ms'] = rec.get('interval_ms')
        sps = _metrics.recent_samples_per_second(60.0)
        if sps is not None:
            doc['samples_per_second'] = sps
        try:
            # live/peak device memory + host RSS, computed on demand
            # (cold path; tracked-array fallback where the backend
            # exposes no allocator stats) — a fleet operator should see
            # the pressure BEFORE the OOM, not in its post-mortem
            from . import memory as _memory
            doc['memory'] = _memory.health_fields()
        except Exception:
            doc['memory'] = None
        try:
            # last-compile info + the open compile window + persistent-
            # cache hit/miss/bytes (cold path, computed on demand)
            from . import compile as _compile
            doc['compile'] = _compile.health_fields()
        except Exception:
            doc['compile'] = None
        try:
            from ..checkpoint import last_committed_step
            doc['last_committed_step'] = last_committed_step()
        except Exception:
            doc['last_committed_step'] = None
        try:
            from ..resilience.elastic import stall_verdict
            doc['verdict'] = stall_verdict(ms)
        except Exception:
            doc['verdict'] = None
        mon = _fleet.monitor()
        if mon is not None:
            doc['fleet'] = mon.view()
        v = doc.get('verdict') or {}
        s = v.get('straggler') or {}
        if v.get('lost'):
            doc['status'] = 'peer_loss'
        elif s.get('flagged') and s.get('rank') == doc.get('rank'):
            # a detector tripped naming THIS rank: degrade our own
            # health so an external supervisor sees the same suspect
            doc['status'] = 'straggler'
        return doc


# ---------------------------------------------------------------------------
# process-global instance
# ---------------------------------------------------------------------------

_server = None
_server_lock = threading.RLock()


def get():
    """The process-global TelemetryServer, or None (disarmed)."""
    return _server


def start(port=None, rank=0, membership=None, **kwargs):
    """Start (or return) the process-global endpoint. ``port=None``
    reads ``MXTPU_METRICS_PORT`` + rank; an explicit port is used
    as-is."""
    global _server
    with _server_lock:
        if _server is not None:
            return _server
        if port is None:
            from .. import config as _config
            base = int(_config.get('MXTPU_METRICS_PORT') or 0)
            if not base:
                return None
            port = base + int(rank)
        _server = TelemetryServer(port=int(port), membership=membership,
                                  **kwargs)
    return _server


def stop():
    global _server
    with _server_lock:
        if _server is not None:
            _server.stop()
            _server = None


def maybe_start(rank=None, membership=None):
    """Arm the endpoint iff MXTPU_METRICS_PORT is set (the
    ``parallel.dist`` bring-up hook). Never raises — observability must
    not take down training."""
    try:
        if rank is None:
            from .. import config as _config
            rank = membership.rank if membership is not None \
                else max(0, _config.get('MXNET_TPU_PROC_ID'))
        return start(rank=rank, membership=membership)
    except Exception:
        _log.exception("telemetry endpoint failed to start")
        return None
