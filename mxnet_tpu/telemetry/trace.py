"""Step-level span tracing: nested scopes over the training-step lifecycle.

The metrics registry (``telemetry.metrics``) answers "how much"; this
module answers "WHEN, and inside what". A ``span("name", **labels)``
context manager emits chrome-trace ``'B'``/``'E'`` events into a
lock-free per-thread ring buffer; ``chrome_events()`` merges every
thread's ring into one balanced, deterministic ``traceEvents`` stream
that chrome://tracing / Perfetto load directly (and that
``profiler.dump()`` folds together with its own op rows and the
telemetry ``'C'`` counter tracks).

Design constraints, in order:

- **Disarmed cost is one attribute check.** ``span()`` reads the
  module gate and returns a shared no-op singleton; nothing is
  allocated, nothing is recorded (``MXTPU_TRACE=1`` arms it, or
  ``trace.enable()``).
- **Lock-free when armed.** Each thread appends to its own
  preallocated ring (only ring *creation* takes a lock). No
  cross-thread contention on the hot path; a full ring overwrites its
  oldest events and counts the spans it dropped
  (``mxnet_tpu_trace_dropped_spans_total``).
- **Dumps are always valid.** Ring overwrite and crash-time flushes
  both produce unbalanced B/E streams; ``balance_events()`` repairs
  them at export time (orphan ``E`` dropped, open ``B`` closed with a
  synthetic ``E`` marked ``{'flushed': True}``) so every dump passes
  ``tools/check_trace.py``.
- **Stable pid/tid mapping.** Threads get small sequential tids in
  first-span order (process-lifetime, shared with profiler.py via
  ``tid_for_current_thread()``), plus ``'M'`` thread-name metadata —
  the merged trace has one coherent tid space instead of raw idents.

Span timing: ``ts`` is ``time.time()`` microseconds (the same timebase
as profiler.py and the telemetry 'C' events, so merged streams align);
per-span durations additionally aggregate into a per-thread
``{name: [count, total_us, self_us]}`` table — *self* time excludes
child spans, which is what ``telemetry.attribution`` buckets so nested
spans never double-count. ``drain_aggregates()`` (the flight
recorder's per-step hook) swaps those tables out.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time as _time

from ..base import telem_flags as _telem

__all__ = [
    'enable', 'disable', 'enabled', 'span', 'instant', 'complete',
    'chrome_events', 'thread_metadata', 'balance_events', 'dump',
    'drain_aggregates', 'open_spans', 'stats', 'clear',
    'set_ring_capacity', 'tid_for_current_thread',
]

_state = {'on': False}
_DEFAULT_RING = None          # resolved lazily from MXTPU_TRACE_RING

# thread registry: ring creation (rare) locks; appends never do.
# RLock: span() runs inside the SIGTERM preemption save (checkpoint
# spans) — a signal interrupting THIS thread mid-registration must
# re-enter the registry, not self-deadlock on a plain Lock (the PR-8
# bug class; enforced by tools/mxtpu_lint's signal-safety rule).
_rings_lock = threading.RLock()
_rings = []                   # every _Ring ever created, in tid order
_tids = {}                    # thread ident -> (tid, name)
_local = threading.local()
_gen = [0]                    # bumped by clear(): stale thread-local
                              # rings re-register on their next span
# telemetry sync state: last counter values already pushed to the
# metrics registry (counters must only ever move forward)
_synced = {'spans': 0, 'dropped': 0}


def enable():
    _state['on'] = True


def disable():
    _state['on'] = False


def enabled() -> bool:
    return _state['on']


def _ring_capacity() -> int:
    global _DEFAULT_RING
    if _DEFAULT_RING is None:
        from .. import config as _config
        with _rings_lock:
            if _DEFAULT_RING is None:
                _DEFAULT_RING = max(
                    16, int(_config.get('MXTPU_TRACE_RING')))
    return _DEFAULT_RING


def set_ring_capacity(n):
    """Events per thread ring for rings created AFTER this call (pass
    None to restore the MXTPU_TRACE_RING config default). clear() drops
    existing rings, so tests set capacity + clear to take effect."""
    global _DEFAULT_RING
    with _rings_lock:
        _DEFAULT_RING = None if n is None else max(16, int(n))


class _Ring:
    """One thread's event buffer. Owned exclusively by its thread:
    append() is plain list indexing, no lock. `stack` tracks the open
    spans (name, t0_us, child_us) for nesting/self-time; `agg` is the
    per-step aggregation table drain_aggregates() swaps out."""

    __slots__ = ('events', 'cap', 'n', 'tid', 'name', 'stack', 'agg',
                 'spans_total', 'dropped', 'gen')

    def __init__(self, cap, tid, name):
        self.gen = _gen[0]
        self.cap = cap
        self.events = [None] * cap
        self.n = 0
        self.tid = tid
        self.name = name
        self.stack = []
        self.agg = {}
        self.spans_total = 0
        self.dropped = 0

    def append(self, ev):
        slot = self.n % self.cap
        old = self.events[slot]
        if old is not None and old['ph'] == 'B':
            # overwriting a begin event drops that whole span from the
            # ring (balance_events drops that span's orphan 'E' at
            # export)
            self.dropped += 1
        self.events[slot] = ev
        self.n += 1

    def snapshot(self):
        if self.n <= self.cap:
            return list(self.events[:self.n])
        i = self.n % self.cap
        return self.events[i:] + self.events[:i]


def tid_for_current_thread() -> int:
    """Small sequential tid for this thread (assigned on first use,
    stable for the process lifetime; shared with profiler.py so both
    event sources land in one coherent tid space). Registers only the
    tid — no ring is built until this thread records a span, so
    profiler-only threads cost a dict entry, not a ring buffer."""
    tid = getattr(_local, 'tid', None)
    if tid is None:
        t = threading.current_thread()
        with _rings_lock:
            ent = _tids.get(t.ident)
            if ent is None:
                tid = len(_tids) + 1
                _tids[t.ident] = (tid, t.name)
            else:
                tid = ent[0]
        _local.tid = tid
    return tid


def _ring() -> _Ring:
    r = getattr(_local, 'ring', None)
    if r is not None and r.gen != _gen[0]:
        r = None
    if r is None:
        tid = tid_for_current_thread()
        name = threading.current_thread().name
        with _rings_lock:
            r = _Ring(_ring_capacity(), tid, name)
            _rings.append(r)
        _local.ring = r
    return r


def _now_us() -> float:
    return _time.time() * 1e6


@contextlib.contextmanager
def _rings_locked(timeout=2.0):
    """Best-effort lock for the read/export paths. Same-thread signal
    re-entry is already safe (the registry lock is reentrant), but a
    crash-time dump must also survive a wedged holder on ANOTHER
    thread: after `timeout` we proceed lock-free — the holder that
    timed us out is interrupted or blocked, not mutating. Writers
    (_ring, tid assignment, clear) keep blocking acquires; their
    critical sections never block."""
    got = _rings_lock.acquire(timeout=timeout)
    try:
        yield
    finally:
        if got:
            _rings_lock.release()


class _NullSpan:
    """Shared disarmed span: enter/exit allocate nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span:
    __slots__ = ('name', 'args', 'ring', 't0')

    def __init__(self, name, args):
        self.name = name
        self.args = args

    def __enter__(self):
        r = _ring()
        # lint: lockset-race-ok a _Span instance is created, entered and exited by ONE thread (span() builds a fresh instance per use); nothing shares it
        self.ring = r
        t0 = _now_us()
        # lint: lockset-race-ok same single-thread span instance as above
        self.t0 = t0
        ev = {'name': self.name, 'cat': 'span', 'ph': 'B', 'ts': t0,
              'tid': r.tid}
        if self.args:
            ev['args'] = self.args
        r.append(ev)
        r.stack.append([self.name, t0, 0.0])
        return self

    def __exit__(self, *exc):
        r = self.ring
        t1 = _now_us()
        r.append({'name': self.name, 'cat': 'span', 'ph': 'E', 'ts': t1,
                  'tid': r.tid})
        dur = max(0.0, t1 - self.t0)
        child = 0.0
        if r.stack and r.stack[-1][0] == self.name:
            child = r.stack.pop()[2]
        if r.stack:
            r.stack[-1][2] += dur          # credit the parent's child time
        st = r.agg.get(self.name)
        self_us = max(0.0, dur - child)
        if st is None:
            r.agg[self.name] = [1, dur, self_us]
        else:
            st[0] += 1
            st[1] += dur
            st[2] += self_us
        r.spans_total += 1
        return False


def span(name, **labels):
    """Nested timing scope. Armed: emits a chrome 'B'/'E' pair into
    this thread's ring and aggregates (count, total, self) time under
    `name`. Disarmed: returns a shared no-op (one dict check)."""
    if not _state['on']:
        return _NULL
    return _Span(name, labels or None)


def instant(name, **args):
    """One chrome instant event ('i'), e.g. a collective annotation
    carrying its analytic byte count."""
    if not _state['on']:
        return
    r = _ring()
    ev = {'name': name, 'cat': 'span', 'ph': 'i', 'ts': _now_us(),
          'tid': r.tid, 's': 't'}
    if args:
        ev['args'] = args
    r.append(ev)


def complete(name, ts_us, dur_us, **args):
    """One chrome complete event ('X') for an externally measured
    interval (e.g. folding in durations from another trace source)."""
    if not _state['on']:
        return
    r = _ring()
    ev = {'name': name, 'cat': 'span', 'ph': 'X', 'ts': float(ts_us),
          'dur': max(0.0, float(dur_us)), 'tid': r.tid}
    if args:
        ev['args'] = args
    r.append(ev)


# ---------------------------------------------------------------------------
# export / merge
# ---------------------------------------------------------------------------

def balance_events(events, close_ts=None):
    """Repair a chrome event stream so every 'B' has a matching 'E':
    per (pid, tid), orphan 'E' events (their 'B' was overwritten or
    predates the stream) are dropped and still-open 'B' events get a
    synthetic closing 'E' at `close_ts` (default: the stream's max ts)
    tagged args={'flushed': True}. Non-B/E events pass through."""
    if close_ts is None:
        close_ts = max((e.get('ts', 0.0) for e in events), default=0.0)
    out = []
    stacks = {}
    for ev in events:
        ph = ev.get('ph')
        if ph == 'B':
            stacks.setdefault((ev.get('pid'), ev.get('tid')), []).append(ev)
            out.append(ev)
        elif ph == 'E':
            stack = stacks.get((ev.get('pid'), ev.get('tid')))
            if not stack:
                continue                   # orphan E: its B was dropped
            stack.pop()
            out.append(ev)
        else:
            out.append(ev)
    for (pid, tid), stack in sorted(
            stacks.items(), key=lambda kv: (str(kv[0][0]), str(kv[0][1]))):
        for ev in reversed(stack):         # close innermost first
            out.append({'name': ev['name'], 'cat': ev.get('cat', 'span'),
                        'ph': 'E', 'ts': max(close_ts, ev.get('ts', 0.0)),
                        'pid': pid, 'tid': tid, 'args': {'flushed': True}})
    return out


def thread_metadata(pid=None):
    """Chrome 'M' thread_name events for every registered thread (the
    stable small-int tid -> thread name mapping — includes
    profiler-only threads that never recorded a span)."""
    pid = os.getpid() if pid is None else pid
    with _rings_locked():
        named = sorted(_tids.values())
    return [{'name': 'thread_name', 'ph': 'M', 'pid': pid, 'tid': tid,
             'args': {'name': name}} for tid, name in named]


def chrome_events(flush_open=True, metadata=False, sync=True):
    """Merged span events from every thread ring: balanced, pid/tid
    stamped, sorted by timestamp with a deterministic tie order (ring
    creation order — two exports of the same data are identical).
    `sync=False` skips the metrics-registry push — crash dumps from a
    signal handler must not touch the registry locks the interrupted
    frame may hold."""
    pid = os.getpid()
    with _rings_locked():
        rings = list(_rings)
    now = _now_us()
    events = []
    for r in rings:
        evs = [dict(e, pid=pid) for e in r.snapshot()]
        if flush_open:
            evs = balance_events(evs, close_ts=now)
        events.append(evs)
    merged = [e for evs in events for e in evs]
    # stable sort: per-ring order is already correct; ties across rings
    # resolve by ring (creation) order, which never changes
    merged.sort(key=lambda e: e.get('ts', 0.0))
    if sync:
        _sync_metrics()
    if metadata:
        return thread_metadata(pid) + merged
    return merged


def dump(path):
    """One standalone chrome://tracing JSON of every thread's spans
    (balanced + thread-name metadata), written atomically."""
    doc = {'traceEvents': chrome_events(flush_open=True, metadata=True),
           'displayTimeUnit': 'ms'}
    from ..serialization import atomic_write_file
    atomic_write_file(path, json.dumps(doc).encode())
    return path


# ---------------------------------------------------------------------------
# aggregation / introspection (flight recorder + attribution hooks)
# ---------------------------------------------------------------------------

def drain_aggregates(consumer_tid=None):
    """Merged {name: {'count', 'total_ms', 'self_ms',
    'consumer_self_ms'}} across every thread since the previous drain,
    clearing each ring's table (the per-step summary the flight
    recorder snapshots). `consumer_self_ms` is the self time recorded
    ON the `consumer_tid` thread — the step loop's own wall time, which
    is what attribution may bill against step intervals; work on other
    threads (prefetch producers, DataLoader workers, the checkpoint
    writer) overlaps the step and only counts in the totals. With
    `consumer_tid=None` every thread counts as the consumer."""
    with _rings_locked():
        rings = list(_rings)
    merged = {}
    for r in rings:
        agg, r.agg = r.agg, {}             # GIL-atomic swap
        on_consumer = consumer_tid is None or r.tid == consumer_tid
        for name, (count, total, self_us) in agg.items():
            st = merged.get(name)
            if st is None:
                st = merged[name] = {'count': 0, 'total_ms': 0.0,
                                     'self_ms': 0.0,
                                     'consumer_self_ms': 0.0}
            st['count'] += count
            st['total_ms'] += total / 1e3
            st['self_ms'] += self_us / 1e3
            if on_consumer:
                st['consumer_self_ms'] += self_us / 1e3
    return merged


def open_spans():
    """Currently open spans across all threads, outermost first:
    [{'name', 'thread', 'tid', 'age_ms'}] — the crash-time view of
    what every thread was inside when the process wedged."""
    now = _now_us()
    with _rings_locked():
        rings = list(_rings)
    out = []
    for r in rings:
        for name, t0, _child in list(r.stack):
            out.append({'name': name, 'thread': r.name, 'tid': r.tid,
                        'age_ms': round((now - t0) / 1e3, 3)})
    return out


def stats():
    """{'spans_total', 'dropped_spans_total', 'ring_depth', 'threads'}
    across every ring (ring_depth = events currently buffered)."""
    with _rings_locked():
        rings = list(_rings)
    return {
        'spans_total': sum(r.spans_total for r in rings),
        'dropped_spans_total': sum(r.dropped for r in rings),
        'ring_depth': sum(min(r.n, r.cap) for r in rings),
        'threads': len(rings),
    }


def _sync_metrics():
    """Push ring statistics into the metrics registry (counter deltas
    only — counters must be monotonic across repeated syncs)."""
    if not _telem['on']:
        return
    from . import metrics as _metrics
    st = stats()
    with _rings_locked():
        d_spans = st['spans_total'] - _synced['spans']
        d_dropped = st['dropped_spans_total'] - _synced['dropped']
        if d_spans > 0:
            _synced['spans'] = st['spans_total']
        if d_dropped > 0:
            _synced['dropped'] = st['dropped_spans_total']
    if d_spans > 0:
        _metrics.inc('mxnet_tpu_trace_spans_total', d_spans)
    if d_dropped > 0:
        _metrics.inc('mxnet_tpu_trace_dropped_spans_total', d_dropped)
    _metrics.set_gauge('mxnet_tpu_trace_ring_depth', st['ring_depth'])


def clear():
    """Drop every ring and aggregate. The tid map survives (tids stay
    stable for the process lifetime) and so does the enable state.
    Live threads holding a dropped ring re-register on their next span
    (generation check in _ring), so nothing records into limbo."""
    with _rings_lock:
        _gen[0] += 1
        _rings.clear()
        _synced['spans'] = 0
        _synced['dropped'] = 0


# config gate (read at import; declared in config.py)
from .. import config as _config_mod  # noqa: E402

if _config_mod.get('MXTPU_TRACE'):
    enable()
