"""Crash-time flight recorder: the last N steps, always ready to dump.

A crash or a stall at step 48,213 of a multi-hour run is only
debuggable if the process carried its own black box: what the recent
steps spent their time on, what the loss was doing, whether the
non-finite guard was tripping, which faults fired. The flight recorder
is that box — a bounded ring of per-step summaries (span self-times
drained from ``telemetry.trace``, loss, guard flag) plus a bounded log
of notable events (fault injections, guard trips, rollbacks, watchdog
stalls), dumped as ONE atomic JSON:

- by the watchdog when the step heartbeat stalls,
- by the non-finite guard's rollback ladder,
- at interpreter exit (``atexit``) and on fatal signals
  (SIGTERM/SIGABRT, chaining any previously installed handler —
  e.g. the checkpoint preemption hook keeps working),
- on demand via ``flight.dump(reason=...)``.

The dump also embeds the balanced chrome ``traceEvents`` stream and
every thread's currently-OPEN spans, so a hang names the exact frame
each thread was inside (``tools/check_trace.py`` validates the
embedded stream like any other trace dump).

Armed together with tracing (``MXTPU_TRACE=1``): ``record_step()`` is
a no-op while tracing is disarmed, so an untraced run pays one dict
check per step. Loss values are resolved one step deferred — step N's
device scalar is read when step N+1 is recorded, after its program has
long finished — so recording never adds a host sync (the same
deferred-read contract as ``resilience.NonFiniteGuard``).
"""
from __future__ import annotations

import atexit
import collections
import contextlib
import json
import os
import signal as _signal
import threading
import time as _time

from ..base import telem_flags as _telem
from . import compile as _compile
from . import memory as _memory
from . import trace as _trace

__all__ = ['FlightRecorder', 'get', 'record_step', 'note',
           'annotate_last', 'dump', 'default_dump_path',
           'install_crash_hooks']


class FlightRecorder:
    """Bounded ring of step summaries + event log. One process-global
    instance (``flight.get()``); tests may build their own."""

    def __init__(self, capacity=None, event_capacity=256):
        if capacity is None:
            from .. import config as _config
            capacity = _config.get('MXTPU_FLIGHT_STEPS')
        self.capacity = max(1, int(capacity))
        self._steps = collections.deque(maxlen=self.capacity)
        self._events = collections.deque(maxlen=int(event_capacity))
        # RLock, same signal-safety rationale as the module-level
        # _recorder_lock: note() and _pop_pending() run inside the
        # SIGTERM preemption save and the atexit dump — a signal
        # landing while THIS thread holds the ring lock (record_step's
        # critical section) must re-enter, not self-deadlock. Found by
        # mxtpu_lint's signal-safety rule once the call graph learned
        # to resolve `get().note(...)` through the accessor.
        self._lock = threading.RLock()
        self._last_t = None          # perf_counter of the previous step
        self._pending_loss = None    # (record, device scalar) to resolve
        self.dumps = 0

    # -- recording ---------------------------------------------------------

    def record_step(self, step, loss=None, guard_ok=None, extra=None):
        """One training step completed. `loss` may be a device scalar —
        it is NOT read here; it resolves at the NEXT record_step (one
        step deferred, no host sync). No-op while tracing is disarmed."""
        if not _trace._state['on']:
            return
        now = _time.perf_counter()
        # this thread runs the step loop: only ITS self-times may be
        # billed against step wall time (attribution); other threads'
        # spans overlap the step and count only in the totals
        rec = {'step': int(step), 'time': _time.time(), 'loss': None,
               'spans_ms': _trace.drain_aggregates(
                   consumer_tid=_trace.tid_for_current_thread())}
        if self._last_t is not None:
            rec['interval_ms'] = round((now - self._last_t) * 1e3, 3)
        self._last_t = now
        if guard_ok is not None:
            rec['guard_ok'] = bool(guard_ok)
        # memory watermark fields (MXTPU_MEMORY): the newest sample's
        # prebuilt dict — disarmed this is one dict check returning the
        # shared None, same no-alloc discipline as the trace gate
        mem = _memory.step_fields()
        if mem is not None:
            rec['mem'] = mem
        # compile-ledger fields: only the first step after a compile
        # carries them (consume-on-read), same no-alloc discipline
        comp = _compile.step_fields()
        if comp is not None:
            rec['compile'] = comp
        if extra:
            rec.update(extra)
        with self._lock:
            pending, self._pending_loss = (
                self._pending_loss, (rec, loss) if loss is not None
                else None)
            self._steps.append(rec)
        # resolve OUTSIDE the lock: the float() is a device read — ~free
        # a full step after dispatch, but a wedged device must never
        # wedge the lock (the watchdog's dump needs it to stall-report)
        self._resolve(pending)
        _trace._sync_metrics()

    def _pop_pending(self):
        with self._lock:
            pending, self._pending_loss = self._pending_loss, None
        return pending

    @staticmethod
    def _resolve(pending):
        """Read a deferred loss scalar into its step record (its program
        finished a full step ago; a failure records None). The record is
        already in the ring — a concurrent reader sees None or the
        float, never corruption."""
        if pending is None:
            return
        rec, loss = pending
        try:
            # lint: host-sync-ok deliberately deferred ONE step: this program finished long ago
            rec['loss'] = float(getattr(loss, '_data', loss))
        except Exception:
            rec['loss'] = None

    def note(self, kind, /, **info):
        """One notable event (fault fired, guard tripped, rollback,
        stall, ...). Bounded; no-op while tracing is disarmed."""
        if not _trace._state['on']:
            return
        ev = {'kind': kind, 'time': _time.time()}
        if info:
            ev.update(info)
        with self._lock:
            self._events.append(ev)

    def annotate_last(self, **fields):
        """Attach fields to the most recent step record (e.g. the
        guard's one-step-deferred verdict: annotate_last(guard_ok=False)
        lands on the step whose flag just drained bad)."""
        if not _trace._state['on']:
            return
        with self._lock:
            if self._steps:
                self._steps[-1].update(fields)

    # -- reading / dumping -------------------------------------------------

    @contextlib.contextmanager
    def _locked_for_dump(self, timeout=2.0):
        """Best-effort lock for the read/dump paths. A crash-time dump
        must never deadlock: same-thread signal re-entry is covered by
        the ring lock being an RLock, but a wedged holder on ANOTHER
        thread must not wedge the watchdog's report. After `timeout`
        we proceed lock-free — safe, because a holder that timed us
        out is interrupted or blocked, not mutating."""
        got = self._lock.acquire(timeout=timeout)
        try:
            yield
        finally:
            if got:
                self._lock.release()

    def steps(self):
        with self._locked_for_dump():
            return [dict(r) for r in self._steps]

    def last_step_record(self):
        """The newest step record (copy), or None — the fleet snapshot
        builder's per-step source; never drains the ring."""
        with self._locked_for_dump():
            return dict(self._steps[-1]) if self._steps else None

    def events(self):
        with self._locked_for_dump():
            return [dict(e) for e in self._events]

    def snapshot(self, resolve_loss=False, signal_safe=False):
        """The full post-mortem document. `resolve_loss=False` at crash
        time: reading a pending device scalar could block on a wedged
        device — the dump must never hang. `signal_safe=True` (fatal-
        signal handlers) additionally skips every metrics-registry
        touch: the interrupted frame may hold those locks."""
        if resolve_loss:
            self._resolve(self._pop_pending())    # device read: no lock
        with self._locked_for_dump():
            steps = [dict(r) for r in self._steps]
            events = [dict(e) for e in self._events]
        return {
            'pid': os.getpid(),
            'time': _time.time(),
            'steps': steps,
            'events': events,
            'open_spans': _trace.open_spans(),
            # the open compile window, when a build is mid-flight at
            # crash time — a stall INSIDE compile.backend is forensics
            # gold (which site, which phase, how long)
            'compile_in_flight': _compile.in_flight(),
            'trace_stats': _trace.stats(),
            'faults_armed': self._armed_faults(),
            'traceEvents': _trace.chrome_events(flush_open=True,
                                                metadata=True,
                                                sync=not signal_safe),
        }

    @staticmethod
    def _armed_faults():
        try:
            from ..resilience import faults as _faults
            return _faults.active()
        except Exception:
            return {}

    def dump(self, path=None, reason='', signal_safe=False):
        """Write the post-mortem JSON atomically. Returns the path, or
        None when there is nothing recorded (or tracing is disarmed) —
        an empty flight recorder never shadows a real dump.
        `signal_safe=True` (fatal-signal handlers) skips every
        metrics-registry touch: the interrupted frame may hold the
        registry's non-reentrant lock."""
        if not _trace._state['on']:
            return None
        with self._locked_for_dump():
            empty = not self._steps and not self._events
        if empty and not _trace.stats()['spans_total']:
            return None
        if path is None:
            path = default_dump_path()
        doc = self.snapshot(resolve_loss=False, signal_safe=signal_safe)
        doc['reason'] = reason or 'manual'
        # the watchdog's stall dump and an atexit/SIGTERM dump can
        # overlap; the counter bump rides the same crash-tolerant lock
        # as the ring reads (timeout, then proceed — never wedge a dump)
        with self._locked_for_dump():
            self.dumps += 1
        if _telem['on'] and not signal_safe:
            from . import metrics as _metrics
            _metrics.inc('mxnet_tpu_trace_flight_dumps_total')
        d = os.path.dirname(path)
        if d:
            # a not-yet-created MXTPU_FLIGHT_DIR must not silently lose
            # the post-mortem (same fix as memory.dump_oom)
            os.makedirs(d, exist_ok=True)
        from ..serialization import atomic_write_file
        atomic_write_file(path, json.dumps(doc, default=str).encode())
        return path

    def format_summary(self, last=8):
        """Human-readable tail for log embedding (the watchdog report)."""
        steps = self.steps()[-last:]
        events = self.events()[-last:]
        lines = ['--- flight recorder (last %d steps) ---' % len(steps)]
        for r in steps:
            top = sorted(r['spans_ms'].items(),
                         key=lambda kv: -kv[1]['self_ms'])[:4]
            spans = ' '.join(f"{n}={st['self_ms']:.1f}ms" for n, st in top)
            lines.append(
                f"step {r['step']}: interval={r.get('interval_ms', '?')}ms "
                f"loss={r.get('loss')} guard_ok={r.get('guard_ok', '?')} "
                f"{spans}")
        for e in events:
            lines.append(f"event {e['kind']}: "
                         + ' '.join(f'{k}={v}' for k, v in e.items()
                                    if k not in ('kind', 'time')))
        for s in _trace.open_spans():
            lines.append(f"open span {s['name']} on thread {s['thread']} "
                         f"for {s['age_ms']:.0f}ms")
        return '\n'.join(lines)

    def clear(self):
        with self._lock:
            self._steps.clear()
            self._events.clear()
            self._last_t = None
            self._pending_loss = None


def default_dump_path():
    """Where a dump with no explicit path lands: MXTPU_FLIGHT_PATH when
    set, else MXTPU_FLIGHT_DIR (default: the system temp directory —
    never the CWD) + mxtpu_flight-<pid>.json. The pid suffix keeps the
    ranks of a multi-process job from clobbering each other's black
    box."""
    from .. import config as _config
    explicit = _config.get('MXTPU_FLIGHT_PATH')
    if explicit:
        return explicit
    d = _config.get('MXTPU_FLIGHT_DIR')
    if not d:
        import tempfile
        d = tempfile.gettempdir()
    return os.path.join(d, f'mxtpu_flight-{os.getpid()}.json')


_recorder = None
# RLock: get() runs inside the fatal-signal dump hooks — a signal
# interrupting the first-construction critical section on this very
# thread must re-enter, not self-deadlock (the PR-8 SIGTERM bug class;
# now enforced by tools/mxtpu_lint's signal-safety rule)
_recorder_lock = threading.RLock()
_hooks = {'atexit': False, 'signals': False}


def get() -> FlightRecorder:
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = FlightRecorder()
    return _recorder


def record_step(step, loss=None, guard_ok=None, extra=None):
    get().record_step(step, loss=loss, guard_ok=guard_ok, extra=extra)


def note(kind, /, **info):
    get().note(kind, **info)


def annotate_last(**fields):
    get().annotate_last(**fields)


def dump(path=None, reason='', signal_safe=False):
    return get().dump(path=path, reason=reason, signal_safe=signal_safe)


def _atexit_dump():
    try:
        get().dump(reason='atexit')
    except Exception:
        pass


def _make_signal_handler(signum, prev):
    def handler(sig, frame):
        try:
            get().dump(reason=f'signal:{_signal.Signals(sig).name}',
                       signal_safe=True)
        except Exception:
            pass
        if callable(prev):
            prev(sig, frame)             # chain (e.g. checkpoint SIGTERM)
        elif prev == _signal.SIG_DFL:
            _signal.signal(sig, _signal.SIG_DFL)
            _signal.raise_signal(sig)
    return handler


def install_crash_hooks(signals=(getattr(_signal, 'SIGTERM', None),
                                 getattr(_signal, 'SIGABRT', None))):
    """Register the atexit dump and chain fatal-signal handlers so any
    crash leaves the post-mortem artifact. Idempotent; signal hooks are
    skipped quietly off the main thread (signal.signal would raise)."""
    if not _hooks['atexit']:
        _hooks['atexit'] = True
        atexit.register(_atexit_dump)
    if not _hooks['signals']:
        try:
            for sig in signals:
                if sig is None:
                    continue
                prev = _signal.getsignal(sig)
                _signal.signal(sig, _make_signal_handler(sig, prev))
            _hooks['signals'] = True
        except ValueError:
            pass                         # not the main thread


# armed together with tracing: MXTPU_TRACE=1 runs always leave a black
# box behind (an explicit trace.enable() mid-run can call
# install_crash_hooks itself)
from .. import config as _config_mod  # noqa: E402

if _config_mod.get('MXTPU_TRACE'):
    install_crash_hooks()
