"""Runtime telemetry: process-global metrics registry + recompile detector.

TPU-native analog of the reference's engine-level profiler statistics
(ref: src/profiler/profiler.h — every layer reported into one sink). The
hot paths that decide MFU — imperative op dispatch, the CachedOp/fused-step
compile caches, kvstore traffic, the IO pipeline, and the trainer step —
each report into this registry so perf work is judged against hard numbers.

Design:

- Near-zero cost when disabled: every instrumentation site checks the
  process-wide ``base.telem_flags['on']`` dict flag first (the same
  fast-path pattern as ``base.prof_flags`` / profiler._sync_flags), so a
  disabled run pays one dict lookup per site and records nothing.
- Three exports: ``prometheus()`` (text exposition format), ``dump(path)``
  (structured JSON), and ``chrome_events()`` — chrome-trace ``'C'`` counter
  events that profiler.dump()/dumps() merge into the trace stream.
- A recompile detector: compile sites (CachedOp per block, the trainer's
  fused update, ...) report every (re)compile with the shape/dtype
  signature that caused it; when one site compiles more than N times a
  ``RecompileWarning`` names the site and the churning signature — the
  classic silent MFU killer on XLA.

Enable with ``MXNET_TPU_TELEMETRY=1`` (read at import) or
``telemetry.enable()``; read with ``report()`` / ``dump(path)`` /
``prometheus()``; zero with ``reset()``.
"""
from __future__ import annotations

import json
import re
import threading
import time as _time
import warnings
from typing import Any, Dict, Optional, Tuple

from ..base import MXNetError, telem_flags as _telem

__all__ = [
    'enable', 'disable', 'enabled', 'reset', 'report', 'dump', 'prometheus',
    'chrome_events', 'counter', 'gauge', 'histogram', 'inc', 'set_gauge',
    'observe', 'value', 'series', 'remove_series', 'record_compile',
    'record_cache_hit', 'record_step',
    'recent_samples_per_second', 'set_step_flops',
    'set_recompile_threshold', 'RecompileWarning',
    'Counter', 'Gauge', 'Histogram',
]

# every metric is namespaced + lowercase_snake (enforced here and by
# tools/check_telemetry_names.py over the whole tree)
_NAME_RE = re.compile(r'^mxnet_tpu_[a-z][a-z0-9_]*$')

_lock = threading.RLock()
_metrics: Dict[str, 'Metric'] = {}


class RecompileWarning(RuntimeWarning):
    """One compile site produced more than N distinct compilations."""


def _label_key(labels: Dict[str, Any]) -> Tuple:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class Metric:
    kind = 'metric'

    def __init__(self, name: str, help: str = ''):
        if not _NAME_RE.match(name):
            raise MXNetError(
                f"telemetry metric name {name!r} must be lowercase_snake "
                f"and namespaced mxnet_tpu_*")
        self.name = name
        self.help = help
        # RLock: instrumented paths (checkpoint save gauges/histograms)
        # run inside the SIGTERM preemption save — a signal landing
        # while this thread is mid-inc() must re-enter, not deadlock.
        # A reentrant update can at worst lose one increment; a plain
        # Lock loses the whole preemption grace window.
        self._lock = threading.RLock()
        self._values: Dict[Tuple, Any] = {}

    def labelsets(self):
        with self._lock:
            return list(self._values)

    def remove_matching(self, **labels):
        """Drop every recorded labelset whose labels are a superset of
        ``labels`` (e.g. ``remove_matching(rank=3)`` retires all of a
        departed rank's series regardless of other labels). Returns the
        number of series removed."""
        want = _label_key(labels)
        removed = 0
        with self._lock:
            for key in list(self._values):
                if set(want) <= set(key):
                    del self._values[key]
                    removed += 1
        return removed

    def _fmt_labels(self, key: Tuple) -> str:
        if not key:
            return ''
        # Prometheus exposition format requires \\, \" and \n escaped in
        # label values (kvstore label values come from user-chosen keys)
        def esc(v):
            return str(v).replace('\\', r'\\').replace('"', r'\"') \
                .replace('\n', r'\n')
        return '{' + ','.join(f'{k}="{esc(v)}"' for k, v in key) + '}'


class Counter(Metric):
    kind = 'counter'

    def inc(self, amount: float = 1, **labels):
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + amount

    def value(self, **labels):
        with self._lock:
            return self._values.get(_label_key(labels))


class Gauge(Metric):
    kind = 'gauge'

    def set(self, val: float, **labels):
        with self._lock:
            self._values[_label_key(labels)] = val

    def value(self, **labels):
        with self._lock:
            return self._values.get(_label_key(labels))


# Prometheus-style default latency buckets (seconds), upper bounds
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class Histogram(Metric):
    kind = 'histogram'

    def __init__(self, name, help='', buckets=None):
        super().__init__(name, help)
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))

    def observe(self, val: float, **labels):
        key = _label_key(labels)
        with self._lock:
            st = self._values.get(key)
            if st is None:
                st = {'buckets': [0] * (len(self.buckets) + 1),
                      'sum': 0.0, 'count': 0, 'min': val, 'max': val}
                self._values[key] = st
            for i, ub in enumerate(self.buckets):
                if val <= ub:
                    st['buckets'][i] += 1
                    break
            else:
                st['buckets'][-1] += 1          # +Inf bucket
            st['sum'] += val
            st['count'] += 1
            st['min'] = min(st['min'], val)
            st['max'] = max(st['max'], val)

    def value(self, **labels):
        """(count, sum) for the labelset, or None if never observed."""
        with self._lock:
            st = self._values.get(_label_key(labels))
            return None if st is None else (st['count'], st['sum'])


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def _get_or_create(name, cls, help='', **kwargs):
    with _lock:
        m = _metrics.get(name)
        if m is None:
            m = cls(name, help, **kwargs)
            _metrics[name] = m
        elif not isinstance(m, cls):
            raise MXNetError(
                f"telemetry metric {name!r} already registered as "
                f"{m.kind}, not {cls.kind}")
        return m


def counter(name: str, help: str = '') -> Counter:
    return _get_or_create(name, Counter, help)


def gauge(name: str, help: str = '') -> Gauge:
    return _get_or_create(name, Gauge, help)


def histogram(name: str, help: str = '', buckets=None) -> Histogram:
    return _get_or_create(name, Histogram, help, buckets=buckets)


# one-liner helpers for instrumentation sites (get-or-create + record)
def inc(name: str, amount: float = 1, **labels):
    counter(name).inc(amount, **labels)


def set_gauge(name: str, val: float, **labels):
    gauge(name).set(val, **labels)


def observe(name: str, val: float, **labels):
    histogram(name).observe(val, **labels)


def value(name: str, **labels):
    """Current value of a metric/labelset, or None if never recorded."""
    with _lock:
        m = _metrics.get(name)
    return None if m is None else m.value(**labels)


def remove_series(name: str, **labels):
    """Retire every labelset of ``name`` matching the ``labels`` subset
    (no-op for an unregistered metric). The fleet monitor uses this to
    evict a departed rank's gauge rows — a ghost rank frozen at its
    last values would otherwise haunt every scrape."""
    with _lock:
        m = _metrics.get(name)
    return 0 if m is None else m.remove_matching(**labels)


def series(name: str):
    """[(labels dict, raw value)] for every recorded labelset of a
    metric — the read the fleet snapshot builder aggregates over.
    Empty when the metric was never recorded."""
    with _lock:
        m = _metrics.get(name)
    if m is None:
        return []
    with m._lock:
        items = sorted(m._values.items())
    return [(dict(key), v) for key, v in items]


# ---------------------------------------------------------------------------
# enable / disable / reset
# ---------------------------------------------------------------------------

def enable():
    _telem['on'] = True


def disable():
    _telem['on'] = False


def enabled() -> bool:
    return _telem['on']


def reset():
    """Zero every metric and the recompile/step detectors (registrations
    and enable state are kept)."""
    with _lock:
        for m in _metrics.values():
            with m._lock:
                m._values.clear()
        _compile_sites.clear()
        _step_state['flops'] = None
        _step_state['peak_flops'] = None
        _step_state['last_step_monotonic'] = None


# ---------------------------------------------------------------------------
# recompile detector
# ---------------------------------------------------------------------------

# site -> {'compiles': total, 'episode': compiles this churn episode,
#          'warned': bool, 'mark': _step_mark at the last compile}
_compile_sites: Dict[str, Dict[str, Any]] = {}
_recompile_threshold: Optional[int] = None   # None -> read config lazily
_step_mark = [0]   # bumped by record_step; the recompile detector's clock


def set_recompile_threshold(n: Optional[int]):
    """Warn when one compile site exceeds `n` compiles (None restores the
    MXNET_TPU_RECOMPILE_WARN_THRESHOLD config default)."""
    global _recompile_threshold
    _recompile_threshold = n


def _threshold() -> int:
    if _recompile_threshold is not None:
        return _recompile_threshold
    from .. import config as _config
    return _config.get('MXNET_TPU_RECOMPILE_WARN_THRESHOLD')


def record_compile(site: str, signature: str, seconds: float,
                   detail: str = ''):
    """One XLA (re)compilation at `site` for input `signature`.

    Feeds the compile counters and the recompile detector: when a site's
    compile count within one churn episode exceeds the threshold, a
    RecompileWarning names the churning signature (and, when the compile
    ledger supplies one, the exact churning axis via `detail`) so the
    shape/dtype instability is actionable.  The latch clears per
    episode, matching the memory-leak detector's discipline: a site
    that goes quiet for more than the threshold's worth of training
    steps (record_step marks) starts a fresh episode and re-fires.
    """
    inc('mxnet_tpu_compile_total', site=site)
    counter('mxnet_tpu_compile_seconds_total').inc(seconds, site=site)
    with _lock:
        mark = _step_mark[0]
        st = _compile_sites.setdefault(
            site, {'compiles': 0, 'episode': 0, 'warned': False,
                   'mark': mark})
        if mark - st.get('mark', mark) > _threshold():
            # quiet for > threshold steps since this site's last
            # compile: the churn episode ended — clear the latch
            st['warned'] = False
            st['episode'] = 0
        st['compiles'] += 1
        st['episode'] = st.get('episode', st['compiles'] - 1) + 1
        st['mark'] = mark
        fire = st['episode'] > _threshold() and not st['warned']
        if fire:
            st['warned'] = True
            n = st['compiles']
    if fire:
        inc('mxnet_tpu_recompile_warnings_total', site=site)
        axis = f" Churning axis: {detail}." if detail else ""
        warnings.warn(
            f"telemetry: {site} has compiled {n} times "
            f"(> threshold {_threshold()}); latest signature: {signature}."
            f"{axis} "
            f"Churning input shapes/dtypes force XLA recompilation every "
            f"step — pad or bucket inputs to a fixed signature.",
            RecompileWarning, stacklevel=3)


def record_cache_hit(site: str):
    inc('mxnet_tpu_compile_cache_hits_total', site=site)


# ---------------------------------------------------------------------------
# step instrumentation (trainer / executor)
# ---------------------------------------------------------------------------

_step_state: Dict[str, Optional[float]] = {
    'flops': None, 'peak_flops': None, 'last_step_monotonic': None}


_UNSET = object()


def set_step_flops(flops_per_step: Optional[float],
                   peak_flops: Any = _UNSET):
    """Supply the model FLOPs of one optimization step (and optionally the
    accelerator peak FLOP/s) so record_step can publish an MFU gauge.
    Omitting peak_flops keeps the current peak; passing None clears it."""
    _step_state['flops'] = flops_per_step
    if peak_flops is not _UNSET:
        _step_state['peak_flops'] = peak_flops


def record_step(seconds: float, samples: int):
    """One full training iteration: step-time histogram, samples/sec
    gauge, and — when set_step_flops was called with both numbers — an
    MFU estimate."""
    observe('mxnet_tpu_step_time_seconds', seconds)
    inc('mxnet_tpu_steps_total')
    _step_mark[0] += 1
    _step_state['last_step_monotonic'] = _time.monotonic()
    if seconds > 0:
        set_gauge('mxnet_tpu_samples_per_second', samples / seconds)
        flops, peak = _step_state['flops'], _step_state['peak_flops']
        if flops and peak:
            set_gauge('mxnet_tpu_mfu_percent',
                      100.0 * flops / (seconds * peak))


def recent_samples_per_second(max_age_seconds: float):
    """The step samples/sec gauge, but only when a step was recorded
    within the last `max_age_seconds` — a stale gauge from an earlier
    training phase must not masquerade as a current rate (e.g. during an
    eval loop where no Trainer is stepping). None otherwise."""
    last = _step_state['last_step_monotonic']
    if last is None or _time.monotonic() - last > max_age_seconds:
        return None
    return value('mxnet_tpu_samples_per_second')


# ---------------------------------------------------------------------------
# exports
# ---------------------------------------------------------------------------

def _snapshot():
    """[(metric, [(labelkey, value-or-histstate), ...]), ...] — metrics
    with at least one recorded value, sorted by name."""
    with _lock:
        metrics = sorted(_metrics.values(), key=lambda m: m.name)
    out = []
    for m in metrics:
        with m._lock:
            vals = sorted(m._values.items())
        if vals:
            out.append((m, vals))
    return out


def report() -> str:
    """Human-readable summary of every recorded metric; empty string when
    nothing has been recorded (e.g. telemetry disabled)."""
    lines = []
    for m, vals in _snapshot():
        for key, v in vals:
            label = m.name + m._fmt_labels(key)
            if m.kind == 'histogram':
                avg = v['sum'] / v['count'] if v['count'] else 0.0
                lines.append(
                    f"histogram  {label}  count={v['count']} "
                    f"sum={v['sum']:.6f} avg={avg:.6f} "
                    f"min={v['min']:.6f} max={v['max']:.6f}")
            else:
                vv = f"{v:.6f}".rstrip('0').rstrip('.') \
                    if isinstance(v, float) else str(v)
                lines.append(f"{m.kind:<9s}  {label}  {vv}")
    if not lines:
        return ''
    return '=== mxnet_tpu telemetry ===\n' + '\n'.join(lines)


def dump(path: str):
    """Structured JSON dump of every recorded metric."""
    doc = {}
    for m, vals in _snapshot():
        series = []
        for key, v in vals:
            entry = {'labels': dict(key)}
            if m.kind == 'histogram':
                entry.update(
                    buckets=dict(zip([str(b) for b in m.buckets] + ['+Inf'],
                                     v['buckets'])),
                    sum=v['sum'], count=v['count'],
                    min=v['min'], max=v['max'])
            else:
                entry['value'] = v
            series.append(entry)
        doc[m.name] = {'type': m.kind, 'help': m.help, 'series': series}
    with open(path, 'w') as f:
        json.dump(doc, f, indent=2, sort_keys=True)
    return path


def prometheus() -> str:
    """Prometheus text exposition format (0.0.4) of the registry."""
    lines = []
    for m, vals in _snapshot():
        if m.help:
            lines.append(f"# HELP {m.name} {m.help}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        for key, v in vals:
            if m.kind == 'histogram':
                cum = 0
                for ub, n in zip(m.buckets, v['buckets']):
                    cum += n
                    le = dict(key); le['le'] = repr(float(ub))
                    lines.append(f"{m.name}_bucket"
                                 + m._fmt_labels(_label_key(le)) + f" {cum}")
                le = dict(key); le['le'] = '+Inf'
                lines.append(f"{m.name}_bucket"
                             + m._fmt_labels(_label_key(le))
                             + f" {v['count']}")
                lines.append(f"{m.name}_sum" + m._fmt_labels(key)
                             + f" {v['sum']}")
                lines.append(f"{m.name}_count" + m._fmt_labels(key)
                             + f" {v['count']}")
            else:
                lines.append(f"{m.name}{m._fmt_labels(key)} {v}")
    return '\n'.join(lines) + ('\n' if lines else '')


def chrome_events():
    """Current counter/gauge values as chrome-trace 'C' events, merged by
    profiler.dump()/dumps() into the trace stream (one snapshot row per
    metric series at dump time)."""
    import os
    import time
    now = time.time() * 1e6
    pid = os.getpid()
    evs = []
    for m, vals in _snapshot():
        if m.kind == 'histogram':
            continue
        for key, v in vals:
            evs.append({'name': m.name + m._fmt_labels(key),
                        'cat': 'telemetry', 'ph': 'C', 'ts': now,
                        'pid': pid, 'tid': 0, 'args': {m.name: v}})
    return evs


# config gate (read at import; see config.py for the declaration)
from .. import config as _config_mod  # noqa: E402

if _config_mod.get('MXNET_TPU_TELEMETRY'):
    enable()
