"""Runtime feature detection (ref: python/mxnet/runtime.py, src/libinfo.cc)."""
from __future__ import annotations

import collections

import jax


class Feature(collections.namedtuple('Feature', ['name', 'enabled'])):
    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


def _detect():
    devices = jax.devices()
    has_tpu = any(d.platform not in ('cpu',) for d in devices)
    feats = {
        'TPU': has_tpu,
        'CUDA': False,
        'CUDNN': False,
        'NCCL': False,
        'XLA': True,
        'PALLAS': has_tpu,
        'CPU': True,
        'OPENMP': True,
        'F16C': True,
        'BF16': True,
        'BLAS_OPEN': True,
        'DIST_KVSTORE': True,
        'INT64_TENSOR_SIZE': True,
        'SIGNAL_HANDLER': False,
        'DEBUG': False,
        'MKLDNN': False,
        'TENSORRT': False,
        'TVM_OP': False,
        'PROFILER': True,
    }
    return {k: Feature(k, v) for k, v in feats.items()}


class Features(dict):
    """Ref: runtime.py Features."""

    instance = None

    def __new__(cls):
        if cls.instance is None:
            cls.instance = super().__new__(cls)
            dict.__init__(cls.instance, _detect())
        return cls.instance

    def __repr__(self):
        return str(list(self.values()))

    def is_enabled(self, feature_name):
        feature_name = feature_name.upper()
        if feature_name not in self:
            raise RuntimeError(f"Feature '{feature_name}' is unknown")
        return self[feature_name].enabled


def feature_list():
    return list(Features().values())
