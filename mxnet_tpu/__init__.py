"""mxnet_tpu — a TPU-native deep-learning framework with the MXNet API.

Brand-new implementation targeting JAX/XLA/Pallas/pjit on TPU, with the
capability surface of Apache MXNet 1.6 (reference repo: eric-haibin-lin/mxnet).
See SURVEY.md for the component map this implements.

Usage mirrors MXNet:

    import mxnet_tpu as mx
    x = mx.nd.ones((2, 3), ctx=mx.tpu(0))
    with mx.autograd.record():
        y = (x * 2).sum()
    y.backward()
"""
from .base import MXNetError, DataError, __version__, register_op, list_ops
from .context import (Context, cpu, gpu, tpu, cpu_pinned, num_gpus, num_tpus,
                      gpu_memory_info, current_context)
from . import ops        # registers all operators
from . import ndarray
from . import ndarray as nd
from . import autograd
from . import random
from .random import seed
from . import initializer
from .initializer import init  # noqa: F401
from . import optimizer
from . import lr_scheduler
from . import metric
from . import kvstore
from . import kvstore as kv    # ref: python/mxnet/__init__.py `mx.kv` alias
from .kvstore import create as _kv_create  # noqa: F401
from . import io
from . import recordio
from . import gluon
from . import profiler
from . import telemetry
from . import callback
from . import resilience
from . import checkpoint
from . import runtime
from . import config
from . import subgraph
from . import engine
from . import util
from . import test_utils
from . import numpy as np  # numpy-compatible frontend (mx.np)
from . import numpy_extension as npx
from . import symbol
from . import symbol as sym
from . import module
from . import visualization as viz
from . import parallel
from . import amp
from . import contrib
from . import operator
from . import torch
from . import rtc
from . import library
from . import attribute
from .attribute import AttrScope
from . import name
from . import monitor
from .monitor import Monitor
from . import log
from . import libinfo
from . import registry
from . import executor
from . import executor_manager
from . import kvstore_server
# reference-launcher compat: a DMLC_ROLE=server process exits here with
# the (empty) server role instead of running the training script body
kvstore_server._init_kvstore_server_module()
from . import image

__all__ = ['nd', 'ndarray', 'autograd', 'gluon', 'optimizer', 'metric', 'io',
           'kvstore', 'random', 'cpu', 'gpu', 'tpu', 'Context', 'MXNetError',
           'AttrScope', 'Monitor']


# env-var configuration applied at import (ref: the reference's
# read-at-startup vars, docs/faq/env_var.md)
import os as _os  # noqa: E402
if _os.environ.get('MXNET_SEED'):
    seed(config.get('MXNET_SEED'))
if config.get('MXNET_PROFILER_AUTOSTART'):
    profiler.start()
