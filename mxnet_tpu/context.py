"""Device/context model over jax devices.

Ref: include/mxnet/base.h:102-115 (Context{kCPU,kGPU,kCPUPinned,kCPUShared})
and python/mxnet/context.py. On TPU, "gpu" maps to a TPU chip so that
unmodified reference scripts (`mx.gpu(0)`) run on TPU; `tpu()` is the
first-class native spelling.
"""
from __future__ import annotations

import threading
from typing import Optional

import jax

from .base import MXNetError


class Context:
    """A device context. devtype in {'cpu', 'gpu', 'tpu', 'cpu_pinned', 'cpu_shared'}."""

    devtype2id = {'cpu': 1, 'gpu': 2, 'cpu_pinned': 3, 'tpu': 4, 'cpu_shared': 5}
    devid2type = {v: k for k, v in devtype2id.items()}
    _default_ctx = threading.local()

    def __init__(self, device_type: str, device_id: int = 0):
        if device_type not in self.devtype2id:
            raise MXNetError(f"unknown device type {device_type!r}")
        self.device_type = device_type
        self.device_id = device_id

    @property
    def device_typeid(self) -> int:
        return self.devtype2id[self.device_type]

    def jax_device(self):
        """Resolve this context to a concrete jax device."""
        if self.device_type in ('cpu', 'cpu_pinned', 'cpu_shared'):
            devs = jax.devices('cpu') if _has_platform('cpu') else jax.devices()
        else:
            # 'gpu' and 'tpu' both resolve to the accelerator platform; on a
            # TPU machine mx.gpu(0) runs on TPU so reference scripts work.
            devs = _accelerator_devices()
            if not devs:
                devs = jax.devices()
        if self.device_id >= len(devs):
            raise MXNetError(
                f"{self}: device_id {self.device_id} out of range ({len(devs)} available)")
        return devs[self.device_id]

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    __str__ = __repr__

    def __enter__(self):
        if not hasattr(self._default_ctx, 'stack'):
            self._default_ctx.stack = []
        self._default_ctx.stack.append(self)
        return self

    def __exit__(self, *exc):
        self._default_ctx.stack.pop()

    @classmethod
    def default_ctx(cls) -> "Context":
        stack = getattr(cls._default_ctx, 'stack', None)
        if stack:
            return stack[-1]
        return _DEFAULT


def _has_platform(name: str) -> bool:
    try:
        return bool(jax.devices(name))
    except RuntimeError:
        return False


def _accelerator_devices():
    devs = [d for d in jax.devices() if d.platform not in ('cpu',)]
    return devs


def cpu(device_id: int = 0) -> Context:
    return Context('cpu', device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context('cpu_pinned', device_id)


def gpu(device_id: int = 0) -> Context:
    return Context('gpu', device_id)


def tpu(device_id: int = 0) -> Context:
    return Context('tpu', device_id)


def num_gpus() -> int:
    """Number of accelerator chips visible (ref: python/mxnet/context.py num_gpus)."""
    return len(_accelerator_devices())


def num_tpus() -> int:
    return len(_accelerator_devices())


def gpu_memory_info(device_id: int = 0):
    devs = _accelerator_devices()
    if device_id >= len(devs):
        raise MXNetError(f"no accelerator device {device_id}")
    stats = devs[device_id].memory_stats() or {}
    total = stats.get('bytes_limit', 0)
    used = stats.get('bytes_in_use', 0)
    return (total - used, total)


def current_context() -> Context:
    return Context.default_ctx()


_DEFAULT = Context('cpu', 0)
