"""KVStore server process entry (ref: python/mxnet/kvstore_server.py).

The reference's dist_sync topology runs dedicated server processes that
aggregate worker pushes (kvstore_dist_server.h). The TPU-native backend
has NO separate servers: gradient aggregation is an XLA all-reduce over
ICI/DCN inside the compiled step, and every process is a worker
(parallel/dist.py). This module keeps the launch-compatibility surface —
a process started in the server role initializes the distributed client
and parks until shutdown, so reference launch scripts that spawn
`DMLC_ROLE=server` processes keep working against this framework."""
from __future__ import annotations

import logging
import os


class KVStoreServer:
    """Role-compat server loop (ref: kvstore_server.py:KVStoreServer).
    run() blocks until the job's workers finish (jax.distributed
    shutdown), performing no aggregation of its own."""

    def __init__(self, kvstore):
        self.kvstore = kvstore

    def run(self):
        logging.info(
            "mxnet_tpu kvstore server role: aggregation happens inside "
            "the compiled step (XLA all-reduce); server idles until "
            "shutdown")
        # nothing to serve: return immediately so the process exits
        # cleanly — workers do not depend on it
        return


def _init_kvstore_server_module():
    """Ref: kvstore_server.py:_init_kvstore_server_module — spawns the
    server loop when DMLC_ROLE=server."""
    if os.environ.get('DMLC_ROLE') == 'server':
        from . import kvstore as kv
        server = KVStoreServer(kv.create('dist_sync'))
        server.run()
        return True
    return False
