"""KVStore server process entry (ref: python/mxnet/kvstore_server.py).

The reference's dist_sync topology runs dedicated server processes that
aggregate worker pushes (kvstore_dist_server.h). The TPU-native backend
has NO separate servers: gradient aggregation is an XLA all-reduce over
ICI/DCN inside the compiled step, and every process is a worker
(parallel/dist.py). This module keeps the launch-compatibility surface —
a process started in the server role initializes the distributed client
and parks until shutdown, so reference launch scripts that spawn
`DMLC_ROLE=server` processes keep working against this framework."""
from __future__ import annotations

import logging
import os


class KVStoreServer:
    """Role-compat server shim (ref: kvstore_server.py:KVStoreServer).
    run() logs the design note and returns immediately: there is no
    aggregation work in this backend, so a server-role process has
    nothing to do and should exit cleanly (workers never depend on it)."""

    def __init__(self, kvstore):
        self.kvstore = kvstore

    def run(self):
        logging.info(
            "mxnet_tpu kvstore server role: aggregation happens inside "
            "the compiled step (XLA all-reduce over ICI/DCN); this "
            "backend has no server work — exiting the server role")
        return


def _init_kvstore_server_module():
    """Invoked at package import (mxnet_tpu/__init__.py, mirroring the
    reference's import-time hook): a DMLC_ROLE=server process runs the
    (empty) server role and EXITS before any user training code — the
    reference's server processes likewise never execute the script body.
    Returns True in the server role (after which the interpreter exits);
    False otherwise."""
    if os.environ.get('DMLC_ROLE') == 'server':
        server = KVStoreServer(None)
        server.run()
        import sys
        sys.exit(0)
    return False
