from .optimizer import (Optimizer, Updater, get_updater, create, register,
                        SGD, Signum, FTML, LARS, LAMB, NAG, SGLD, Adam, AdamW,
                        AdaGrad, RMSProp, AdaDelta, Ftrl, Adamax, Nadam, DCASGD,
                        Test)

__all__ = ['Optimizer', 'Updater', 'get_updater', 'create', 'register', 'SGD',
           'Signum', 'FTML', 'LARS', 'LAMB', 'NAG', 'SGLD', 'Adam', 'AdamW',
           'AdaGrad', 'RMSProp', 'AdaDelta', 'Ftrl', 'Adamax', 'Nadam',
           'DCASGD', 'Test']
