"""Optimizers (ref: python/mxnet/optimizer/optimizer.py).

Each optimizer's math lives in mxnet_tpu.ops.optimizer_ops as pure jax
functions (the reference's optimizer_op.cc kernels); here we keep the
stateful Optimizer API: registry, per-param lr/wd multipliers, update
counts, multi-precision master weights, and the Updater used by
kvstore/Trainer.
"""
from __future__ import annotations

import math
import pickle

import numpy as onp

from ..base import Registry, MXNetError
from ..ndarray.ndarray import NDArray, _invoke, zeros as nd_zeros
from ..ops import optimizer_ops as O

_REG = Registry('optimizer')


def register(klass):
    _REG.register(klass)
    return klass


def create(name, **kwargs):
    return _REG.create(name, **kwargs)


class Optimizer:
    """Base optimizer (ref: optimizer.py:52)."""

    # True when update() is pure jnp math over (weight, grad, state) plus
    # the (lr, wd, t, rescale_grad) scalars — the Trainer then compiles
    # ALL parameter updates into one jitted multi-tensor program (analog
    # of ref src/operator/contrib/preloaded_multi_sgd.cc). Optimizers
    # that sync to host (LARS), draw randomness (SGLD), or mutate python
    # state mid-update (Nadam's m_schedule) must leave this False.
    fused_update = False

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None, **kwargs):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.aggregate_num = 0
        if param_idx2name is None:
            param_idx2name = {}
        self.idx2name = param_idx2name.copy()
        self.param_dict = param_dict if param_dict else {}

    create_optimizer = staticmethod(create)

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        """fp32 master copy for low-precision weights (ref: optimizer.py
        create_state_multi_precision)."""
        weight_master_copy = None
        if self.multi_precision and weight.dtype in (onp.float16, onp.dtype('bfloat16')
                                                     if hasattr(onp, 'dtype') else None):
            weight_master_copy = weight.astype('float32')
            return (weight_master_copy, self.create_state(index, weight_master_copy))
        if str(weight.dtype) in ('float16', 'bfloat16') and self.multi_precision:
            weight_master_copy = weight.astype('float32')
            return (weight_master_copy, self.create_state(index, weight_master_copy))
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and str(weight.dtype) in ('float16', 'bfloat16'):
            master, base_state = state
            grad32 = grad.astype('float32')
            self.update(index, master, grad32, base_state)
            weight._data = master._data.astype(weight._data.dtype)
        else:
            self.update(index, weight, grad, state)

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError("LRScheduler of the optimizer has already been defined")
        self.lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = args_lr_mult.copy()

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            is_weight = n.endswith('_weight')
            if not is_weight:
                self.wd_mult[n] = 0.0
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if not isinstance(index, (list, tuple)):
            index = [index]
        for idx in index:
            if idx not in self._index_update_count:
                self._index_update_count[idx] = self.begin_num_update
            self._index_update_count[idx] += 1
            self.num_update = max(self._index_update_count[idx], self.num_update)

    def _get_lrs(self, indices):
        lr = self.learning_rate
        lrs = []
        for index in indices:
            if index in self.param_dict:
                lrs.append(lr * self.param_dict[index].lr_mult)
            elif index in self.lr_mult:
                lrs.append(lr * self.lr_mult[index])
            elif index in self.idx2name:
                lrs.append(lr * self.lr_mult.get(self.idx2name[index], 1.0))
            else:
                lrs.append(lr)
        return lrs

    def _get_lr(self, index):
        return self._get_lrs([index])[0]

    def _get_wds(self, indices):
        wds = []
        for index in indices:
            if index in self.param_dict:
                wds.append(self.wd * self.param_dict[index].wd_mult)
            elif index in self.wd_mult:
                wds.append(self.wd * self.wd_mult[index])
            elif index in self.idx2name:
                wds.append(self.wd * self.wd_mult.get(self.idx2name[index], 1.0))
            else:
                wds.append(self.wd)
        return wds

    def _get_wd(self, index):
        return self._get_wds([index])[0]

    def __getstate__(self):
        ret = self.__dict__.copy()
        # param_dict holds live Parameter objects (thread-local trace state,
        # device arrays) — not serialisable and re-attached by Trainer.
        ret['param_dict'] = {}
        return ret

    def __setstate__(self, state):
        self.__dict__.update(state)
        self.param_dict = {}


def _cg(v):
    return -1.0 if v is None else v


@register
class SGD(Optimizer):
    """SGD with momentum and multi-precision (ref: optimizer.py:526)."""
    fused_update = True

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd_zeros(weight.shape, dtype='float32')
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        # lazy semantics apply only to row-sparse gradients (ref:
        # optimizer.py:526 SGD docstring; FComputeEx dispatch on stype)
        lazy = self.lazy_update and grad.stype == 'row_sparse'
        if state is not None:
            new_w, new_mom = _invoke(
                O.sgd_mom_update, weight, grad, state, lr=lr,
                momentum=self.momentum, wd=wd, rescale_grad=self.rescale_grad,
                clip_gradient=_cg(self.clip_gradient), lazy_update=lazy)
            weight._data = new_w._data
            state._data = new_mom._data
        else:
            new_w = _invoke(O.sgd_update, weight, grad, lr=lr, wd=wd,
                            rescale_grad=self.rescale_grad,
                            clip_gradient=_cg(self.clip_gradient),
                            lazy_update=lazy)
            weight._data = new_w._data


@register
class Signum(Optimizer):
    """Ref: optimizer.py:672."""
    fused_update = True

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd_zeros(weight.shape, dtype='float32')
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        if state is not None:
            new_w, new_mom = _invoke(
                O.signum_update, weight, grad, state, lr=lr,
                momentum=self.momentum, wd=wd, rescale_grad=self.rescale_grad,
                clip_gradient=_cg(self.clip_gradient), wd_lh=self.wd_lh)
            weight._data = new_w._data
            state._data = new_mom._data
        else:
            new_w = _invoke(O.signsgd_update, weight, grad, lr=lr, wd=wd,
                            rescale_grad=self.rescale_grad,
                            clip_gradient=_cg(self.clip_gradient))
            weight._data = new_w._data


@register
class FTML(Optimizer):
    fused_update = True
    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (nd_zeros(weight.shape, dtype='float32'),
                nd_zeros(weight.shape, dtype='float32'),
                nd_zeros(weight.shape, dtype='float32'))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        d, v, z = state
        new_w, nd_, nv, nz = _invoke(
            O.ftml_update, weight, grad, d, v, z, lr=lr, beta1=self.beta1,
            beta2=self.beta2, epsilon=self.epsilon, t=t, wd=wd,
            rescale_grad=self.rescale_grad, clip_grad=_cg(self.clip_gradient))
        weight._data = new_w._data
        d._data, v._data, z._data = nd_._data, nv._data, nz._data


@register
class LARS(Optimizer):
    """Layer-wise adaptive rate scaling (ref: optimizer.py:797)."""

    def __init__(self, momentum=0.0, eta=0.001, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.eta = eta
        self.epsilon = epsilon

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd_zeros(weight.shape, dtype='float32')
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        w_norm = float(weight.norm().asscalar())
        g_norm = float((grad * self.rescale_grad).norm().asscalar())
        if w_norm > 0 and g_norm > 0:
            lr = lr * self.eta * w_norm / (g_norm + wd * w_norm + self.epsilon)
        if state is not None:
            new_w, new_mom = _invoke(
                O.sgd_mom_update, weight, grad, state, lr=lr,
                momentum=self.momentum, wd=wd, rescale_grad=self.rescale_grad,
                clip_gradient=_cg(self.clip_gradient))
            weight._data = new_w._data
            state._data = new_mom._data
        else:
            new_w = _invoke(O.sgd_update, weight, grad, lr=lr, wd=wd,
                            rescale_grad=self.rescale_grad,
                            clip_gradient=_cg(self.clip_gradient))
            weight._data = new_w._data


@register
class LAMB(Optimizer):
    """Layer-wise Adaptive Moments for Batch training (ref: optimizer.py:1250)."""
    fused_update = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (nd_zeros(weight.shape, dtype='float32'),
                nd_zeros(weight.shape, dtype='float32'))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        mean, var = state
        g_update, new_mean, new_var = _invoke(
            O.lamb_update_phase1, weight, grad, mean, var, beta1=self.beta1,
            beta2=self.beta2, epsilon=self.epsilon, t=t,
            bias_correction=self.bias_correction, wd=wd,
            rescale_grad=self.rescale_grad,
            clip_gradient=_cg(self.clip_gradient))
        mean._data, var._data = new_mean._data, new_var._data
        r1 = weight.astype('float32').norm()
        r2 = g_update.norm()
        new_w = _invoke(O.lamb_update_phase2, weight, g_update, r1, r2, lr=lr,
                        lower_bound=_cg(self.lower_bound),
                        upper_bound=_cg(self.upper_bound))
        weight._data = new_w._data


@register
class NAG(Optimizer):
    fused_update = True
    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd_zeros(weight.shape, dtype='float32')
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        if state is not None:
            new_w, new_mom = _invoke(
                O.nag_mom_update, weight, grad, state, lr=lr,
                momentum=self.momentum, wd=wd, rescale_grad=self.rescale_grad,
                clip_gradient=_cg(self.clip_gradient))
            weight._data = new_w._data
            state._data = new_mom._data
        else:
            new_w = _invoke(O.sgd_update, weight, grad, lr=lr, wd=wd,
                            rescale_grad=self.rescale_grad,
                            clip_gradient=_cg(self.clip_gradient))
            weight._data = new_w._data


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics."""

    def update(self, index, weight, grad, state):
        from ..ndarray import random as nd_random
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        noise = nd_random.normal(0, math.sqrt(lr), shape=weight.shape,
                                 dtype='float32')
        weight._data = (weight - lr / 2 * (g + wd * weight) + noise)._data


@register
class Adam(Optimizer):
    """Ref: optimizer.py:1547."""
    fused_update = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (nd_zeros(weight.shape, dtype='float32'),
                nd_zeros(weight.shape, dtype='float32'))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        coef1 = 1. - self.beta1 ** t
        coef2 = 1. - self.beta2 ** t
        # ** 0.5 (not math.sqrt): stays traceable when t rides
        # through the Trainer's fused-update jit as a tracer
        lr_t = lr * coef2 ** 0.5 / coef1
        mean, var = state
        lazy = self.lazy_update and grad.stype == 'row_sparse'
        new_w, new_mean, new_var = _invoke(
            O.adam_update, weight, grad, mean, var, lr=lr_t, beta1=self.beta1,
            beta2=self.beta2, epsilon=self.epsilon, wd=wd,
            rescale_grad=self.rescale_grad,
            clip_gradient=_cg(self.clip_gradient), lazy_update=lazy)
        weight._data = new_w._data
        mean._data, var._data = new_mean._data, new_var._data


@register
class AdamW(Optimizer):
    """Decoupled weight decay Adam (ref: src/operator/contrib/adamw.cc)."""
    fused_update = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, eta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.eta = eta

    def create_state(self, index, weight):
        return (nd_zeros(weight.shape, dtype='float32'),
                nd_zeros(weight.shape, dtype='float32'))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        mean, var = state
        new_w, new_mean, new_var = _invoke(
            O.adamw_update, weight, grad, mean, var,
            rescale_grad=self.rescale_grad, lr=lr, eta=self.eta,
            beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon, wd=wd,
            clip_gradient=_cg(self.clip_gradient))
        weight._data = new_w._data
        mean._data, var._data = new_mean._data, new_var._data


@register
class AdaGrad(Optimizer):
    fused_update = True
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd_zeros(weight.shape, dtype='float32')

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        new_w, new_hist = _invoke(
            O.adagrad_update, weight, grad, state, lr=lr,
            epsilon=self.float_stable_eps, wd=wd,
            rescale_grad=self.rescale_grad,
            clip_gradient=_cg(self.clip_gradient))
        weight._data = new_w._data
        state._data = new_hist._data


@register
class RMSProp(Optimizer):
    fused_update = True
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (nd_zeros(weight.shape, dtype='float32'),
                    nd_zeros(weight.shape, dtype='float32'),
                    nd_zeros(weight.shape, dtype='float32'))
        return nd_zeros(weight.shape, dtype='float32')

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        if not self.centered:
            new_w, new_n = _invoke(
                O.rmsprop_update, weight, grad, state, lr=lr,
                gamma1=self.gamma1, epsilon=self.epsilon, wd=wd,
                rescale_grad=self.rescale_grad,
                clip_gradient=_cg(self.clip_gradient),
                clip_weights=_cg(self.clip_weights))
            weight._data = new_w._data
            state._data = new_n._data
        else:
            n, g, delta = state
            new_w, nn, ng, ndel = _invoke(
                O.rmspropalex_update, weight, grad, n, g, delta, lr=lr,
                gamma1=self.gamma1, gamma2=self.gamma2, epsilon=self.epsilon,
                wd=wd, rescale_grad=self.rescale_grad,
                clip_gradient=_cg(self.clip_gradient),
                clip_weights=_cg(self.clip_weights))
            weight._data = new_w._data
            n._data, g._data, delta._data = nn._data, ng._data, ndel._data


@register
class AdaDelta(Optimizer):
    fused_update = True
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (nd_zeros(weight.shape, dtype='float32'),
                nd_zeros(weight.shape, dtype='float32'))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        acc_g, acc_delta = state
        new_w, ng, ndelta = _invoke(
            O.adadelta_update, weight, grad, acc_g, acc_delta, rho=self.rho,
            epsilon=self.epsilon, wd=wd, rescale_grad=self.rescale_grad,
            clip_gradient=_cg(self.clip_gradient))
        weight._data = new_w._data
        acc_g._data, acc_delta._data = ng._data, ndelta._data


@register
class Ftrl(Optimizer):
    fused_update = True
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (nd_zeros(weight.shape, dtype='float32'),
                nd_zeros(weight.shape, dtype='float32'))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        z, n = state
        new_w, nz, nn = _invoke(
            O.ftrl_update, weight, grad, z, n, lr=lr, lamda1=self.lamda1,
            beta=self.beta, wd=wd, rescale_grad=self.rescale_grad,
            clip_gradient=_cg(self.clip_gradient))
        weight._data = new_w._data
        z._data, n._data = nz._data, nn._data


@register
class Adamax(Optimizer):
    fused_update = True
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (nd_zeros(weight.shape, dtype='float32'),
                nd_zeros(weight.shape, dtype='float32'))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        lr /= (1. - self.beta1 ** t)
        m, u = state
        g = (grad * self.rescale_grad).astype('float32')
        if self.clip_gradient is not None:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        g = g + wd * weight.astype('float32')
        m._data = (self.beta1 * m + (1. - self.beta1) * g)._data
        import jax.numpy as jnp
        u._data = jnp.maximum(self.beta2 * u._data, jnp.abs(g._data))
        weight._data = (weight.astype('float32') - lr * m / (u + 1e-8)) \
            ._data.astype(weight._data.dtype)


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.

    def create_state(self, index, weight):
        return (nd_zeros(weight.shape, dtype='float32'),
                nd_zeros(weight.shape, dtype='float32'))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        g = (grad * self.rescale_grad).astype('float32')
        if self.clip_gradient is not None:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        g = g + wd * weight.astype('float32')
        momentum_t = self.beta1 * (1. - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (1. - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        m, v = state
        m._data = (self.beta1 * m + (1. - self.beta1) * g)._data
        v._data = (self.beta2 * v + (1. - self.beta2) * g * g)._data
        grad_prime = g / (1. - self.m_schedule)
        m_t_prime = m / (1. - m_schedule_next)
        v_t_prime = v / (1. - self.beta2 ** t)
        m_t_bar = (1. - momentum_t) * grad_prime + momentum_t_1 * m_t_prime
        new_w = (weight.astype('float32')
                 - lr * m_t_bar / (v_t_prime.sqrt() + self.epsilon))
        weight._data = new_w._data.astype(weight._data.dtype)


@register
class DCASGD(Optimizer):
    fused_update = True
    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (nd_zeros(weight.shape, dtype='float32'), weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = (grad * self.rescale_grad).astype('float32')
        if self.clip_gradient is not None:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        mon, previous_weight = state
        w32 = weight.astype('float32')
        delta = (-lr * (g + wd * w32 + self.lamda * g * g
                        * (w32 - previous_weight)))
        if mon is not None:
            mon._data = (self.momentum * mon + delta)._data
            delta = mon
        previous_weight._data = weight._data
        weight._data = (w32 + delta)._data.astype(weight._data.dtype)


@register
class Test(Optimizer):
    fused_update = True
    def create_state(self, index, weight):
        return nd_zeros(weight.shape, dtype='float32')

    def update(self, index, weight, grad, state):
        weight._data = (weight + grad * self.rescale_grad)._data


class Updater:
    """Local updater interface (ref: optimizer.py:2070)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}
        self.aggregate_updates = optimizer.aggregate_num > 0

    def __call__(self, index, grad, weight):
        if not isinstance(index, (list, tuple)):
            indices = [index]
            grads = [grad]
            weights = [weight]
        else:
            indices, grads, weights = index, grad, weight
        for i, g, w in zip(indices, grads, weights):
            if i not in self.states:
                self.states[i] = self.optimizer.create_state_multi_precision(i, w)
                self.states_synced[i] = True
            self.optimizer.update_multi_precision(i, w, g, self.states[i])

    def sync_state_context(self, state, context):
        return state

    def set_states(self, states):
        from ..ndarray.ndarray import array as nd_array
        import numpy as onp

        def _ndify(s):
            if isinstance(s, onp.ndarray):
                return nd_array(s)
            if isinstance(s, (list, tuple)):
                return tuple(_ndify(x) for x in s)
            return s

        states = pickle.loads(states)
        if isinstance(states, tuple) and len(states) == 2 and \
                isinstance(states[1], Optimizer):
            loaded, self.optimizer = states
        else:
            loaded = states
        self.states = {k: _ndify(v) for k, v in loaded.items()}
        self.states_synced = dict.fromkeys(self.states.keys(), False)

    def get_states(self, dump_optimizer=False):
        def _npify(s):
            if isinstance(s, NDArray):
                return s.asnumpy()
            if isinstance(s, (list, tuple)):
                return tuple(_npify(x) for x in s)
            return s
        states = {k: _npify(v) for k, v in self.states.items()}
        if dump_optimizer:
            return pickle.dumps((states, self.optimizer))
        return pickle.dumps(states)


def get_updater(optimizer):
    return Updater(optimizer)
