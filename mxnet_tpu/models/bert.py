"""BERT for pretraining — the flagship/north-star model.

Ref: the GluonNLP BERT-base recipe named in BASELINE.json; attention kernels
correspond to the reference's interleaved_matmul selfatt ops
(src/operator/contrib/transformer.cc:650-828), realised here as the fused
multi_head_attention op (XLA/Pallas flash path).

bf16-friendly: activations run in the block dtype; layernorm statistics in
fp32 (see ops/nn.py layer_norm).
"""
from __future__ import annotations

import math

from ..gluon import nn
from ..gluon.block import HybridBlock
from .. import ndarray as nd
from ..ops import attention as attn_ops
from ..ndarray.ndarray import _invoke


def bert_base_config():
    return dict(vocab_size=30522, hidden=768, layers=12, heads=12,
                intermediate=3072, max_len=512, type_vocab=2)


def bert_large_config():
    return dict(vocab_size=30522, hidden=1024, layers=24, heads=16,
                intermediate=4096, max_len=512, type_vocab=2)


class BertSelfAttention(HybridBlock):
    def __init__(self, hidden, heads, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self._heads = heads
        self._hidden = hidden
        self._attn_dropout = dropout
        with self.name_scope():
            self.qkv = nn.Dense(3 * hidden, flatten=False,
                                in_units=hidden, prefix='qkv_')
            self.proj = nn.Dense(hidden, flatten=False, in_units=hidden,
                                 prefix='proj_')
            self.dropout = nn.Dropout(dropout)

    def forward(self, x, mask=None):
        # x: (N, T, C)
        qkv = self.qkv(x)
        q, k, v = qkv.split(3, axis=-1)
        out = _invoke(attn_ops.multi_head_attention, q, k, v, mask,
                      num_heads=self._heads, dropout_p=self._attn_dropout)
        return self.dropout(self.proj(out))


class BertLayer(HybridBlock):
    def __init__(self, hidden, heads, intermediate, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.attention = BertSelfAttention(hidden, heads, dropout)
            self.ln1 = nn.LayerNorm(in_channels=hidden)
            self.ffn1 = nn.Dense(intermediate, flatten=False,
                                 in_units=hidden, prefix='ffn1_')
            self.ffn2 = nn.Dense(hidden, flatten=False,
                                 in_units=intermediate, prefix='ffn2_')
            self.ln2 = nn.LayerNorm(in_channels=hidden)
            self.dropout = nn.Dropout(dropout)

    def _add_ln(self, ln, x, sub):
        # residual + LN through one op so the fused Pallas epilogue can
        # take it when MXTPU_PALLAS_LN=1 (ops/nn.py add_layer_norm)
        from ..ops import nn as _nn_ops
        return _invoke(_nn_ops.add_layer_norm, x, sub,
                       ln.gamma.data(), ln.beta.data(), eps=ln._epsilon)

    def forward(self, x, mask=None):
        attn = self.attention(x, mask)
        x = self._add_ln(self.ln1, x, attn)
        # FFN1 matmul + bias + GELU through one op so the fused Pallas
        # epilogue can take it when MXTPU_PALLAS_FFN=1 (ops/nn.py
        # dense_gelu; the XLA default is the same Dense+gelu math)
        from ..ops import nn as _nn_ops
        h = _invoke(_nn_ops.dense_gelu, x, self.ffn1.weight.data(),
                    self.ffn1.bias.data())
        h = self.dropout(self.ffn2(h))
        return self._add_ln(self.ln2, x, h)


class BertModel(HybridBlock):
    def __init__(self, vocab_size=30522, hidden=768, layers=12, heads=12,
                 intermediate=3072, max_len=512, type_vocab=2, dropout=0.1,
                 **kwargs):
        super().__init__(**kwargs)
        self._hidden = hidden
        with self.name_scope():
            self.word_embed = nn.Embedding(vocab_size, hidden,
                                           prefix='word_embed_')
            self.pos_embed = nn.Embedding(max_len, hidden,
                                          prefix='pos_embed_')
            self.type_embed = nn.Embedding(type_vocab, hidden,
                                           prefix='type_embed_')
            self.embed_ln = nn.LayerNorm(in_channels=hidden)
            self.embed_dropout = nn.Dropout(dropout)
            self.encoder = nn.HybridSequential(prefix='encoder_')
            with self.encoder.name_scope():
                for _ in range(layers):
                    self.encoder.add(BertLayer(hidden, heads, intermediate,
                                               dropout))
            self.pooler = nn.Dense(hidden, flatten=False, in_units=hidden,
                                   activation='tanh', prefix='pooler_')

    def forward(self, tokens, token_types=None, valid_length=None):
        # tokens: (N, T) int32
        T = tokens.shape[1]
        pos = nd.arange(0, T, dtype='int32').reshape(1, T)
        emb = self.word_embed(tokens) + self.pos_embed(pos)
        if token_types is not None:
            emb = emb + self.type_embed(token_types)
        x = self.embed_dropout(self.embed_ln(emb))
        mask = None
        if valid_length is not None:
            ar = nd.arange(0, T, dtype='float32')
            mask = (ar.reshape(1, 1, 1, T) <
                    valid_length.reshape(-1, 1, 1, 1))
        for layer in self.encoder:
            x = layer(x, mask)
        pooled = self.pooler(nd.slice_axis(x, axis=1, begin=0, end=1)
                             .squeeze(axis=1))
        return x, pooled


def _gather_positions(seq, positions):
    """(N, T, C) gathered at (N, M) int positions -> (N, M, C)."""
    import jax.numpy as jnp
    return jnp.take_along_axis(
        seq, positions.astype('int32')[:, :, None], axis=1)


class BertForPretraining(HybridBlock):
    """MLM + NSP heads (the pretraining objective in the north-star recipe)."""

    def __init__(self, config=None, **kwargs):
        super().__init__(**kwargs)
        cfg = config or bert_base_config()
        self._cfg = cfg
        with self.name_scope():
            self.bert = BertModel(**cfg)
            self.mlm_dense = nn.Dense(cfg['hidden'], flatten=False,
                                      in_units=cfg['hidden'],
                                      activation='gelu',
                                      prefix='mlm_dense_')
            self.mlm_ln = nn.LayerNorm(in_channels=cfg['hidden'])
            self.mlm_decoder = nn.Dense(cfg['vocab_size'], flatten=False,
                                        in_units=cfg['hidden'],
                                        prefix='mlm_decoder_')
            self.nsp = nn.Dense(2, in_units=cfg['hidden'], prefix='nsp_')

    def forward(self, tokens, token_types=None, valid_length=None,
                masked_positions=None):
        """masked_positions: optional (N, M) int32 — the MLM-masked token
        positions. When given, the decoder runs only on those M positions
        (the GluonNLP pretraining recipe: ~15% of tokens are masked, so
        decoding all T positions wastes ~21% of step FLOPs on logits the
        loss discards). mlm is then (N, M, vocab) instead of (N, T, vocab).
        """
        seq, pooled = self.bert(tokens, token_types, valid_length)
        if masked_positions is not None:
            seq = _invoke(_gather_positions, seq, masked_positions)
        mlm = self.mlm_decoder(self.mlm_ln(self.mlm_dense(seq)))
        nsp = self.nsp(pooled)
        return mlm, nsp


def masked_cross_entropy(logits, labels):
    """Mean cross entropy over the positions where labels >= 0 (-1 marks
    padding/unmasked). Shared by the BERT MLM and GPT LM objectives."""
    logp = nd.log_softmax(logits, axis=-1)
    valid = (labels >= 0)
    safe_labels = nd.where(valid, labels, nd.zeros_like(labels))
    token_loss = -nd.pick(logp, safe_labels, axis=-1) * valid
    return nd.sum(token_loss) / (nd.sum(valid) + 1e-6)


def bert_pretrain_loss(mlm_logits, nsp_logits, labels, nsp_labels,
                       mask_weight=None):
    """Masked-LM + NSP cross entropy. labels: (N, T) with -1 for unmasked."""
    mlm_loss = masked_cross_entropy(mlm_logits, labels)
    nsp_logp = nd.log_softmax(nsp_logits, axis=-1)
    nsp_loss = nd.mean(-nd.pick(nsp_logp, nsp_labels, axis=-1))
    return mlm_loss + nsp_loss


# ---------------------------------------------------------------------------
# Pipeline-parallel bridge (VERDICT r4 #6): express the Gluon BERT as the
# embed → encoder-stages → head split that parallel/pipeline.py
# pipelines over a 'pp' mesh axis. The functional stage math mirrors
# BertLayer.forward exactly (eval mode — GPipe microbatching assumes
# deterministic stages), so a pipelined step is parity-comparable
# against the same Gluon model on the pure-DP path.
# ---------------------------------------------------------------------------

def _p(param):
    """A Gluon Parameter's jax payload."""
    return param.data()._data


def bert_pipeline_funcs(model: 'BertForPretraining', n_stages,
                        mesh=None, pp_axis='pp'):
    """Extract (params, embed_fn, stage_fn, head_fn, loss_fn) for
    parallel.PipelineTrainStep from an initialized BertForPretraining.

    The encoder's layers split evenly into `n_stages` pipeline stages
    (layers % n_stages == 0); embedding and the MLM/NSP heads replicate
    outside the pipeline.

    Constraints (validated, not assumed): the model must be built with
    dropout=0 — GPipe microbatch stages must be deterministic — and the
    pipelined forward is the token_types=None path (type_embed gets no
    gradient on the DP path either when token_types is never fed, so the
    two paths train the same weights).
    """
    import jax
    import jax.numpy as jnp
    from ..base import MXNetError
    from ..ops import nn as F
    from ..ops import attention as attn_ops
    from ..parallel.pipeline import split_layers_into_stages

    bert = model.bert
    heads = bert.encoder[0].attention._heads
    eps = bert.embed_ln._epsilon
    drop = bert.encoder[0].attention._attn_dropout
    if drop:
        raise MXNetError(
            f"bert_pipeline_funcs: model was built with dropout={drop}; "
            "pipeline stages must be deterministic — rebuild the model "
            "with dropout=0.0 (GPipe recomputes microbatches in bubble "
            "ticks, so stochastic stages would diverge from the DP path)")

    layer_params = []
    for layer in bert.encoder:
        a = layer.attention
        layer_params.append({
            'qkv_w': _p(a.qkv.weight), 'qkv_b': _p(a.qkv.bias),
            'proj_w': _p(a.proj.weight), 'proj_b': _p(a.proj.bias),
            'ln1_g': _p(layer.ln1.gamma), 'ln1_b': _p(layer.ln1.beta),
            'ffn1_w': _p(layer.ffn1.weight), 'ffn1_b': _p(layer.ffn1.bias),
            'ffn2_w': _p(layer.ffn2.weight), 'ffn2_b': _p(layer.ffn2.bias),
            'ln2_g': _p(layer.ln2.gamma), 'ln2_b': _p(layer.ln2.beta),
        })

    params = {
        'embed': {
            'word': _p(bert.word_embed.weight),
            'pos': _p(bert.pos_embed.weight),
            'ln_g': _p(bert.embed_ln.gamma),
            'ln_b': _p(bert.embed_ln.beta),
        },
        'stages': split_layers_into_stages(layer_params, n_stages),
        'head': {
            'pooler_w': _p(bert.pooler.weight),
            'pooler_b': _p(bert.pooler.bias),
            'mlm_w': _p(model.mlm_dense.weight),
            'mlm_b': _p(model.mlm_dense.bias),
            'mlm_ln_g': _p(model.mlm_ln.gamma),
            'mlm_ln_b': _p(model.mlm_ln.beta),
            'dec_w': _p(model.mlm_decoder.weight),
            'dec_b': _p(model.mlm_decoder.bias),
            'nsp_w': _p(model.nsp.weight),
            'nsp_b': _p(model.nsp.bias),
        },
    }

    def embed_fn(p, tokens):
        T = tokens.shape[-1]
        emb = p['word'][tokens.astype(jnp.int32)] \
            + p['pos'][jnp.arange(T, dtype=jnp.int32)][None, :, :]
        return F.layer_norm(emb, p['ln_g'], p['ln_b'], eps=eps)

    def one_layer(x, lp):
        qkv = x @ lp['qkv_w'].T + lp['qkv_b']
        q, k, v = jnp.split(qkv, 3, axis=-1)
        attn = attn_ops.multi_head_attention(q, k, v, num_heads=heads,
                                             dropout_p=0.0)
        attn = attn @ lp['proj_w'].T + lp['proj_b']
        x = F.layer_norm(x + attn, lp['ln1_g'], lp['ln1_b'], eps=eps)
        h = F.dense_gelu(x, lp['ffn1_w'], lp['ffn1_b'])
        h = h @ lp['ffn2_w'].T + lp['ffn2_b']
        return F.layer_norm(x + h, lp['ln2_g'], lp['ln2_b'], eps=eps)

    def stage_fn(sp, x):
        # sp leaves: (layers_per_stage, ...) — scan over the layer axis
        def body(carry, lp):
            return one_layer(carry, lp), None
        out, _ = jax.lax.scan(body, x, sp)
        return out

    def head_fn(p, seq):
        pooled = jnp.tanh(seq[:, 0, :] @ p['pooler_w'].T + p['pooler_b'])
        h = F.activation(seq @ p['mlm_w'].T + p['mlm_b'], act_type='gelu')
        h = F.layer_norm(h, p['mlm_ln_g'], p['mlm_ln_b'], eps=eps)
        mlm = h @ p['dec_w'].T + p['dec_b']
        nsp = pooled @ p['nsp_w'].T + p['nsp_b']
        return mlm, nsp

    def loss_fn(outputs, y):
        mlm_logits, nsp_logits = outputs
        labels, nsp_labels = y
        logp = jax.nn.log_softmax(mlm_logits, axis=-1)
        valid = (labels >= 0)
        safe = jnp.where(valid, labels, 0)
        tok = -jnp.take_along_axis(logp, safe[..., None].astype(jnp.int32),
                                   axis=-1)[..., 0] * valid
        mlm_loss = jnp.sum(tok) / (jnp.sum(valid) + 1e-6)
        nlogp = jax.nn.log_softmax(nsp_logits, axis=-1)
        nsp_loss = jnp.mean(-jnp.take_along_axis(
            nlogp, nsp_labels[:, None].astype(jnp.int32), axis=-1))
        return mlm_loss + nsp_loss

    return params, embed_fn, stage_fn, head_fn, loss_fn
