"""GPT-style decoder-only causal language model.

The autoregressive counterpart of the BERT flagship: pre-norm transformer
decoder blocks over the fused `multi_head_attention` op with
`causal=True`, which routes through the Pallas flash kernel's causal path
on TPU (ops/pallas_attention.py) — no (T, T) mask tensor is ever
materialised. Weight-tied output head (standard GPT recipe).

Ref: the reference ships encoder-style attention kernels
(src/operator/contrib/transformer.cc) and GluonNLP built GPT-2 on top of
them; here the causal variant is first-class.
"""
from __future__ import annotations

from ..gluon import nn
from ..gluon.block import HybridBlock
from .. import ndarray as nd
from ..ops import attention as attn_ops
from ..ndarray.ndarray import _invoke
from .bert import masked_cross_entropy


def gpt2_small_config():
    return dict(vocab_size=50257, hidden=768, layers=12, heads=12,
                max_len=1024)


class GPTBlock(HybridBlock):
    def __init__(self, hidden, heads, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self._heads = heads
        self._attn_dropout = dropout
        with self.name_scope():
            self.ln1 = nn.LayerNorm(in_channels=hidden)
            self.qkv = nn.Dense(3 * hidden, flatten=False,
                                in_units=hidden, prefix='qkv_')
            self.proj = nn.Dense(hidden, flatten=False, in_units=hidden,
                                 prefix='proj_')
            self.ln2 = nn.LayerNorm(in_channels=hidden)
            self.ffn1 = nn.Dense(4 * hidden, flatten=False,
                                 in_units=hidden, prefix='ffn1_')
            self.ffn2 = nn.Dense(hidden, flatten=False,
                                 in_units=4 * hidden, prefix='ffn2_')
            self.dropout = nn.Dropout(dropout)

    def forward(self, x):
        # pre-norm residual blocks (GPT-2 recipe)
        h = self.ln1(x)
        qkv = self.qkv(h)
        q, k, v = qkv.split(3, axis=-1)
        attn = _invoke(attn_ops.multi_head_attention, q, k, v, None,
                       num_heads=self._heads, dropout_p=self._attn_dropout,
                       causal=True)
        x = x + self.dropout(self.proj(attn))
        h = nd.activation(self.ffn1(self.ln2(x)), act_type='gelu')
        return x + self.dropout(self.ffn2(h))


class GPTModel(HybridBlock):
    """Decoder-only LM. forward(tokens) -> (N, T, vocab) logits with the
    output projection tied to the token embedding."""

    def __init__(self, vocab_size=50257, hidden=768, layers=12, heads=12,
                 max_len=1024, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self._cfg = dict(vocab_size=vocab_size, hidden=hidden,
                         layers=layers, heads=heads, max_len=max_len)
        with self.name_scope():
            self.word_embed = nn.Embedding(vocab_size, hidden,
                                           prefix='word_embed_')
            self.pos_embed = nn.Embedding(max_len, hidden,
                                          prefix='pos_embed_')
            self.embed_dropout = nn.Dropout(dropout)
            self.blocks = nn.HybridSequential(prefix='blocks_')
            with self.blocks.name_scope():
                for _ in range(layers):
                    self.blocks.add(GPTBlock(hidden, heads, dropout))
            self.ln_f = nn.LayerNorm(in_channels=hidden)

    def forward(self, tokens):
        T = tokens.shape[1]
        pos = nd.arange(0, T, dtype='int32').reshape(1, T)
        x = self.embed_dropout(self.word_embed(tokens)
                               + self.pos_embed(pos))
        for blk in self.blocks:
            x = blk(x)
        x = self.ln_f(x)
        # weight-tied LM head: logits = x @ E^T (data() resolves to the
        # trace proxy inside a compiled step)
        return nd.dot(x, self.word_embed.weight.data(), transpose_b=True)


def gpt_lm_loss(logits, labels):
    """Next-token cross entropy; labels = tokens shifted left, -1 pads."""
    return masked_cross_entropy(logits, labels)
