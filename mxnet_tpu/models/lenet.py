"""LeNet for MNIST (ref: example/gluon/mnist.py network shape)."""
from __future__ import annotations

from ..gluon import nn


class LeNet(nn.HybridBlock):
    def __init__(self, classes=10, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix='')
            self.features.add(nn.Conv2D(20, kernel_size=5, activation='relu'))
            self.features.add(nn.MaxPool2D(pool_size=2, strides=2))
            self.features.add(nn.Conv2D(50, kernel_size=5, activation='relu'))
            self.features.add(nn.MaxPool2D(pool_size=2, strides=2))
            self.features.add(nn.Flatten())
            self.features.add(nn.Dense(500, activation='relu'))
            self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))
