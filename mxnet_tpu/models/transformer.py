"""Transformer encoder-decoder (ref: example/gluon transformer / the
contrib interleaved attention ops, src/operator/contrib/transformer.cc)."""
from __future__ import annotations

import math

from ..gluon import nn
from ..gluon.block import HybridBlock
from .. import ndarray as nd
from ..ops import attention as attn_ops
from ..ndarray.ndarray import _invoke


class PositionalEncoding(HybridBlock):
    def __init__(self, hidden, max_len=1024, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        import numpy as onp
        pe = onp.zeros((max_len, hidden), onp.float32)
        position = onp.arange(max_len)[:, None].astype(onp.float32)
        div = onp.exp(onp.arange(0, hidden, 2) * (-math.log(10000.0) / hidden))
        pe[:, 0::2] = onp.sin(position * div)
        pe[:, 1::2] = onp.cos(position * div)
        self.pe = self.params.get_constant('pe', pe)
        self.dropout = nn.Dropout(dropout)

    def forward(self, x):
        T = x.shape[1]
        pe = self.pe.data(x.context)
        return self.dropout(x + nd.slice_axis(pe, axis=0, begin=0, end=T)
                            .expand_dims(0))


class MultiHeadAttention(HybridBlock):
    """`causal` is a construction-time flag: Block.__call__ forwards only
    positional tensors (reference semantics), so masking mode cannot ride
    the call."""

    def __init__(self, hidden, heads, dropout=0.1, causal=False, **kwargs):
        super().__init__(**kwargs)
        self._heads = heads
        self._causal = causal
        with self.name_scope():
            self.q_proj = nn.Dense(hidden, flatten=False, in_units=hidden)
            self.k_proj = nn.Dense(hidden, flatten=False, in_units=hidden)
            self.v_proj = nn.Dense(hidden, flatten=False, in_units=hidden)
            self.out_proj = nn.Dense(hidden, flatten=False, in_units=hidden)

    def forward(self, q, k, v, mask=None):
        out = _invoke(attn_ops.multi_head_attention,
                      self.q_proj(q), self.k_proj(k), self.v_proj(v), mask,
                      num_heads=self._heads, causal=self._causal)
        return self.out_proj(out)


class EncoderLayer(HybridBlock):
    def __init__(self, hidden, heads, ffn_hidden, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.attn = MultiHeadAttention(hidden, heads, dropout)
            self.ln1 = nn.LayerNorm(in_channels=hidden)
            self.ffn1 = nn.Dense(ffn_hidden, flatten=False, in_units=hidden)
            self.ffn2 = nn.Dense(hidden, flatten=False, in_units=ffn_hidden)
            self.ln2 = nn.LayerNorm(in_channels=hidden)
            self.drop = nn.Dropout(dropout)

    def forward(self, x, mask=None):
        x = self.ln1(x + self.drop(self.attn(x, x, x, mask)))
        h = self.ffn2(nd.activation(self.ffn1(x), act_type='relu'))
        return self.ln2(x + self.drop(h))


class DecoderLayer(HybridBlock):
    def __init__(self, hidden, heads, ffn_hidden, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.self_attn = MultiHeadAttention(hidden, heads, dropout,
                                                causal=True)
            self.ln1 = nn.LayerNorm(in_channels=hidden)
            self.cross_attn = MultiHeadAttention(hidden, heads, dropout)
            self.ln2 = nn.LayerNorm(in_channels=hidden)
            self.ffn1 = nn.Dense(ffn_hidden, flatten=False, in_units=hidden)
            self.ffn2 = nn.Dense(hidden, flatten=False, in_units=ffn_hidden)
            self.ln3 = nn.LayerNorm(in_channels=hidden)
            self.drop = nn.Dropout(dropout)

    def forward(self, x, memory, mem_mask=None):
        x = self.ln1(x + self.drop(self.self_attn(x, x, x)))
        x = self.ln2(x + self.drop(self.cross_attn(x, memory, memory,
                                                   mem_mask)))
        h = self.ffn2(nd.activation(self.ffn1(x), act_type='relu'))
        return self.ln3(x + self.drop(h))


class TransformerEncoder(HybridBlock):
    def __init__(self, vocab_size, hidden=512, layers=6, heads=8,
                 ffn_hidden=2048, max_len=1024, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self._hidden = hidden
        with self.name_scope():
            self.embed = nn.Embedding(vocab_size, hidden)
            self.pos = PositionalEncoding(hidden, max_len, dropout)
            self.layers = nn.HybridSequential(prefix='layers_')
            with self.layers.name_scope():
                for _ in range(layers):
                    self.layers.add(EncoderLayer(hidden, heads, ffn_hidden,
                                                 dropout))

    def forward(self, tokens, mask=None):
        x = self.pos(self.embed(tokens) * math.sqrt(self._hidden))
        for layer in self.layers:
            x = layer(x, mask)
        return x


class TransformerModel(HybridBlock):
    """Full enc-dec (transformer-big when hidden=1024, heads=16)."""

    def __init__(self, src_vocab, tgt_vocab, hidden=512, enc_layers=6,
                 dec_layers=6, heads=8, ffn_hidden=2048, max_len=1024,
                 dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self._hidden = hidden
        with self.name_scope():
            self.encoder = TransformerEncoder(src_vocab, hidden, enc_layers,
                                              heads, ffn_hidden, max_len,
                                              dropout)
            self.tgt_embed = nn.Embedding(tgt_vocab, hidden)
            self.tgt_pos = PositionalEncoding(hidden, max_len, dropout)
            self.dec_layers = nn.HybridSequential(prefix='dec_')
            with self.dec_layers.name_scope():
                for _ in range(dec_layers):
                    self.dec_layers.add(DecoderLayer(hidden, heads,
                                                     ffn_hidden, dropout))
            self.out_proj = nn.Dense(tgt_vocab, flatten=False,
                                     in_units=hidden)

    def forward(self, src_tokens, tgt_tokens, src_mask=None):
        memory = self.encoder(src_tokens, src_mask)
        x = self.tgt_pos(self.tgt_embed(tgt_tokens) * math.sqrt(self._hidden))
        for layer in self.dec_layers:
            x = layer(x, memory, src_mask)
        return self.out_proj(x)
