"""Reference model implementations used by benchmarks and examples.

LeNet (ref: example/gluon/mnist), BERT-base (GluonNLP recipe — the north
star config), Transformer (example/gluon/transformer shape), GPT-style
causal LM (decoder-only over the flash kernel's causal path), built on
mxnet_tpu.gluon.
"""
from .lenet import LeNet
from .bert import BertModel, BertForPretraining, bert_base_config, bert_pretrain_loss
from .transformer import TransformerEncoder, TransformerModel
from .gpt import GPTModel, gpt_lm_loss, gpt2_small_config
from .ssd import SSD, ssd_512, ssd_300, ssd_train_loss
