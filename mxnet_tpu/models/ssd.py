"""SSD single-shot detector — the SSD-512 verification config
(BASELINE.json configs; ref: example/ssd/symbol/symbol_builder.py and the
multibox ops src/operator/contrib/multibox_{prior,target,detection}.cc).

TPU-first shape discipline: anchors are a compile-time constant for a
given input size (multibox_prior runs on static feature-map shapes), the
whole forward is hybridizable into one XLA program, and training labels
ride as a fixed-size (B, M, 5) padded tensor so the step never recompiles.
"""
from __future__ import annotations

from ..gluon import nn
from ..gluon.block import HybridBlock
from .. import ndarray as nd
from ..ndarray.ndarray import _invoke


def _feature_block(channels, repeats, pool=True):
    blk = nn.HybridSequential()
    for _ in range(repeats):
        blk.add(nn.Conv2D(channels, 3, padding=1))
        blk.add(nn.BatchNorm())
        blk.add(nn.Activation('relu'))
    if pool:
        blk.add(nn.MaxPool2D(2, strides=2))
    return blk


def _down_block(channels):
    """Extra feature layer: 1x1 squeeze + 3x3 stride-2 (SSD extras)."""
    blk = nn.HybridSequential()
    blk.add(nn.Conv2D(channels // 2, 1))
    blk.add(nn.BatchNorm())
    blk.add(nn.Activation('relu'))
    blk.add(nn.Conv2D(channels, 3, strides=2, padding=1))
    blk.add(nn.BatchNorm())
    blk.add(nn.Activation('relu'))
    return blk


# per-scale anchor sizes/ratios for the 512 config (ref:
# example/ssd/symbol/legacy_vgg16_ssd_512.py get_symbol anchor params)
_SSD512_SIZES = [(.07, .1025), (.15, .2121), (.3, .3674), (.45, .5196),
                 (.6, .6708), (.75, .8216), (.9, .9721)]
_SSD512_RATIOS = [[1, 2, .5]] + [[1, 2, .5, 3, 1. / 3]] * 5 + [[1, 2, .5]]


class SSD(HybridBlock):
    """Backbone + multi-scale heads. num_classes EXCLUDES background
    (VOC=20); class predictions carry num_classes+1 channels.

    The default backbone is a compact VGG-style stack; scales halve the
    feature map down to 1x1 like the reference's 512 config.
    """

    def __init__(self, num_classes=20, image_size=512, sizes=None,
                 ratios=None, **kwargs):
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.image_size = image_size
        self._sizes = sizes or _SSD512_SIZES
        self._ratios = ratios or _SSD512_RATIOS
        n_scales = len(self._sizes)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix='backbone_')
            with self.features.name_scope():
                self.features.add(_feature_block(32, 1))
                self.features.add(_feature_block(64, 1))
                self.features.add(_feature_block(128, 2))
            self.stages = nn.HybridSequential(prefix='stages_')
            self.cls_heads = nn.HybridSequential(prefix='cls_')
            self.loc_heads = nn.HybridSequential(prefix='loc_')
            with self.stages.name_scope():
                self.stages.add(_feature_block(256, 2, pool=False))
                for _ in range(n_scales - 1):
                    self.stages.add(_down_block(256))
            for i in range(n_scales):
                n_anchor = len(self._sizes[i]) + len(self._ratios[i]) - 1
                with self.cls_heads.name_scope():
                    self.cls_heads.add(nn.Conv2D(
                        n_anchor * (num_classes + 1), 3, padding=1))
                with self.loc_heads.name_scope():
                    self.loc_heads.add(nn.Conv2D(n_anchor * 4, 3, padding=1))

    def forward(self, x):
        """x: (B, 3, S, S) -> (anchors (1, A, 4) corner,
        cls_preds (B, num_cls+1, A), loc_preds (B, A*4))."""
        from ..ops.contrib import multibox_prior
        import jax.numpy as jnp
        x = self.features(x)
        anchors, cls_preds, loc_preds = [], [], []
        B = x.shape[0]
        C1 = self.num_classes + 1
        for i, stage in enumerate(self.stages):
            x = stage(x)
            anc = _invoke(multibox_prior, x, sizes=tuple(self._sizes[i]),
                          ratios=tuple(self._ratios[i]))     # (1, hw*a, 4)
            cls = self.cls_heads[i](x)                       # (B, a*C1, h, w)
            loc = self.loc_heads[i](x)
            anchors.append(anc)
            # (B, a*C1, h, w) -> (B, hw*a, C1): transpose then group
            cls_preds.append(cls.transpose((0, 2, 3, 1))
                             .reshape(B, -1, C1))
            loc_preds.append(loc.transpose((0, 2, 3, 1)).reshape(B, -1))
        anchor = nd.concat(*anchors, dim=1)
        cls_pred = nd.concat(*cls_preds, dim=1).transpose((0, 2, 1))
        loc_pred = nd.concat(*loc_preds, dim=1)
        return anchor, cls_pred, loc_pred

    def detect(self, x, nms_threshold=0.45, threshold=0.01, nms_topk=400):
        """Decoded detections (B, A, 6) [cls, score, x0, y0, x1, y1]."""
        from ..ops.detection import multibox_detection
        anchor, cls_pred, loc_pred = self(x)
        prob = nd.softmax(cls_pred, axis=1)
        return _invoke(multibox_detection, prob, loc_pred, anchor,
                       nms_threshold=nms_threshold, threshold=threshold,
                       nms_topk=nms_topk)


def ssd_512(num_classes=20, **kwargs):
    """SSD-512 (BASELINE.json verification config)."""
    return SSD(num_classes=num_classes, image_size=512, **kwargs)


def ssd_300(num_classes=20, **kwargs):
    """A 300-input variant with the 512 head layout minus one scale."""
    return SSD(num_classes=num_classes, image_size=300,
               sizes=_SSD512_SIZES[:6], ratios=_SSD512_RATIOS[:6], **kwargs)


def ssd_train_loss(anchor, cls_pred, loc_pred, label,
                   negative_mining_ratio=3.0):
    """MultiBox training loss: cross entropy over mined classes + smooth-L1
    on positive boxes, normalised by positive count (ref:
    example/ssd/train/metric.py recipe + multibox_target.cc).
    label: (B, M, 5) rows [cls x0 y0 x1 y1], -1-padded."""
    from ..ops.detection import multibox_target
    box_t, box_m, cls_t = _invoke(
        multibox_target, anchor, label, cls_pred,
        negative_mining_ratio=negative_mining_ratio)
    # classification: ignore_label -1 rows drop out of the loss
    logp = nd.log_softmax(cls_pred.transpose((0, 2, 1)), axis=-1)
    keep = (cls_t >= 0)
    safe = nd.where(keep, cls_t, nd.zeros_like(cls_t))
    cls_loss = -nd.pick(logp, safe, axis=-1) * keep
    # localization: smooth-L1 on masked offsets
    diff = nd.abs((loc_pred - box_t) * box_m)
    loc_loss = nd.where(diff < 1.0, 0.5 * diff * diff, diff - 0.5)
    n_pos = nd.sum(box_m) / 4.0 + 1e-6
    return (nd.sum(cls_loss) + nd.sum(loc_loss)) / n_pos
