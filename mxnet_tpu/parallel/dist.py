"""Multi-process distributed init + launcher + elastic membership.

Ref: tools/launch.py + dmlc tracker (scheduler/server/worker env bootstrap
via DMLC_ROLE / DMLC_PS_ROOT_URI). TPU-native: `jax.distributed.initialize`
replaces the tracker; there are no server processes — every process is a
symmetric worker and collectives ride ICI/DCN.

Env protocol (launch-compatible shape):
  MXNET_TPU_COORDINATOR  host:port of process 0
  MXNET_TPU_NUM_PROCS    total processes
  MXNET_TPU_PROC_ID      this process's rank
(Also accepts the DMLC_* names for drop-in use of reference launch scripts.)

Elastic membership (`MXTPU_ELASTIC=1`, ROADMAP item 4): the ps-lite
tracker's worker-churn awareness has no analog in jax.distributed — a
preempted host wedges every peer inside a collective until the job dies.
The ``Membership`` layer closes that gap on a lightweight TCP side
channel (NEVER the ICI collectives, which are exactly what a lost peer
wedges): rank 0 runs a coordinator thread tracking per-peer heartbeat
ages, every process runs a sender thread beating once per
``MXTPU_HEARTBEAT_SECONDS``, and a peer silent for
``MXTPU_PEER_DEADLINE_SECONDS`` is declared LOST — the signal
``resilience.ElasticController`` turns into commit -> re-form -> resume.
"""
from __future__ import annotations

import collections
import json
import logging
import os
import re
import shutil
import socket
import subprocess
import sys
import threading
import time as _time

import jax

from ..base import MXNetError, telem_flags as _telem

_log = logging.getLogger('mxnet_tpu.dist')

_initialized = False
_membership = None
# publication lock for the process-global membership: membership() is
# read from the watchdog/elastic-monitor/endpoint threads while
# start_/stop_membership swap the reference on the main thread. RLock
# by the signal-safety rationale: membership() is reachable from the
# SIGTERM preemption path (manifest `world` metadata).
_membership_lock = threading.RLock()


def _resolve_world(coordinator=None, num_processes=None, process_id=None,
                   need_coordinator=True):
    """One resolution of (coordinator, world, rank) from args/env —
    shared by ``init()`` and ``start_membership()`` so the two can never
    derive different coordinators (the membership side-channel port is
    derived from the coordinator's). MXNET_TPU_* first, the DMLC_*
    drop-in names next. The coordinator (and with it the
    localhost-fallback warning) is only resolved when actually needed —
    a single-process init has nobody to rendezvous with."""
    from .. import config as _config
    num_processes = num_processes \
        or _config.get('MXNET_TPU_NUM_PROCS') \
        or int(os.environ.get('DMLC_NUM_WORKER', '1'))
    if process_id is None:
        pid = _config.get('MXNET_TPU_PROC_ID')
        process_id = pid if pid >= 0 \
            else int(os.environ.get('DMLC_WORKER_ID', '0'))
    if need_coordinator:
        coordinator = coordinator \
            or _config.get('MXNET_TPU_COORDINATOR') \
            or _dmlc_coordinator()
    return coordinator, int(num_processes), int(process_id)


def init(coordinator=None, num_processes=None, process_id=None,
         local_device_ids=None):
    """Initialize jax.distributed from args or env.

    Transient "coordinator not yet listening" races (workers regularly
    start before rank 0's service binds) get a bounded retry with
    exponential backoff (``MXTPU_DIST_INIT_RETRIES``) instead of a fatal
    error. With ``MXTPU_ELASTIC=1`` the membership side channel starts
    here too (see ``Membership``)."""
    global _initialized
    if _initialized:
        return
    from .. import config as _config
    _, num_processes, process_id = _resolve_world(
        None, num_processes, process_id, need_coordinator=False)
    elastic = bool(_config.get('MXTPU_ELASTIC'))
    if num_processes > 1 or elastic:
        # only now is a coordinator address needed (and only now may
        # the localhost-fallback warning fire)
        coordinator, _, _ = _resolve_world(
            coordinator, num_processes, process_id)
    if num_processes > 1:
        from ..resilience.retry import retry_call
        target = _initialize_once if elastic else \
            jax.distributed.initialize

        def _attempt(**kw):
            # jaxlib surfaces BOTH transient connect races (grpc
            # DEADLINE_EXCEEDED / UNAVAILABLE) and permanent mistakes
            # as RuntimeError — classify, so a double init or bad
            # argument fails immediately instead of burning the whole
            # backoff budget behind misleading 'transient' warnings
            try:
                return target(**kw)
            except RuntimeError as e:
                if any(t in str(e) for t in
                       ('only be called once', 'should be defined',
                        'must be defined')):
                    raise MXNetError(
                        f"dist.init: non-transient "
                        f"jax.distributed.initialize failure (not "
                        f"retried): {e}") from e
                raise

        retry_call(
            _attempt,
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
            retries=_config.get('MXTPU_DIST_INIT_RETRIES'),
            backoff_seconds=0.25,
            retry_on=(RuntimeError, ConnectionError, OSError),
            give_up_on=(MXNetError,),
            site='dist.init')
    _initialized = True
    if _config.get('MXTPU_ELASTIC') and _membership is None:
        start_membership(coordinator=coordinator,
                         num_processes=num_processes,
                         process_id=process_id)


_elastic_client = False


def _initialize_once(coordinator_address, num_processes, process_id,
                     local_device_ids=None):
    """Elastic-mode jax.distributed bring-up. Mirrors
    jax._src.distributed.State.initialize but builds the client with the
    knobs the stock wrapper does not expose:

    - ``shutdown_on_destruction=False``: dropping the handle must not
      enter the runtime's shutdown barrier — that barrier waits for
      EVERY peer, the dead one included, which is exactly the wedge
      elastic teardown escapes (``shutdown()`` above relies on this).
    - ``shutdown_timeout=5``: if the orderly barrier IS entered (healthy
      world), give up in seconds, not the 5-minute default.
    """
    from jax._src import config as _jax_config
    from jax._src import distributed as _jd
    from jax._src.lib import xla_extension
    state = _jd.global_state
    if state.client is not None:
        return
    if isinstance(local_device_ids, int):
        local_device_ids = [local_device_ids]
    if local_device_ids:
        # same per-process device pinning stock initialize applies
        visible = ','.join(str(x) for x in local_device_ids)
        _jax_config.update('jax_cuda_visible_devices', visible)
        _jax_config.update('jax_rocm_visible_devices', visible)
    state.coordinator_address = coordinator_address
    bind = '[::]:' + coordinator_address.rsplit(':', 1)[1]
    if process_id == 0 and state.service is None:
        state.service = xla_extension.get_distributed_runtime_service(
            bind, num_processes)
    state.num_processes = num_processes
    state.process_id = process_id
    global _elastic_client
    client = xla_extension.get_distributed_runtime_client(
        coordinator_address, process_id, init_timeout=300,
        shutdown_timeout=5, shutdown_on_destruction=False,
        use_compression=True)
    client.connect()
    state.client = client
    _elastic_client = True
    try:
        state.initialize_preemption_sync_manager()
    except Exception:
        pass


def shutdown(timeout=5.0):
    """Tear down jax.distributed (elastic re-form path).

    The runtime's orderly ``client.shutdown()`` is a BARRIER over every
    peer — including the dead one — and blocks until they all arrive:
    exactly the wedge elastic teardown exists to escape. So with a dead
    peer the elastic path never enters it: the client handle (created
    with ``shutdown_on_destruction=False`` by ``_initialize_once``) is
    dropped, the coordination service is stopped on a daemon thread with
    a bounded join (stopping it aborts the barrier server-side), and the
    distributed bookkeeping is reset so ``process_count()`` and jax's
    own atexit hook see a clean single-process state. Non-elastic
    clients (stock ``jax.distributed.initialize``) still get the orderly
    shutdown, also bounded. Returns True when the teardown completed
    within ``timeout``."""
    global _initialized
    _initialized = False
    try:
        state = jax._src.distributed.global_state
    except Exception:
        return True
    if state.client is None and state.service is None:
        return True
    # hand the live handles to the teardown thread in a box, then reset
    # the bookkeeping FIRST: jax's atexit clean_up consults these same
    # fields — once they are None it cannot re-enter the barrier
    box = [state.client, state.service]
    state.client = None
    state.service = None
    state.process_id = 0
    state.num_processes = 1
    state.preemption_sync_manager = None
    state.coordinator_address = None
    done = threading.Event()
    elastic = _elastic_client

    def _do():
        client, service = box[0], box[1]
        try:
            if not elastic and client is not None:
                client.shutdown()     # orderly barrier: healthy world
            # elastic: NEVER enter the shutdown barrier (it waits for
            # the dead peer) — drop the last client reference instead;
            # shutdown_on_destruction=False makes the destructor stop
            # the agent threads without any peer rendezvous, measured
            # ~20 ms, after which the service stops cleanly
            box[0] = client = None
            if service is not None:
                service.shutdown()
        except Exception as e:
            _log.warning("distributed teardown: %r", e)
        finally:
            box[1] = None
            done.set()

    threading.Thread(target=_do, daemon=True,
                     name='mxtpu-dist-shutdown').start()
    if not done.wait(timeout):
        _log.warning(
            "distributed teardown did not finish within %.1fs; "
            "abandoning it on a daemon thread (bookkeeping already "
            "reset — survivors keep making progress)", timeout)
        return False
    return True


def reinit(coordinator, num_processes, process_id,
           local_device_ids=None):
    """Re-initialize jax.distributed at a NEW world size (after
    ``shutdown()``) — the re-form half of elastic training. World size 1
    needs no distributed runtime at all."""
    global _initialized
    _initialized = False
    if num_processes <= 1:
        _initialized = True
        return
    init(coordinator=coordinator, num_processes=num_processes,
         process_id=process_id, local_device_ids=local_device_ids)


def _dmlc_coordinator():
    uri = os.environ.get('DMLC_PS_ROOT_URI')
    port = os.environ.get('DMLC_PS_ROOT_PORT', '9000')
    if uri:
        return f"{uri}:{port}"
    _log.warning(
        "dist.init: no coordinator address configured — looked for "
        "MXNET_TPU_COORDINATOR, then DMLC_PS_ROOT_URI[:DMLC_PS_ROOT_PORT] "
        "— falling back to localhost:12345 (fine single-host; multi-host "
        "workers will hang at initialize until one of those env vars "
        "names rank 0)")
    return 'localhost:12345'


def rank():
    return jax.process_index()


def num_workers():
    return jax.process_count()


def host_topology(devices):
    """Group ``devices`` (in order) into per-host runs by their owning
    process: ``[(process_index, [device, ...]), ...]``. This is the
    hierarchy query the compressed-collective path builds its
    (cross-host, intra-host) dp decomposition from — the same
    host-level world the elastic membership layer heartbeats over (one
    membership rank per jax process). Contiguous runs only: a device
    order that interleaves processes yields more groups than processes,
    which ``dp_host_split`` treats as "no clean hierarchy"."""
    groups = []
    for d in devices:
        p = getattr(d, 'process_index', 0)
        if groups and groups[-1][0] == p:
            groups[-1][1].append(d)
        else:
            groups.append((p, [d]))
    return groups


def dp_host_split(devices, force=None):
    """(n_hosts, devices_per_host) decomposition of a dp-axis device
    run, or ``(1, len(devices))`` when no clean hierarchy exists.

    ``force`` (or the ``MXTPU_HIERARCHICAL_DP`` knob when None):
    0 auto-detects from the device->process topology via
    ``host_topology``; 1 forces flat; N>=2 forces N equal contiguous
    groups (CPU simulation — single-process meshes have no real host
    boundary to discover). Auto-detection requires equal-size
    contiguous per-process runs; anything else falls back flat rather
    than build a lopsided hierarchy."""
    from .. import config as _config
    n = len(devices)
    if force is None:
        force = int(_config.get('MXTPU_HIERARCHICAL_DP') or 0)
    force = int(force)
    if force == 1 or n <= 1:
        return 1, n
    if force >= 2:
        if n % force != 0:
            raise MXNetError(
                f"MXTPU_HIERARCHICAL_DP={force}: the dp axis has {n} "
                f"devices, not divisible into {force} equal host "
                f"groups — pick a divisor of {n} or 0 (auto).")
        return force, n // force
    groups = host_topology(devices)
    sizes = {len(ds) for _p, ds in groups}
    procs = {p for p, _ds in groups}
    if len(groups) <= 1 or len(sizes) != 1 or len(procs) != len(groups):
        return 1, n
    return len(groups), n // len(groups)


# ---------------------------------------------------------------------------
# elastic membership side channel
# ---------------------------------------------------------------------------

def _elastic_port(coordinator=None):
    """Side-channel port: MXTPU_ELASTIC_PORT, else jax coordinator port
    + 1000 (keeps parallel jobs on one host from colliding)."""
    from .. import config as _config
    port = _config.get('MXTPU_ELASTIC_PORT')
    if port:
        return int(port)
    base = 12345
    coordinator = coordinator or _config.get('MXNET_TPU_COORDINATOR')
    if coordinator and ':' in coordinator:
        try:
            base = int(coordinator.rsplit(':', 1)[1])
        except ValueError:
            pass
    return base + 1000


# the reserved barrier tag of the scale-up admission rendezvous: its
# completion set includes the PENDING joiners (not just the alive
# ranks), and completing it is the admission point — the coordinator
# promotes every pending joiner into the alive set atomically with the
# generation bump (see Membership._handle_locked)
ADMIT_TAG = 'admit'


class Membership:
    """Heartbeat-tracked peer membership over a TCP side channel.

    Rank 0 is the membership coordinator: a server thread answers one
    JSON line per connection (``{'op': 'beat'|'leave'|'view'|'barrier',
    'rank': r, ...}``) with the current view (``{'world', 'alive',
    'ages', 'lost', 'left'}``). Every rank — 0 included — runs a sender
    thread that beats once per ``heartbeat_seconds`` (rank 0 short-
    circuits to a local state update so the coordinator never depends on
    its own socket). A peer whose heartbeat age exceeds
    ``deadline_seconds`` is LOST; a peer that said goodbye (``leave()``,
    the SIGTERM path) is LEFT — departed but not a failure.

    The side channel is deliberately not the collective fabric: a peer
    wedged inside an ICI collective still heartbeats (the sender is a
    daemon thread), while a SIGKILLed/preempted peer goes silent on both
    — which is exactly the distinction the stall classifier needs
    (``resilience.elastic.stall_verdict``)."""

    def __init__(self, rank, world, coordinator_host='127.0.0.1',
                 port=None, heartbeat_seconds=None, deadline_seconds=None,
                 start=True):
        from .. import config as _config
        self.rank = int(rank)
        self.world = int(world)
        self.coordinator_host = coordinator_host
        self.port = int(port) if port else _elastic_port()
        self.heartbeat_seconds = float(
            heartbeat_seconds if heartbeat_seconds is not None
            else _config.get('MXTPU_HEARTBEAT_SECONDS'))
        self.deadline_seconds = float(
            deadline_seconds if deadline_seconds is not None
            else _config.get('MXTPU_PEER_DEADLINE_SECONDS'))
        self.is_coordinator = self.rank == 0
        self.current_step = None      # piggybacked on each beat
        # RLock: view()/lost_peers() are reachable from the checkpoint
        # SIGTERM handler (save() records the membership world in the
        # manifest) — a signal landing while THIS thread holds a plain
        # Lock would self-deadlock the preemption save. Critical
        # sections are tiny and never block, so reentrancy is safe.
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._threads = []
        self._server = None
        # fleet-telemetry piggyback (ISSUE 13): a provider callable
        # yields a compact snapshot dict attached to each beat; the
        # coordinator keeps the newest per rank and hands each one to
        # on_snapshot (the fleet monitor) OUTSIDE the membership lock
        # (and, for remote beats, AFTER the reply is written — the
        # hook must not inflate the sender's measured RTT).
        # on_peers_removed mirrors remove_peers into the monitor so a
        # departed rank cannot haunt the straggler verdict forever.
        self.telemetry_provider = None
        self.on_snapshot = None
        self.on_peers_removed = None
        # coordinator-side: a callable returning the current flagged
        # straggler summary (or None), attached to every reply — so
        # WORKER watchdogs can name the suspect too, not just rank 0
        # ((world-1)/world of wedges happen on a non-coordinator)
        self.verdict_provider = None
        self._telem = {}              # rank -> {'snap','mono','time'}
        # (rtt, offset, when) samples of this clock vs the
        # coordinator's, one per beat round-trip; the min-RTT sample in
        # the window is the clock_offset() estimate (NTP's intuition:
        # the tightest round-trip bounds the asymmetry error best)
        self._off_samples = collections.deque(maxlen=64)
        # coordinator state (rank 0)
        now = _time.monotonic()
        self._last_beat = {r: now for r in range(self.world)}
        self._steps = {}
        self._left = set()
        # JOIN candidates pending admission (scale-up): rank ->
        # announcement time, with liveness tracked separately in
        # _join_beat so a joiner that dies again BEFORE admission is
        # garbage-collected instead of wedging every future admit
        # rendezvous. Promotion into _last_beat happens only when the
        # admission rendezvous (barrier tag ADMIT_TAG) completes.
        self._joining = {}
        self._join_beat = {}
        self._barriers = {}           # tag -> {rank: nonce} arrived this gen
        self._barrier_gen = {}        # tag -> completed-rendezvous count
        self._barrier_done = {}       # tag -> {rank: (nonce, gen)} latest
        self._barrier_calls = 0
        # sender-side state (every rank)
        self._view = None             # last view dict from the coordinator
        self._last_ok = now           # last successful beat round-trip
        self.send_failures = 0
        if start:
            self.start()

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        # restartable: stop()/leave() set the event — a re-start (or a
        # become_coordinator promotion) must not spawn threads that see
        # it still set and exit on their first wait
        self._stop.clear()
        if self.is_coordinator and self._server is None:
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind(('', self.port))
            srv.listen(16)
            srv.settimeout(0.2)
            self._server = srv
            t = threading.Thread(target=self._serve, daemon=True,
                                 name='mxtpu-membership-coord')
            t.start()
            self._threads.append(t)
        if not getattr(self, '_beating', False):
            self._beating = True
            t = threading.Thread(target=self._beat_loop, daemon=True,
                                 name='mxtpu-membership-beat')
            t.start()
            self._threads.append(t)
        return self

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=max(1.0, 2 * self.heartbeat_seconds))
        self._threads = []
        self._beating = False
        # retire the socket under the lock: a server thread that
        # outlived its join timeout (wedged handler) reads the handle
        # through the same lock, so it sees either the live socket
        # (accept then raises OSError on the close) or None — never a
        # torn in-between
        with self._lock:
            srv, self._server = self._server, None
        if srv is not None:
            try:
                srv.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- coordinator server (rank 0) ---------------------------------------

    def _serve(self):
        with self._lock:
            srv = self._server
        while srv is not None and not self._stop.is_set():
            try:
                conn, _addr = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            msg = None
            try:
                conn.settimeout(1.0)
                with conn, conn.makefile('rwb') as f:
                    line = f.readline()
                    if not line:
                        continue
                    msg = json.loads(line.decode())
                    reply = self._finish_reply(self._handle_locked(msg))
                    f.write(json.dumps(reply).encode() + b'\n')
                    f.flush()
            except (OSError, ValueError):
                pass
            # hooks AFTER the reply is on the wire (the fleet monitor's
            # detector pass must not inflate the sender's measured beat
            # RTT) — but regardless of whether the write SUCCEEDED:
            # _handle_locked already mutated state, and skipping e.g.
            # the 'remove' mirror on a client disconnect would leave a
            # departed rank haunting the monitor forever
            if msg is not None:
                self._run_hooks(msg)

    def _handle(self, msg):
        reply = self._finish_reply(self._handle_locked(msg))
        self._run_hooks(msg)
        return reply

    def _finish_reply(self, reply):
        """Reply enrichment, outside the membership lock: the
        coordinator wall clock ('now' — stamped as close to the reply
        as possible, the sender's round-trip turns it into a
        clock-offset sample) and the current flagged straggler summary
        (so every rank's cached view can upgrade its own watchdog
        verdict)."""
        if not isinstance(reply, dict):
            return reply
        reply['now'] = _time.time()
        provider = self.verdict_provider
        if provider is not None:
            try:
                s = provider()
                if s is not None:
                    reply['straggler'] = s
            except Exception:
                pass
        return reply

    def _run_hooks(self, msg):
        """Fleet hooks, OUTSIDE the membership lock: the monitor takes
        its own lock and emits flight notes/metrics — nesting those
        acquisitions under self._lock would add a cross-module lock
        edge (tools/mxtpu_lint lock-order rule). Remote requests run
        this after the reply is written (see _serve)."""
        op = msg.get('op')
        if op == 'beat' and msg.get('telem') is not None:
            hook = self.on_snapshot
            if hook is not None:
                try:
                    hook(int(msg.get('rank', -1)), msg['telem'])
                except Exception:
                    _log.exception("membership: on_snapshot hook failed")
        elif op == 'remove':
            hook = self.on_peers_removed
            if hook is not None:
                try:
                    hook([int(r) for r in msg.get('ranks', [])])
                except Exception:
                    _log.exception(
                        "membership: on_peers_removed hook failed")

    def _handle_locked(self, msg):
        op = msg.get('op')
        r = int(msg.get('rank', -1))
        with self._lock:
            if op == 'beat':
                if r in self._joining:
                    # PENDING joiner: liveness only — the rank enters
                    # the alive set at the admission rendezvous, not by
                    # heartbeating at the side channel
                    self._join_beat[r] = _time.monotonic()
                else:
                    self._last_beat[r] = _time.monotonic()
                if msg.get('step') is not None:
                    self._steps[r] = int(msg['step'])
                if msg.get('telem') is not None:
                    self._telem[r] = {'snap': msg['telem'],
                                      'mono': _time.monotonic(),
                                      'time': _time.time()}
            elif op == 'leave':
                self._left.add(r)
            elif op == 'join':
                # JOIN announcement (scale-up): the rank stays PENDING
                # — surfaced under view['joining'] so every survivor's
                # controller quiesces at its next step boundary — and
                # only the admission rendezvous promotes it into the
                # alive set. Stale records of a previous incarnation
                # (LEFT on preemption, LOST on SIGKILL) are discarded
                # so the rejoiner is not instantly re-declared lost
                # off a months-old heartbeat timestamp.
                now = _time.monotonic()
                self._left.discard(r)
                self._last_beat.pop(r, None)
                self._steps.pop(r, None)
                if r not in self._joining:
                    self._joining[r] = now
                self._join_beat[r] = now
            elif op in ('barrier', 'barrier_poll'):
                # generation-counted rendezvous: a reused tag (kvstore's
                # fixed 'kvstore', repeated re-forms) must synchronize
                # EVERY time, so completion bumps the tag's generation
                # and clears the arrival set instead of leaving a
                # permanently-satisfied one behind. Arrivals carry a
                # per-call nonce so a RETRY whose original reply was
                # lost after the rendezvous completed is recognized
                # (replied done) instead of counting toward — and then
                # waiting forever on — the NEXT generation.
                tag = str(msg.get('tag', ''))
                nonce = msg.get('nonce')
                arrived = self._barriers.setdefault(tag, {})  # r -> nonce
                done = self._barrier_done.setdefault(tag, {})
                gen0 = self._barrier_gen.setdefault(tag, 0)
                if op == 'barrier':
                    prev = done.get(r)
                    if prev is not None and prev[0] == nonce:
                        gen0 = prev[1] - 1   # this call already completed
                    else:
                        arrived[r] = nonce
                view = self._view_locked()
                # the ADMISSION rendezvous (tag ADMIT_TAG) completes
                # only when the pending joiners have arrived TOO — and
                # completion is the generation-counted admission
                # point: every pending joiner is promoted into the
                # alive set atomically with the barrier bump, so the
                # completed reply's view already shows the larger
                # world to survivors and joiners alike.
                need = set(view['alive'])
                if tag == ADMIT_TAG:
                    need |= set(self._joining)
                if arrived and need <= set(arrived) | self._left:
                    self._barrier_gen[tag] = self._barrier_gen[tag] + 1
                    for rr, nn in arrived.items():
                        done[rr] = (nn, self._barrier_gen[tag])
                    arrived.clear()
                    if tag == ADMIT_TAG and self._joining:
                        nowm = _time.monotonic()
                        for rr in list(self._joining):
                            self._last_beat[rr] = nowm
                            self._left.discard(rr)
                        self._joining.clear()
                        self._join_beat.clear()
                        view = self._view_locked()
                view['barrier_gen'] = self._barrier_gen[tag]
                view['barrier_baseline'] = gen0
                view['barrier_done'] = self._barrier_gen[tag] > gen0
                return view
            elif op == 'remove':
                for x in msg.get('ranks', []):
                    self._left.add(int(x))
                    self._telem.pop(int(x), None)
                    # a pending JOIN from the removed rank is cancelled
                    # too (it can re-announce after the re-form)
                    self._joining.pop(int(x), None)
                    self._join_beat.pop(int(x), None)
            return self._view_locked()

    def _view_locked(self):
        now = _time.monotonic()
        if self._joining:
            # GC joiners that went silent again before admission — a
            # half-finished JOIN must not wedge future rendezvous
            for r in [r for r, t in self._join_beat.items()
                      if now - t > self.deadline_seconds]:
                self._joining.pop(r, None)
                self._join_beat.pop(r, None)
        ages = {str(r): round(now - t, 3)
                for r, t in self._last_beat.items() if r not in self._left}
        lost = sorted(int(r) for r, age in ages.items()
                      if age > self.deadline_seconds)
        alive = sorted(int(r) for r in ages if int(r) not in lost)
        view = {'world': len(alive), 'alive': alive, 'ages': ages,
                'lost': lost, 'left': sorted(self._left),
                'steps': {str(k): v for k, v in self._steps.items()}}
        if self._joining:
            view['joining'] = {str(r): round(now - t, 3)
                               for r, t in self._joining.items()}
        return view

    # -- sender (every rank) -----------------------------------------------

    def _beat_loop(self):
        from ..resilience import faults as _faults
        while not self._stop.wait(self.heartbeat_seconds):
            try:
                # the fault site: raise drops this beat (enough in a row
                # and the coordinator declares us lost), hang delays it
                _faults.fire('dist.heartbeat')
                self.beat()
            except MXNetError:
                pass    # _request already counted the send failure
            except Exception:
                with self._lock:
                    self.send_failures += 1

    def beat(self, step=None):
        """One heartbeat round-trip (the sender thread's body; callable
        directly from tests and training loops). Updates the cached
        membership view, attaches the fleet telemetry snapshot (when a
        provider is set) and feeds the clock-offset estimator."""
        if step is not None:
            self.current_step = int(step)
        if _telem['on']:
            from .. import telemetry as _telemetry
            _telemetry.inc('mxnet_tpu_elastic_heartbeats_total')
        msg = {'op': 'beat', 'rank': self.rank, 'step': self.current_step}
        provider = self.telemetry_provider
        if provider is not None:
            try:
                snap = provider()
            except Exception:
                _log.exception("membership: telemetry provider failed")
                snap = None
            if snap is not None:
                msg['telem'] = snap
        if self.is_coordinator:
            view = self._handle(msg)
            with self._lock:
                self._view = view
                self._last_ok = _time.monotonic()
            return view
        t0, m0 = _time.time(), _time.monotonic()
        view = self._request(msg)
        t1, m1 = _time.time(), _time.monotonic()
        self._note_offset(t0, t1, view.get('now'), rtt=m1 - m0)
        return view

    def _note_offset(self, t0, t1, coord_now, rtt=None):
        """One clock-offset sample from a beat round-trip: the
        coordinator stamped ``coord_now`` between our send (t0) and
        receive (t1), so offset = coord_now - midpoint with error
        bounded by rtt/2. The rtt MUST come from a monotonic pair: an
        NTP step between send and receive would otherwise fabricate a
        near-zero wall-clock rtt whose poisoned offset wins the
        min-RTT window for the next 64 beats."""
        if coord_now is None:
            return
        rtt = max(0.0, rtt if rtt is not None else t1 - t0)
        with self._lock:
            self._off_samples.append(
                (rtt, float(coord_now) - (t0 + t1) / 2.0, t1))

    def clock_offset(self):
        """(offset_seconds, rtt_seconds) such that ``local wall clock +
        offset ~= coordinator wall clock``, from the minimum-RTT beat in
        the recent sample window (error <= rtt/2) — what
        ``tools/stitch_traces.py`` shifts per-rank trace timestamps by.
        The coordinator is the reference clock: (0.0, 0.0). None before
        the first completed round-trip."""
        if self.is_coordinator:
            return (0.0, 0.0)
        with self._lock:
            if not self._off_samples:
                return None
            rtt, off, _when = min(self._off_samples)
        return (off, rtt)

    def fleet_snapshots(self):
        """{rank: {'snap', 'age_seconds', 'time'}} — the newest
        telemetry snapshot each rank piggybacked on a heartbeat.
        Coordinator-side state: snapshots are stored where beats are
        handled, so workers always see {} (read the merged fleet view
        from the coordinator's /healthz instead)."""
        now = _time.monotonic()
        with self._lock:
            return {int(r): {'snap': e['snap'],
                             'age_seconds': round(now - e['mono'], 3),
                             'time': e['time']}
                    for r, e in self._telem.items()}

    def _request(self, msg, timeout=None):
        timeout = timeout if timeout is not None else \
            max(1.0, self.heartbeat_seconds * 2)
        # snapshot the endpoint under the lock: retarget() (a re-form
        # pointing at the promoted coordinator) updates host+port as a
        # pair, and a beat racing it must not connect to the OLD host
        # with the NEW port
        with self._lock:
            host, port = self.coordinator_host, self.port
        try:
            with socket.create_connection(
                    (host, port), timeout=timeout) as conn:
                with conn.makefile('rwb') as f:
                    f.write(json.dumps(msg).encode() + b'\n')
                    f.flush()
                    line = f.readline()
            view = json.loads(line.decode())
        except (OSError, ValueError) as e:
            with self._lock:
                self.send_failures += 1
            raise MXNetError(
                f"membership: coordinator "
                f"{host}:{port} unreachable: "
                f"{e!r}") from e
        with self._lock:
            self._view = view
            self._last_ok = _time.monotonic()
        return view

    # -- queries -----------------------------------------------------------

    def view(self):
        """Latest membership view (coordinator: computed live; workers:
        the last beat's reply)."""
        if self.is_coordinator:
            with self._lock:
                return self._view_locked()
        with self._lock:
            return dict(self._view) if self._view else None

    def lost_peers(self):
        """Ranks declared lost. On a worker whose COORDINATOR has gone
        silent past the deadline, that is rank 0 — the worker-side half
        of the failure detector."""
        v = self.view()
        lost = list(v['lost']) if v else []
        if not self.is_coordinator:
            with self._lock:
                coord_age = _time.monotonic() - self._last_ok
            if coord_age > self.deadline_seconds and 0 not in lost:
                lost.append(0)
        return sorted(r for r in lost if r != self.rank)

    def peer_ages(self):
        """{rank: seconds-since-last-heartbeat} for the post-mortem
        verdict (watchdog report / flight dump). Finite values only —
        a retired coordinator (``remove_peers``) pins ``_last_ok`` to
        inf, which must not leak -inf ages into JSON dumps."""
        import math
        v = self.view()
        ages = {int(r): a for r, a in (v or {}).get('ages', {}).items()}
        if not self.is_coordinator:
            with self._lock:
                age = _time.monotonic() - self._last_ok
            if math.isfinite(age):
                ages[0] = round(age, 3)
        ages.pop(self.rank, None)
        return ages

    def alive(self):
        """Sorted live ranks (self included unless it left)."""
        v = self.view()
        if not v:
            return [self.rank]
        alive = set(v['alive'])
        if not self.is_coordinator:
            alive -= set(self.lost_peers())
            alive.add(self.rank)
        return sorted(alive)

    def world_size(self):
        return len(self.alive())

    # -- membership ops ----------------------------------------------------

    def leave(self):
        """Graceful goodbye (the SIGTERM/preemption path): peers see a
        departure, not a failure."""
        try:
            if self.is_coordinator:
                self._handle({'op': 'leave', 'rank': self.rank})
            else:
                self._request({'op': 'leave', 'rank': self.rank})
        except MXNetError:
            pass   # coordinator already gone — nothing to tell
        self._stop.set()

    def join(self):
        """Announce this rank as a JOIN candidate (a preempted rank
        coming back, or brand-new capacity granted by the provider).
        The coordinator marks it PENDING — surfaced in every view under
        ``joining`` so the survivors' controllers quiesce at their next
        step boundary — and the admission rendezvous
        (``barrier(ADMIT_TAG)``) promotes it into the alive set. The
        ``dist.join`` fault site drills failed/delayed announcements.
        Returns the coordinator's view."""
        from ..resilience import faults as _faults
        _faults.fire('dist.join')
        if _telem['on']:
            from .. import telemetry as _telemetry
            _telemetry.inc('mxnet_tpu_elastic_joins_total')
            from ..telemetry import flight as _flight
            _flight.note('elastic.join', rank=self.rank)
        msg = {'op': 'join', 'rank': self.rank}
        if self.is_coordinator:
            return self._handle(msg)
        return self._request(msg)

    def joining(self):
        """{rank: seconds-since-announcement} of JOIN candidates pending
        admission (coordinator: computed live; workers: from the last
        beat reply — at most one heartbeat stale)."""
        v = self.view()
        return {int(r): float(a)
                for r, a in (v or {}).get('joining', {}).items()}

    def remove_peers(self, ranks):
        """Retire lost peers from the tracked set (post re-form: the new
        world must not keep re-declaring the same loss)."""
        msg = {'op': 'remove', 'rank': self.rank,
               'ranks': [int(r) for r in ranks]}
        if self.is_coordinator:
            self._handle(msg)
        else:
            try:
                self._request(msg)
            except MXNetError:
                pass
        # worker-side: absorb into the local view too (the coordinator
        # itself may be among the removed) — pruning 'alive' and 'ages'
        # as well, so a stale coordinator-produced view cannot resurrect
        # a removed peer into the next survivor computation
        rs = set(int(r) for r in ranks)
        with self._lock:
            for r in rs:
                self._telem.pop(r, None)
            if self._view:
                self._view['lost'] = [r for r in self._view.get('lost', [])
                                      if int(r) not in rs]
                self._view['alive'] = [
                    r for r in self._view.get('alive', [])
                    if int(r) not in rs]
                self._view['world'] = len(self._view['alive'])
                for r in list(self._view.get('ages', {})):
                    if int(r) in rs:
                        self._view['ages'].pop(r)
            if 0 in rs:
                self._last_ok = float('inf')   # never re-declare rank 0

    def retarget(self, host=None, port=None):
        """Point this worker's sender at a NEW membership coordinator
        (after the old one died and the lowest surviving rank promoted
        itself via ``become_coordinator``). Without ``host`` the current
        one is kept — correct when the survivors share it (single-host
        drills); a multi-host deployment resolves the promoted rank's
        address via ``ElasticController(coordinator_host_fn=...)``."""
        with self._lock:
            if host is not None:
                self.coordinator_host = host
            if port is not None:
                self.port = int(port)
            self._last_ok = _time.monotonic()
        return self

    def become_coordinator(self):
        """Promote this rank to membership coordinator (lowest surviving
        rank after the old coordinator died). Starts the server thread
        on the same side-channel port, seeded with the current survivor
        set."""
        if self.is_coordinator:
            return self
        alive = self.alive()
        with self._lock:
            # lint: lockset-race-ok monotonic False->True promotion latch; a reader seeing the stale False for one beat retries against the dead coordinator once and self-corrects on the next round-trip
            self.is_coordinator = True
            now = _time.monotonic()
            self._last_beat = {r: now for r in alive}
            self._left = set()
            # pending JOINs announced to the dead coordinator are gone
            # with it — joiners re-announce against the promoted one
            self._joining = {}
            self._join_beat = {}
            self._last_ok = now
        self.start()
        # fleet observability followed the OLD coordinator: if this
        # rank was reporting snapshots, the promotion must also make it
        # the merge point — otherwise worker snapshots arriving here
        # are dropped and the degraded fleet goes dark exactly when it
        # most needs watching
        if self.telemetry_provider is not None:
            try:
                from ..telemetry import fleet as _fleet
                _fleet.attach(self)
            except Exception:
                _log.exception("fleet re-attach after promotion failed")
        return self

    def barrier(self, tag, timeout=None):
        """Membership-level rendezvous: block until every LIVE rank has
        arrived at ``tag`` (left/lost peers are not waited for — that is
        the point: a re-form barrier must not wait for the dead). Raises
        MXNetError on timeout."""
        from .. import config as _config
        from ..resilience import faults as _faults
        _faults.fire('dist.barrier')
        timeout = timeout if timeout is not None else \
            _config.get('MXTPU_BARRIER_TIMEOUT_SECONDS')
        deadline = _time.monotonic() + float(timeout)
        # arrive once; the reply's baseline is THIS rendezvous's
        # generation — poll until the coordinator bumps past it (the
        # bump clears the arrival set, so the same tag synchronizes
        # again next time instead of staying permanently satisfied).
        # Transient send failures retry within the deadline: a re-form
        # barrier often races the PROMOTED coordinator's server start,
        # and aborting on the first refused connection would kill a
        # survivor mid-recovery. The nonce makes a retried arrival
        # idempotent — a reply lost AFTER the rendezvous completed
        # must read back as done, not as a fresh arrival.
        with self._lock:
            self._barrier_calls += 1
            nonce = f'{self.rank}.{self._barrier_calls}'
        msg = {'op': 'barrier', 'rank': self.rank, 'tag': str(tag),
               'nonce': nonce}
        view, baseline = None, None
        while True:
            try:
                view = self._handle(msg) if self.is_coordinator \
                    else self._request(msg)
            except MXNetError:
                view = None
            if view is not None:
                if baseline is None and msg['op'] == 'barrier':
                    baseline = view.get('barrier_baseline', 0)
                    msg = {'op': 'barrier_poll', 'rank': self.rank,
                           'tag': str(tag)}
                if view.get('barrier_gen', 0) > (baseline or 0):
                    view['barrier_done'] = True
                    return view
            if _time.monotonic() > deadline:
                raise MXNetError(
                    f"membership barrier {tag!r} timed out after "
                    f"{timeout}s: arrived ranks missing from alive set "
                    f"{(view or {}).get('alive')}")
            _time.sleep(min(0.05, self.heartbeat_seconds / 4))


def membership():
    """The process-global Membership (None unless started)."""
    with _membership_lock:
        return _membership


def start_membership(coordinator=None, num_processes=None, process_id=None,
                     **kwargs):
    """Start (or return) the process-global membership layer. Called by
    ``init()`` under ``MXTPU_ELASTIC=1``; callable directly for custom
    worlds (tests, drills)."""
    global _membership
    if _membership is not None:
        return _membership
    # the SAME resolution init() uses (one shared helper), so the
    # derived side-channel port cannot diverge between init()-started
    # and directly-started layers
    coordinator, num_processes, process_id = _resolve_world(
        coordinator, num_processes, process_id)
    host = coordinator.rsplit(':', 1)[0] if ':' in coordinator \
        else coordinator
    kwargs.setdefault('port', _elastic_port(coordinator))
    ms = Membership(process_id, num_processes,
                    coordinator_host=host, **kwargs)
    with _membership_lock:
        _membership = ms
    # fleet observability (ISSUE 13): heartbeats piggyback telemetry
    # snapshots, the coordinator merges them, and the per-process
    # /metrics//healthz//flight endpoint arms iff MXTPU_METRICS_PORT
    # is set. Never fatal — observability must not take down training.
    try:
        from ..telemetry import fleet as _fleet, server as _tserver
        _fleet.attach(_membership)
        _tserver.maybe_start(rank=_membership.rank,
                             membership=_membership)
    except Exception:
        _log.exception("fleet observability bring-up failed")
    return _membership


def stop_membership():
    global _membership
    with _membership_lock:
        ms, _membership = _membership, None
    if ms is not None:
        ms.stop()


def barrier(tag='barrier', timeout=None):
    """Module-level membership barrier (no-op without a membership —
    single-process jobs have nobody to rendezvous with, but the fault
    site still fires so drills stay deterministic)."""
    if _membership is None:
        from ..resilience import faults as _faults
        _faults.fire('dist.barrier')
        return None
    return _membership.barrier(tag, timeout=timeout)


# ---------------------------------------------------------------------------
# checkpoint replica transport (ISSUE 10)
# ---------------------------------------------------------------------------
#
# Chunked file transfer on the SAME lightweight TCP side-channel design
# as the membership layer — deliberately never the ICI collectives,
# which are exactly what a dead peer wedges. One request per
# connection: a JSON header line, then (file_put) exactly `size` raw
# bytes, then a JSON reply line (file_get replies stream `size` raw
# bytes after the header). The receiver stages every file of a step
# into a ``step_*.tmp-<pid>`` dir and makes it visible only through
# ``replica_commit``'s single os.replace — the same commit protocol as
# a local checkpoint write, so a kill -9 at ANY point mid-transfer
# leaves no partial replica visible.

_REPLICA_CHUNK = 1 << 20          # 1 MiB transfer chunks
_NS_RE = re.compile(r'^[A-Za-z0-9][A-Za-z0-9_.\-]*$')


def _replica_timeout(timeout=None):
    from .. import config as _config
    return float(timeout) if timeout is not None \
        else float(_config.get('MXTPU_REPLICA_TIMEOUT_SECONDS'))


def replica_port(rank, coordinator=None):
    """Replica-server port of ``rank``: MXTPU_REPLICA_PORT_BASE + rank,
    defaulting the base to the elastic side-channel port + 100 (keeps
    parallel jobs on one host from colliding, same scheme as
    ``_elastic_port``)."""
    from .. import config as _config
    base = int(_config.get('MXTPU_REPLICA_PORT_BASE') or 0)
    if not base:
        base = _elastic_port(coordinator) + 100
    return base + int(rank)


def _safe_rel(rel):
    rel = str(rel)
    if not rel or rel.startswith(('/', '\\')) or '..' in rel.split('/') \
            or '\\' in rel:
        raise MXNetError(f"replica transport: unsafe relative path {rel!r}")
    return rel


def _safe_ns(ns):
    ns = str(ns)
    if not _NS_RE.match(ns):
        raise MXNetError(f"replica transport: bad namespace {ns!r}")
    return ns


def _recv_exact(f, n, chunk=_REPLICA_CHUNK):
    out = bytearray()
    while len(out) < n:
        b = f.read(min(chunk, n - len(out)))
        if not b:
            raise OSError(f"replica transport: connection closed after "
                          f"{len(out)}/{n} bytes")
        out += b
    return bytes(out)


class ReplicaServer:
    """Per-rank checkpoint replica endpoint.

    Stores replicas pushed by PEER ranks under
    ``<root>/<ns>/step_*`` (``ns`` names the owner, e.g. ``rank0``) and
    serves reads of both those hosted replicas and — when ``local_dir``
    is given — this host's OWN committed checkpoints (``ns='local'``),
    so a survivor can restore a dead owner's state from any live host.

    Ops (one JSON header line per connection):

    - ``file_put``  {ns, step, rel, size, sha256} + raw bytes: stage one
      payload file into the step's uncommitted tmp dir (hash-verified
      on receipt).
    - ``replica_commit`` {ns, step}: validate the staged dir against its
      manifest and publish it with one os.replace.
    - ``file_get``  {ns, step, rel}: stream one file back.
    - ``replica_inventory`` [{ns}]: committed hosted steps per namespace
      plus the owner's own local committed steps.
    - ``replica_delete`` {ns, step}: retire a hosted replica (retention
      GC from the owner) — counted in
      ``mxnet_tpu_checkpoint_replica_gc_total``.
    """

    def __init__(self, root, local_dir=None, port=0, start=True):
        self.root = os.path.abspath(root)
        self.local_dir = local_dir
        os.makedirs(self.root, exist_ok=True)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._server = None
        self._threads = []
        self.port = int(port)
        self.gc_total = 0
        self._sweep_stale()
        if start:
            self.start()

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        if self._server is not None:
            return self
        self._stop.clear()
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(('', self.port))
        self.port = srv.getsockname()[1]
        srv.listen(16)
        srv.settimeout(0.2)
        self._server = srv
        t = threading.Thread(target=self._serve, daemon=True,
                             name='mxtpu-replica-server')
        t.start()
        self._threads.append(t)
        return self

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads = []
        # retire the socket under the lock (same discipline as
        # Membership.stop): an accept loop that outlived its join
        # timeout must read the live-socket-or-None pair, never a torn
        # in-between
        with self._lock:
            srv, self._server = self._server, None
        if srv is not None:
            try:
                srv.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def _sweep_stale(self):
        """Sweep staging leftovers of a killed predecessor: nothing is
        in flight when a fresh server starts, so every ``*.tmp-*`` under
        every namespace is a dead write."""
        from ..checkpoint import manifest as mf
        try:
            namespaces = os.listdir(self.root)
        except OSError:
            return
        for ns in namespaces:
            nsdir = os.path.join(self.root, ns)
            if not os.path.isdir(nsdir):
                continue
            for tmp in mf.stale_tmp_dirs(nsdir):
                shutil.rmtree(tmp, ignore_errors=True)

    # -- server loop -------------------------------------------------------

    def _serve(self):
        with self._lock:
            srv = self._server
        while srv is not None and not self._stop.is_set():
            try:
                conn, _addr = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            # one thread per connection: a bandwidth-paced multi-MB put
            # must not block inventory/fetch ops from other peers
            t = threading.Thread(target=self._handle_conn, args=(conn,),
                                 daemon=True, name='mxtpu-replica-conn')
            t.start()

    def _handle_conn(self, conn):
        try:
            conn.settimeout(_replica_timeout())
            with conn, conn.makefile('rwb') as f:
                line = f.readline()
                if not line:
                    return
                try:
                    msg = json.loads(line.decode())
                    reply, payload = self._handle(msg, f)
                except MXNetError as e:
                    reply, payload = {'ok': 0, 'error': str(e)}, None
                except (OSError, ValueError, KeyError, TypeError) as e:
                    reply, payload = {'ok': 0, 'error': repr(e)}, None
                f.write(json.dumps(reply).encode() + b'\n')
                if payload is not None:
                    f.write(payload)
                f.flush()
        except (OSError, ValueError):
            pass

    def _ns_dir(self, ns, create=False):
        d = os.path.join(self.root, _safe_ns(ns))
        if create:
            os.makedirs(d, exist_ok=True)
        return d

    def _step_root(self, ns, step):
        """(namespace dir, final step dir) — ns 'local' reads this
        host's own checkpoint directory (read-only ops)."""
        from ..checkpoint import manifest as mf
        if ns == 'local':
            if self.local_dir is None:
                raise MXNetError("replica server: no local checkpoint "
                                 "dir attached (ns='local' unavailable)")
            base = self.local_dir
        else:
            base = self._ns_dir(ns)
        return base, os.path.join(base, mf.step_dir_name(int(step)))

    def _handle(self, msg, f):
        """Returns (reply dict, optional raw payload bytes)."""
        from ..checkpoint import manifest as mf
        op = msg.get('op')
        if op == 'file_put':
            ns = _safe_ns(msg['ns'])
            if ns == 'local':
                raise MXNetError("replica server: refusing file_put into "
                                 "the local checkpoint dir")
            rel = _safe_rel(msg['rel'])
            size = int(msg['size'])
            data = _recv_exact(f, size)
            digest = mf.sha256_bytes(data)
            if digest != msg.get('sha256'):
                raise MXNetError(
                    f"replica file_put {ns}/{msg.get('step')}/{rel}: "
                    f"content hash mismatch in transfer "
                    f"({digest[:12]}... != "
                    f"{str(msg.get('sha256'))[:12]}...)")
            nsdir = self._ns_dir(ns, create=True)
            staging = os.path.join(
                nsdir, mf.step_dir_name(int(msg['step']))
                + f'.tmp-{os.getpid()}')
            path = os.path.join(staging, rel)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            mf.write_bytes_durable(path, data)
            return {'ok': 1, 'bytes': size}, None
        if op == 'replica_commit':
            ns = _safe_ns(msg['ns'])
            if ns == 'local':
                raise MXNetError("replica server: refusing commit into "
                                 "the local checkpoint dir")
            step = int(msg['step'])
            nsdir = self._ns_dir(ns, create=True)
            final = os.path.join(nsdir, mf.step_dir_name(step))
            staging = final + f'.tmp-{os.getpid()}'
            with self._lock:
                if not os.path.isdir(staging):
                    raise MXNetError(
                        f"replica commit {ns}/{step}: no staged files")
                try:
                    mf.validate_step_dir(staging)
                except mf.CorruptCheckpointError as e:
                    shutil.rmtree(staging, ignore_errors=True)
                    raise MXNetError(
                        f"replica commit {ns}/{step} failed validation "
                        f"(staging discarded): {e}")
                if os.path.isdir(final):
                    old = final + f'.old-{os.getpid()}'
                    if os.path.isdir(old):
                        shutil.rmtree(old)
                    os.replace(final, old)
                    os.replace(staging, final)
                    shutil.rmtree(old, ignore_errors=True)
                else:
                    os.replace(staging, final)
                mf.fsync_dir(nsdir)
            return {'ok': 1, 'step': step}, None
        if op == 'file_get':
            ns = _safe_ns(msg['ns'])
            rel = _safe_rel(msg['rel'])
            _, stepdir = self._step_root(ns, msg['step'])
            path = os.path.join(stepdir, rel)
            try:
                with open(path, 'rb') as pf:
                    data = pf.read()
            except OSError as e:
                raise MXNetError(f"replica file_get "
                                 f"{ns}/{msg.get('step')}/{rel}: {e}")
            return {'ok': 1, 'size': len(data),
                    'sha256': mf.sha256_bytes(data)}, data
        if op == 'replica_inventory':
            want = msg.get('ns')
            hosted = {}
            try:
                namespaces = sorted(os.listdir(self.root))
            except OSError:
                namespaces = []
            for ns in namespaces:
                if not os.path.isdir(os.path.join(self.root, ns)):
                    continue
                if want and ns != want:
                    continue
                hosted[ns] = mf.committed_steps(
                    os.path.join(self.root, ns))
            local = mf.committed_steps(self.local_dir) \
                if self.local_dir else []
            return {'ok': 1, 'hosted': hosted, 'local': local}, None
        if op == 'replica_delete':
            ns = _safe_ns(msg['ns'])
            if ns == 'local':
                raise MXNetError("replica server: refusing delete in "
                                 "the local checkpoint dir")
            _, stepdir = self._step_root(ns, msg['step'])
            removed = 0
            with self._lock:
                if os.path.isdir(stepdir):
                    shutil.rmtree(stepdir, ignore_errors=True)
                    removed = 1
            if removed:
                # one handler thread per connection: the counter bump
                # must not lose updates between concurrent deletes
                with self._lock:
                    self.gc_total += 1
                if _telem['on']:
                    from .. import telemetry as _telemetry
                    _telemetry.inc(
                        'mxnet_tpu_checkpoint_replica_gc_total')
            return {'ok': 1, 'removed': removed}, None
        raise MXNetError(f"replica server: unknown op {op!r}")


def _replica_request(host, port, msg, payload=None, timeout=None,
                     bandwidth_mbps=None, recv_payload=False):
    """One replica-transport round-trip. ``payload`` bytes are streamed
    chunked after the header (paced to ``bandwidth_mbps`` when set);
    ``recv_payload`` reads the reply's ``size`` bytes after the reply
    header. Bounded by the socket timeout at every read/write — a dead
    peer costs one timeout, never a hang."""
    timeout = _replica_timeout(timeout)
    try:
        with socket.create_connection((host, int(port)),
                                      timeout=timeout) as conn:
            conn.settimeout(timeout)
            with conn.makefile('rwb') as f:
                f.write(json.dumps(msg).encode() + b'\n')
                if payload is not None:
                    pace = None
                    if bandwidth_mbps is None:
                        from .. import config as _config
                        bandwidth_mbps = _config.get(
                            'MXTPU_REPLICA_BANDWIDTH_MBPS')
                    if bandwidth_mbps and bandwidth_mbps > 0:
                        pace = 1.0 / (float(bandwidth_mbps) * 1e6)
                    view = memoryview(payload)
                    for off in range(0, len(view), _REPLICA_CHUNK):
                        t0 = _time.perf_counter()
                        chunk = view[off:off + _REPLICA_CHUNK]
                        f.write(chunk)
                        f.flush()
                        if pace:
                            budget = len(chunk) * pace
                            spent = _time.perf_counter() - t0
                            if budget > spent:
                                _time.sleep(budget - spent)
                f.flush()
                line = f.readline()
                if not line:
                    raise OSError("connection closed before reply")
                reply = json.loads(line.decode())
                data = None
                if recv_payload and reply.get('ok'):
                    data = _recv_exact(f, int(reply['size']))
    except (OSError, ValueError) as e:
        raise MXNetError(
            f"replica transport: {host}:{port} {msg.get('op')} failed: "
            f"{e!r}") from e
    if not reply.get('ok'):
        raise MXNetError(
            f"replica transport: {host}:{port} {msg.get('op')} "
            f"rejected: {reply.get('error')}")
    return (reply, data) if recv_payload else reply


def file_put(host, port, ns, step, rel, data, timeout=None,
             bandwidth_mbps=None):
    """Push one payload file of a committed step to a peer's replica
    server (staged — invisible until ``replica_commit``). Fault site
    ``dist.file_put``: raise fails the transfer, corrupt mangles the
    bytes in flight (the receiver's hash check rejects them), hang
    stalls into the socket timeout."""
    from ..resilience import faults as _faults
    kind = _faults.fire('dist.file_put')
    sent = bytes(data)
    if kind == 'corrupt':
        sent = _faults.corrupt_bytes(sent)
    from ..checkpoint import manifest as mf
    return _replica_request(
        host, port,
        {'op': 'file_put', 'ns': ns, 'step': int(step), 'rel': rel,
         'size': len(sent), 'sha256': mf.sha256_bytes(bytes(data))},
        payload=sent, timeout=timeout, bandwidth_mbps=bandwidth_mbps)


def file_get(host, port, ns, step, rel, timeout=None):
    """Fetch one file of a hosted replica (or, with ``ns='local'``, of
    the peer's own committed checkpoint). Returns the raw bytes after
    verifying the transfer hash."""
    from ..checkpoint import manifest as mf
    reply, data = _replica_request(
        host, port,
        {'op': 'file_get', 'ns': ns, 'step': int(step), 'rel': rel},
        timeout=timeout, recv_payload=True)
    if mf.sha256_bytes(data) != reply.get('sha256'):
        raise MXNetError(
            f"replica transport: {ns}/{step}/{rel} from {host}:{port} "
            f"corrupted in transfer (hash mismatch)")
    return data


def replica_commit(host, port, ns, step, timeout=None):
    """Publish a fully staged replica step with one os.replace on the
    receiver (validated against its manifest first)."""
    return _replica_request(
        host, port, {'op': 'replica_commit', 'ns': ns, 'step': int(step)},
        timeout=timeout)


def replica_inventory(host, port, ns=None, timeout=None):
    """{'hosted': {ns: [steps]}, 'local': [steps]} of a peer's replica
    server — the restore-fallback / orphan-GC survey op."""
    msg = {'op': 'replica_inventory'}
    if ns is not None:
        msg['ns'] = ns
    return _replica_request(host, port, msg, timeout=timeout)


def replica_delete(host, port, ns, step, timeout=None):
    """Retire one hosted replica step on a peer (retention GC)."""
    return _replica_request(
        host, port, {'op': 'replica_delete', 'ns': ns, 'step': int(step)},
        timeout=timeout)


def launch_local(script, n=2, env=None, coordinator='localhost:29500',
                 raw_command=False):
    """Spawn n local worker processes (the `--launcher local` analog of
    tools/launch.py; the CLI launcher delegates here so the coordinator env
    protocol lives in one place). Returns their exit codes.

    raw_command=True runs `script` verbatim; otherwise it is a python
    script argv run under the current interpreter."""
    procs = []
    cmd = list(script) if raw_command else [sys.executable] + list(script)
    for i in range(n):
        e = dict(os.environ)
        e.update(env or {})
        e['MXNET_TPU_COORDINATOR'] = coordinator
        e['MXNET_TPU_NUM_PROCS'] = str(n)
        e['MXNET_TPU_PROC_ID'] = str(i)
        procs.append(subprocess.Popen(cmd, env=e))
    return [p.wait() for p in procs]
