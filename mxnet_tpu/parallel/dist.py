"""Multi-process distributed init + launcher.

Ref: tools/launch.py + dmlc tracker (scheduler/server/worker env bootstrap
via DMLC_ROLE / DMLC_PS_ROOT_URI). TPU-native: `jax.distributed.initialize`
replaces the tracker; there are no server processes — every process is a
symmetric worker and collectives ride ICI/DCN.

Env protocol (launch-compatible shape):
  MXNET_TPU_COORDINATOR  host:port of process 0
  MXNET_TPU_NUM_PROCS    total processes
  MXNET_TPU_PROC_ID      this process's rank
(Also accepts the DMLC_* names for drop-in use of reference launch scripts.)
"""
from __future__ import annotations

import os
import subprocess
import sys

import jax


_initialized = False


def init(coordinator=None, num_processes=None, process_id=None,
         local_device_ids=None):
    """Initialize jax.distributed from args or env."""
    global _initialized
    if _initialized:
        return
    coordinator = coordinator or os.environ.get(
        'MXNET_TPU_COORDINATOR',
        _dmlc_coordinator())
    num_processes = num_processes or int(os.environ.get(
        'MXNET_TPU_NUM_PROCS', os.environ.get('DMLC_NUM_WORKER', '1')))
    process_id = process_id if process_id is not None else int(os.environ.get(
        'MXNET_TPU_PROC_ID', os.environ.get('DMLC_WORKER_ID', '0')))
    if num_processes <= 1:
        _initialized = True
        return
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id,
                               local_device_ids=local_device_ids)
    _initialized = True


def _dmlc_coordinator():
    uri = os.environ.get('DMLC_PS_ROOT_URI')
    port = os.environ.get('DMLC_PS_ROOT_PORT', '9000')
    if uri:
        return f"{uri}:{port}"
    return 'localhost:12345'


def rank():
    return jax.process_index()


def num_workers():
    return jax.process_count()


def launch_local(script, n=2, env=None, coordinator='localhost:29500',
                 raw_command=False):
    """Spawn n local worker processes (the `--launcher local` analog of
    tools/launch.py; the CLI launcher delegates here so the coordinator env
    protocol lives in one place). Returns their exit codes.

    raw_command=True runs `script` verbatim; otherwise it is a python
    script argv run under the current interpreter."""
    procs = []
    cmd = list(script) if raw_command else [sys.executable] + list(script)
    for i in range(n):
        e = dict(os.environ)
        e.update(env or {})
        e['MXNET_TPU_COORDINATOR'] = coordinator
        e['MXNET_TPU_NUM_PROCS'] = str(n)
        e['MXNET_TPU_PROC_ID'] = str(i)
        procs.append(subprocess.Popen(cmd, env=e))
    return [p.wait() for p in procs]
