"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh
'pp' axis.

BEYOND the reference: MXNet's model parallelism is manual layer placement
(`Module(group2ctxs=...)`, src/operator/cross_device_copy.cc) with no
pipeline schedule (SURVEY §2.5 "no GPipe/1F1B anywhere"). Here pipeline
stages are a first-class mesh axis: every device holds ONE stage's
parameters (stacked leaves sharded over 'pp'), microbatches stream
through the ring with `lax.ppermute` on ICI neighbor links, and the whole
schedule — forward bubbles, steady state, drain — is a single `lax.scan`
inside `shard_map`, so XLA sees one static program and autodiff runs
straight through the collectives (GPipe: Huang et al. 2019; the ppermute
ring mirrors the ring-attention pattern in ring_attention.py).

Design notes (TPU-first):
- SPMD, not MPMD: all stages run the same `stage_fn`; heterogeneous
  models are expressed by stacking per-stage parameters (vmap-style),
  exactly how scan-over-layers works in JAX transformer stacks.
- The schedule runs S + M - 1 ticks for S stages / M microbatches.
  Devices idle in the bubble ticks compute garbage that is masked out —
  branchless, static shapes, no host control flow.
- Gradients: `jax.grad` differentiates through the scan + ppermute
  (transpose of ppermute is the reverse permute), yielding the standard
  GPipe backward schedule without writing it by hand.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
try:
    from jax import shard_map
except ImportError:    # jax < 0.6 ships it under experimental
    from jax.experimental.shard_map import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ['pipeline_forward', 'pipeline_loss_fn', 'stack_stage_params',
           'split_layers_into_stages', 'pipeline_composite_loss',
           'PipelineTrainStep']


def stack_stage_params(stage_param_list):
    """Stack a list of per-stage parameter pytrees (identical structure)
    into one pytree whose leaves gain a leading stage axis — shard that
    axis over 'pp' and each device holds exactly its stage's weights."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *stage_param_list)


def split_layers_into_stages(layer_params, n_stages):
    """Group a list of per-layer pytrees into n_stages stacked groups:
    [L0..L3] with 2 stages -> stage leaf shape (2, 2, ...) where
    leading axis is stage, second is layer-within-stage."""
    n = len(layer_params)
    assert n % n_stages == 0, (n, n_stages)
    per = n // n_stages
    stages = []
    for s in range(n_stages):
        stages.append(jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs, axis=0),
            *layer_params[s * per:(s + 1) * per]))
    return stack_stage_params(stages)


def pipeline_forward(stage_fn, stage_params, x_microbatches, mesh,
                     pp_axis='pp'):
    """Run microbatches through the stage pipeline.

    stage_fn(params_one_stage, x) -> y: one stage's computation; applied
    by every device to its resident stage. With grouped layers, make
    stage_fn itself a lax.scan over the layer axis.
    stage_params: pytree with leading stage axis (see stack_stage_params),
    sharded over pp_axis.
    x_microbatches: (M, mb, ...) microbatches, replicated.
    Returns (M, mb, ...) outputs of the LAST stage (replicated — each
    bubble tick's garbage is dropped on the floor and outputs psum-
    broadcast from the last stage).
    """
    S = mesh.shape[pp_axis]
    M = x_microbatches.shape[0]
    n_ticks = S + M - 1

    def spmd(params, xs):
        # params: this device's stage (leading axis stripped by shard_map
        # to size 1) — drop it
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        stage = lax.axis_index(pp_axis)
        fwd_perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            held, outs = carry
            # stage 0 injects microbatch t (clamped; bubble ticks recompute
            # an already-sent microbatch and the result is masked later)
            inject = xs[jnp.clip(t, 0, M - 1)]
            cur = jnp.where(stage == 0, inject, held)
            y = stage_fn(params, cur)
            # last stage emits microbatch m = t - (S - 1) at tick t
            m = t - (S - 1)
            is_out = (stage == S - 1) & (m >= 0)
            outs = lax.cond(
                m >= 0,
                lambda o: o.at[jnp.clip(m, 0, M - 1)].set(
                    jnp.where(is_out, y, o[jnp.clip(m, 0, M - 1)])),
                lambda o: o,
                outs)
            # rotate activations one stage forward
            held = lax.ppermute(y, pp_axis, fwd_perm)
            return (held, outs), None

        held0 = jnp.zeros_like(stage_fn(params, xs[0]))
        outs0 = jnp.zeros((M,) + held0.shape, held0.dtype)
        (_, outs), _ = lax.scan(tick, (held0, outs0),
                                jnp.arange(n_ticks))
        # broadcast the last stage's collected outputs to all devices
        # (psum works because every other stage contributes zeros)
        outs = jnp.where(stage == S - 1, outs, jnp.zeros_like(outs))
        return lax.psum(outs, pp_axis)

    pp_spec = P(pp_axis)
    in_specs = (jax.tree_util.tree_map(lambda _: pp_spec, stage_params),
                P())
    try:
        mapped = shard_map(spmd, mesh=mesh, in_specs=in_specs,
                           out_specs=P(), check_vma=False)
    except TypeError:   # jax < 0.7 spells the unchecked mode check_rep
        mapped = shard_map(spmd, mesh=mesh, in_specs=in_specs,
                           out_specs=P(), check_rep=False)
    return mapped(stage_params, x_microbatches)


def pipeline_loss_fn(stage_fn, loss_fn, mesh, pp_axis='pp'):
    """Build loss(stage_params, x_microbatches, y_microbatches) -> scalar
    running the pipeline forward and averaging per-microbatch losses.
    Differentiable: jax.grad through the scan/ppermute yields the GPipe
    backward schedule."""

    def loss(stage_params, x_mb, y_mb):
        out = pipeline_forward(stage_fn, stage_params, x_mb, mesh,
                               pp_axis=pp_axis)
        return jnp.mean(jax.vmap(loss_fn)(out, y_mb))

    return loss


# ---------------------------------------------------------------------------
# Heterogeneous models (VERDICT r4 #6): real networks are not a uniform
# layer stack — BERT is embedding → N identical encoder layers → task
# head. The pipeline axis carries the encoder (where the FLOPs are);
# embedding and head run replicated on every device outside the scan.
# That is the standard TPU GPipe layout: embed/head are O(vocab·C) per
# microbatch — negligible next to the encoder — and replicating them
# avoids both pipeline bubbles for tiny stages and pytree-heterogeneity
# inside the scan carry.
# ---------------------------------------------------------------------------

def pipeline_composite_loss(embed_fn, stage_fn, head_fn, loss_fn, mesh,
                            pp_axis='pp'):
    """loss(params, x_mb, y_mb) -> scalar for an embed→stages→head model.

    params: {'embed': pytree, 'stages': stacked pytree (leading stage
    axis, shard over pp), 'head': pytree}.
    embed_fn(embed_params, x) -> h; stage_fn(one_stage_params, h) -> h;
    head_fn(head_params, h) -> outputs (any pytree); loss_fn(outputs, y)
    -> scalar. x_mb / y_mb are pytrees with a leading (M, mb) microbatch
    axis on every leaf.
    """
    def loss(params, x_mb, y_mb):
        h = jax.vmap(lambda x: embed_fn(params['embed'], x))(x_mb)
        out = pipeline_forward(stage_fn, params['stages'], h, mesh,
                               pp_axis=pp_axis)
        per_mb = jax.vmap(
            lambda o, y: loss_fn(head_fn(params['head'], o), y))(out, y_mb)
        return jnp.mean(per_mb)

    return loss


class PipelineTrainStep:
    """Compiled fwd+bwd+update training step over a 'pp' mesh axis — the
    public pipeline entry point (beyond reference: SURVEY §2.5 lists no
    pipeline schedule; the reference's model parallelism is manual
    placement, python/mxnet/module/module.py group2ctxs).

    Usage:
        step = PipelineTrainStep(params, embed_fn, stage_fn, head_fn,
                                 loss_fn, 'adamw', {'learning_rate': 1e-3},
                                 mesh=mesh)
        loss = step(x_mb, y_mb)   # microbatched pytrees; params updated

    Stage parameters live sharded over pp (each device holds only its
    stage); embed/head replicate. The whole step is ONE jit program with
    donated param/opt-state buffers, mirroring ShardedTrainStep.
    """

    def __init__(self, params, embed_fn, stage_fn, head_fn, loss_fn,
                 optimizer='sgd', optimizer_params=None, mesh=None,
                 pp_axis='pp'):
        from .step import _OPTS
        from .mesh import default_mesh
        if optimizer not in _OPTS:
            raise ValueError(f"PipelineTrainStep supports {sorted(_OPTS)}")
        self.mesh = mesh if mesh is not None else default_mesh()
        self.pp_axis = pp_axis
        opts = dict(optimizer_params or {})
        self.lr = opts.pop('learning_rate', opts.pop('lr', 0.01))
        self._opt_kwargs = opts
        self._opt_init, self._opt_update = _OPTS[optimizer]
        self._loss = pipeline_composite_loss(embed_fn, stage_fn, head_fn,
                                             loss_fn, self.mesh, pp_axis)

        pp_spec = P(pp_axis)
        self._specs = {
            'embed': jax.tree_util.tree_map(lambda _: P(), params['embed']),
            'stages': jax.tree_util.tree_map(lambda _: pp_spec,
                                             params['stages']),
            'head': jax.tree_util.tree_map(lambda _: P(), params['head']),
        }
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), self._specs,
            is_leaf=lambda x: isinstance(x, P))
        # copy=True: the step donates these buffers, and callers keep
        # using the source params (often live Gluon model weights)
        self._params = jax.tree_util.tree_map(
            lambda p, s: jax.device_put(jnp.array(p, copy=True), s),
            params, shardings)
        self._opt_state = jax.tree_util.tree_map(self._opt_init,
                                                 self._params)

        opt_kwargs = dict(self._opt_kwargs)
        lr = self.lr

        def step(ps, opt_state, x_mb, y_mb):
            loss, grads = jax.value_and_grad(self._loss)(ps, x_mb, y_mb)
            new_p = {}
            new_s = {}
            for group in ps:
                flat_p, treedef = jax.tree_util.tree_flatten(ps[group])
                flat_g = jax.tree_util.tree_leaves(grads[group])
                flat_s = treedef.flatten_up_to(opt_state[group])
                ups = [self._opt_update(p, g, s, lr, **opt_kwargs)
                       for p, g, s in zip(flat_p, flat_g, flat_s)]
                new_p[group] = jax.tree_util.tree_unflatten(
                    treedef, [u[0] for u in ups])
                new_s[group] = jax.tree_util.tree_unflatten(
                    treedef, [u[1] for u in ups])
            return loss, new_p, new_s

        self._compiled = jax.jit(step, donate_argnums=(0, 1))

    @property
    def params(self):
        return self._params

    def __call__(self, x_mb, y_mb):
        to_j = lambda a: a._data if hasattr(a, '_data') else jnp.asarray(a)
        x_mb = jax.tree_util.tree_map(to_j, x_mb)
        y_mb = jax.tree_util.tree_map(to_j, y_mb)
        loss, self._params, self._opt_state = self._compiled(
            self._params, self._opt_state, x_mb, y_mb)
        return loss
