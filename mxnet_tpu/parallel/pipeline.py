"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh
'pp' axis.

BEYOND the reference: MXNet's model parallelism is manual layer placement
(`Module(group2ctxs=...)`, src/operator/cross_device_copy.cc) with no
pipeline schedule (SURVEY §2.5 "no GPipe/1F1B anywhere"). Here pipeline
stages are a first-class mesh axis: every device holds ONE stage's
parameters (stacked leaves sharded over 'pp'), microbatches stream
through the ring with `lax.ppermute` on ICI neighbor links, and the whole
schedule — forward bubbles, steady state, drain — is a single `lax.scan`
inside `shard_map`, so XLA sees one static program and autodiff runs
straight through the collectives (GPipe: Huang et al. 2019; the ppermute
ring mirrors the ring-attention pattern in ring_attention.py).

Design notes (TPU-first):
- SPMD, not MPMD: all stages run the same `stage_fn`; heterogeneous
  models are expressed by stacking per-stage parameters (vmap-style),
  exactly how scan-over-layers works in JAX transformer stacks.
- The schedule runs S + M - 1 ticks for S stages / M microbatches.
  Devices idle in the bubble ticks compute garbage that is masked out —
  branchless, static shapes, no host control flow.
- Gradients: `jax.grad` differentiates through the scan + ppermute
  (transpose of ppermute is the reverse permute), yielding the standard
  GPipe backward schedule without writing it by hand.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ['pipeline_forward', 'pipeline_loss_fn', 'stack_stage_params',
           'split_layers_into_stages']


def stack_stage_params(stage_param_list):
    """Stack a list of per-stage parameter pytrees (identical structure)
    into one pytree whose leaves gain a leading stage axis — shard that
    axis over 'pp' and each device holds exactly its stage's weights."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *stage_param_list)


def split_layers_into_stages(layer_params, n_stages):
    """Group a list of per-layer pytrees into n_stages stacked groups:
    [L0..L3] with 2 stages -> stage leaf shape (2, 2, ...) where
    leading axis is stage, second is layer-within-stage."""
    n = len(layer_params)
    assert n % n_stages == 0, (n, n_stages)
    per = n // n_stages
    stages = []
    for s in range(n_stages):
        stages.append(jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs, axis=0),
            *layer_params[s * per:(s + 1) * per]))
    return stack_stage_params(stages)


def pipeline_forward(stage_fn, stage_params, x_microbatches, mesh,
                     pp_axis='pp'):
    """Run microbatches through the stage pipeline.

    stage_fn(params_one_stage, x) -> y: one stage's computation; applied
    by every device to its resident stage. With grouped layers, make
    stage_fn itself a lax.scan over the layer axis.
    stage_params: pytree with leading stage axis (see stack_stage_params),
    sharded over pp_axis.
    x_microbatches: (M, mb, ...) microbatches, replicated.
    Returns (M, mb, ...) outputs of the LAST stage (replicated — each
    bubble tick's garbage is dropped on the floor and outputs psum-
    broadcast from the last stage).
    """
    S = mesh.shape[pp_axis]
    M = x_microbatches.shape[0]
    n_ticks = S + M - 1

    def spmd(params, xs):
        # params: this device's stage (leading axis stripped by shard_map
        # to size 1) — drop it
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        stage = lax.axis_index(pp_axis)
        fwd_perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            held, outs = carry
            # stage 0 injects microbatch t (clamped; bubble ticks recompute
            # an already-sent microbatch and the result is masked later)
            inject = xs[jnp.clip(t, 0, M - 1)]
            cur = jnp.where(stage == 0, inject, held)
            y = stage_fn(params, cur)
            # last stage emits microbatch m = t - (S - 1) at tick t
            m = t - (S - 1)
            is_out = (stage == S - 1) & (m >= 0)
            outs = lax.cond(
                m >= 0,
                lambda o: o.at[jnp.clip(m, 0, M - 1)].set(
                    jnp.where(is_out, y, o[jnp.clip(m, 0, M - 1)])),
                lambda o: o,
                outs)
            # rotate activations one stage forward
            held = lax.ppermute(y, pp_axis, fwd_perm)
            return (held, outs), None

        held0 = jnp.zeros_like(stage_fn(params, xs[0]))
        outs0 = jnp.zeros((M,) + held0.shape, held0.dtype)
        (_, outs), _ = lax.scan(tick, (held0, outs0),
                                jnp.arange(n_ticks))
        # broadcast the last stage's collected outputs to all devices
        # (psum works because every other stage contributes zeros)
        outs = jnp.where(stage == S - 1, outs, jnp.zeros_like(outs))
        return lax.psum(outs, pp_axis)

    pp_spec = P(pp_axis)
    return shard_map(
        spmd, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: pp_spec, stage_params),
                  P()),
        out_specs=P(),
        check_vma=False,
    )(stage_params, x_microbatches)


def pipeline_loss_fn(stage_fn, loss_fn, mesh, pp_axis='pp'):
    """Build loss(stage_params, x_microbatches, y_microbatches) -> scalar
    running the pipeline forward and averaging per-microbatch losses.
    Differentiable: jax.grad through the scan/ppermute yields the GPipe
    backward schedule."""

    def loss(stage_params, x_mb, y_mb):
        out = pipeline_forward(stage_fn, stage_params, x_mb, mesh,
                               pp_axis=pp_axis)
        return jnp.mean(jax.vmap(loss_fn)(out, y_mb))

    return loss
