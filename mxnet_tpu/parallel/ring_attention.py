"""Ring attention: sequence/context parallelism over the mesh.

Absent in the reference (SURVEY §2.5 — long sequences were handled by
bucketing); first-class here. Q/K/V are sharded over a mesh 'sp' axis along
the sequence dimension; K/V blocks rotate around the ring via ppermute while
each device accumulates its queries' attention with online-softmax
(log-sum-exp) merging, so peak memory is O(T/sp * T/sp) per device and the
transfers ride ICI neighbor links.

Technique: blockwise/ring attention (Liu et al., "Ring Attention with
Blockwise Transformers"); implemented from scratch over lax collectives.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:    # jax < 0.6 ships it under experimental
    from jax.experimental.shard_map import shard_map

try:
    _pcast = lax.pcast
except AttributeError:
    # jax < 0.7 has no varying-axis type system: replicated constants are
    # accepted as scan carries directly, so the cast is the identity
    def _pcast(x, axis_name, to=None):
        return x


def _block_attn(q, k, v, scale, causal, q_offset, kv_offset, kmask=None,
                dropout_p=0.0, dropout_seed=None):
    """One block's contribution: returns (out_unnorm, row_max, row_sumexp).

    q: (B, H, Tq, D), k/v: (B, H, Tk, D). Offsets locate the blocks in the
    global sequence for causal masking. kmask: optional (B, Tk) additive
    f32 key mask for the CURRENT kv block (rotates with k/v).
    Attention dropout uses the same counter-based hash as the Pallas
    flash kernel (ops/pallas_attention.py _counter_keep) keyed on GLOBAL
    (head, q-pos, k-pos): the mask is a pure function of coordinates, so
    it is invariant to how the ring rotates the blocks and identical in
    forward and the transposed backward scan. The softmax normaliser l
    accumulates the UN-dropped p (dropout applies to the probabilities
    after normalisation, as in the dense path), so only the p·V product
    sees the keep mask.
    """
    scores = jnp.einsum('bhqd,bhkd->bhqk', q, k,
                        preferred_element_type=jnp.float32) * scale
    if kmask is not None:
        scores = scores + kmask[:, None, None, :]
    Tq, Tk = q.shape[2], k.shape[2]
    if causal:
        q_pos = q_offset + jnp.arange(Tq)
        k_pos = kv_offset + jnp.arange(Tk)
        mask = q_pos[:, None] >= k_pos[None, :]
        scores = jnp.where(mask, scores, -1e30)
    m = jnp.max(scores, axis=-1, keepdims=True)          # (B,H,Tq,1)
    p = jnp.exp(scores - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    pv = p
    if dropout_p > 0.0:
        from ..ops.pallas_attention import _counter_keep
        B, H = q.shape[0], q.shape[1]
        bh = (jnp.arange(B, dtype=jnp.uint32)[:, None] * jnp.uint32(H)
              + jnp.arange(H, dtype=jnp.uint32)[None, :])
        rows = (q_offset + jnp.arange(Tq)).astype(jnp.uint32)
        cols = (kv_offset + jnp.arange(Tk)).astype(jnp.uint32)
        keep = _counter_keep(dropout_seed.reshape(()),
                             bh[:, :, None, None],
                             rows[None, None, :, None],
                             cols[None, None, None, :], dropout_p)
        pv = p * keep
    out = jnp.einsum('bhqk,bhkd->bhqd', pv.astype(v.dtype), v)
    return out, m, l


def _merge(acc_out, acc_m, acc_l, out, m, l):
    """Online-softmax merge of two partial attention results."""
    new_m = jnp.maximum(acc_m, m)
    alpha = jnp.exp(acc_m - new_m)
    beta = jnp.exp(m - new_m)
    new_l = acc_l * alpha + l * beta
    new_out = acc_out * alpha.astype(acc_out.dtype) \
        + out * beta.astype(out.dtype)
    return new_out, new_m, new_l


def ring_attention(q, k, v, mesh: Mesh, sp_axis: str = 'sp', causal=False,
                   scale=None, key_mask=None, dropout_p=0.0,
                   dropout_seed=None):
    """Sequence-parallel attention.

    q/k/v: (B, H, T, D) jax arrays (global logical shapes); T must divide
    by the sp axis size. key_mask: optional (B, T) mask over keys —
    boolean (True = keep) or additive f32 (0 keep / large-negative drop);
    it is sharded along the sequence axis and rotates around the ring
    with its K/V block. dropout_p > 0 applies in-kernel counter-based
    attention dropout; dropout_seed is a uint32 array (any shape, one
    element used). Returns (B, H, T, D) with the same sharding.
    """
    B, H, T, D = q.shape
    n = mesh.shape[sp_axis]
    assert T % n == 0, f"seq len {T} not divisible by sp={n}"
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    Tl = T // n

    spec = P(None, None, sp_axis, None)
    mspec = P(None, sp_axis)
    if key_mask is not None:
        # framework-wide convention: boolean/INTEGER masks are keep/drop
        # (truthy = keep); only floating masks are additive
        if not jnp.issubdtype(key_mask.dtype, jnp.floating):
            key_mask = jnp.where(key_mask.astype(jnp.bool_), 0.0, -1e30)
        key_mask = key_mask.astype(jnp.float32)
    if dropout_p > 0.0:
        if dropout_seed is None:
            raise ValueError("ring_attention: dropout_p > 0 requires "
                             "dropout_seed")
        dropout_seed = jnp.asarray(dropout_seed, jnp.uint32).reshape(-1)[:1]

    def local_fn(q_blk, k_blk, v_blk, m_blk, seed_blk=None):
        idx = lax.axis_index(sp_axis)
        q_off = idx * Tl

        acc_out = jnp.zeros(q_blk.shape, jnp.float32)
        # -1e30 (not -inf): the first merge computes exp(acc_m - new_m),
        # and inf - inf poisons reverse-mode AD with NaN cotangents
        acc_m = jnp.full(q_blk.shape[:3] + (1,), -1e30, jnp.float32)
        acc_l = jnp.zeros(q_blk.shape[:3] + (1,), jnp.float32)
        # initial accumulators are constants; mark them as varying over the
        # ring axis so the scan carry type matches the per-shard outputs
        acc_out, acc_m, acc_l = _pcast((acc_out, acc_m, acc_l), sp_axis,
                                       to='varying')

        perm = [(i, (i + 1) % n) for i in range(n)]

        def body(carry, i):
            # lax.scan (not fori_loop): the ring loop must be
            # reverse-differentiable — jax transposes the ppermute into
            # the counter-rotating ring of the backward pass
            acc_out, acc_m, acc_l, k_cur, v_cur, m_cur = carry
            # block currently held came from device (idx - i) mod n
            kv_off = ((idx - i) % n) * Tl
            out, m, l = _block_attn(q_blk, k_cur, v_cur, scale, causal,
                                    q_off, kv_off, m_cur,
                                    dropout_p=dropout_p,
                                    dropout_seed=seed_blk)
            acc_out, acc_m, acc_l = _merge(acc_out, acc_m, acc_l,
                                           out.astype(jnp.float32), m, l)
            # rotate K/V (+ their key-mask slice) around the ring
            k_next = lax.ppermute(k_cur, sp_axis, perm)
            v_next = lax.ppermute(v_cur, sp_axis, perm)
            m_next = None if m_cur is None else \
                lax.ppermute(m_cur, sp_axis, perm)
            return (acc_out, acc_m, acc_l, k_next, v_next, m_next), None

        (acc_out, acc_m, acc_l, _, _, _), _ = lax.scan(
            body, (acc_out, acc_m, acc_l, k_blk, v_blk, m_blk),
            jnp.arange(n))
        return (acc_out / jnp.maximum(acc_l, 1e-30)).astype(q_blk.dtype)

    # seed is replicated (every device regenerates the same global mask
    # from coordinates); P() marks it unsharded
    if dropout_p > 0.0:
        if key_mask is None:
            def local_nomask_seed(q_blk, k_blk, v_blk, seed_blk):
                return local_fn(q_blk, k_blk, v_blk, None, seed_blk)
            return shard_map(local_nomask_seed, mesh=mesh,
                             in_specs=(spec, spec, spec, P(None)),
                             out_specs=spec)(q, k, v, dropout_seed)
        return shard_map(local_fn, mesh=mesh,
                         in_specs=(spec, spec, spec, mspec, P(None)),
                         out_specs=spec)(q, k, v, key_mask, dropout_seed)
    if key_mask is None:
        def local_nomask(q_blk, k_blk, v_blk):
            return local_fn(q_blk, k_blk, v_blk, None)
        return shard_map(local_nomask, mesh=mesh,
                         in_specs=(spec, spec, spec),
                         out_specs=spec)(q, k, v)
    return shard_map(local_fn, mesh=mesh,
                     in_specs=(spec, spec, spec, mspec),
                     out_specs=spec)(q, k, v, key_mask)
