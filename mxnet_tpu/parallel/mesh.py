"""Device-mesh helpers.

The mesh is the TPU analog of the reference's device topology awareness
(ref: src/kvstore/gpu_topology.h builds reduction trees from PCIe links;
here ICI topology is expressed as mesh axes and XLA routes collectives).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as onp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_default_mesh: Optional[Mesh] = None


def make_mesh(axis_shapes: Sequence[int] = None,
              axis_names: Sequence[str] = ('dp',),
              devices=None) -> Mesh:
    """Create a Mesh. axis_shapes=None uses all devices on one 'dp' axis."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if axis_shapes is None:
        axis_shapes = (n,)
    total = 1
    for s in axis_shapes:
        total *= s
    if total > n:
        raise ValueError(f"mesh {tuple(axis_shapes)} needs {total} devices, "
                         f"have {n}")
    dev_array = onp.array(devices[:total]).reshape(tuple(axis_shapes))
    return Mesh(dev_array, tuple(axis_names))


def default_mesh() -> Mesh:
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = make_mesh()
    return _default_mesh


def set_default_mesh(mesh: Mesh):
    global _default_mesh
    _default_mesh = mesh


def mesh_shape(mesh: Mesh = None):
    mesh = mesh or default_mesh()
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def data_parallel_spec(mesh: Mesh = None, axis: str = 'dp'):
    """PartitionSpec sharding the batch dim over the data axis."""
    return P(axis)


def replicate_spec():
    return P()
