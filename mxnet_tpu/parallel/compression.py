"""Error-feedback gradient compression codecs (ISSUE 12).

Pure-jnp quantize→dequantize pairs usable both INSIDE the compiled pjit
step (``parallel/step.py``'s reduce-scatter epilogue) and eagerly on the
kvstore push path (``kvstore/gradient_compression.py``). The codec
contract is the reference's 2-bit kvstore semantics
(src/kvstore/gradient_compression.h: quantize to {-t, 0, +t} with the
quantization error carried forward) generalized to three wire formats:

- ``fp16``  — truncate fp32 → fp16 (2 bytes/elem, 2x wire shrink);
- ``int8``  — per-block max-abs scale, round to [-127, 127]
  (1 byte/elem + one fp32 scale per block, ~3.9x);
- ``2bit``  — sign+threshold: quantize to {-t*s, 0, +t*s} where ``s``
  is the per-block max-abs scale (or 1.0 with ``block=0`` — the
  reference's absolute-threshold semantics) — 2 bits/elem + one fp32
  scale per block, ~15x.

Error feedback (Lin et al., Deep Gradient Compression; Karimireddy et
al., Error Feedback Fixes SignSGD) lives in the CALLERS: they compute
``dec = encode_decode(grad + residual)`` and carry
``residual = grad + residual - dec`` forward, so the quantization error
is re-offered next step instead of lost. This module is stateless.

NaN/Inf inputs PROPAGATE through every codec: a jnp comparison against
a NaN is False, so a naive threshold quantizer would silently map a
poisoned gradient to 0 and hide it from the non-finite guard — instead
``encode_decode`` re-injects non-finite inputs into the decoded output
so the guard (which reduces over the DECODED grads) still trips.

The collectives themselves are emitted by XLA from sharding
constraints, so the wire accounting is analytic (``wire_bytes``) — the
same methodology as the ``mxnet_tpu_comm_*`` ring accounting.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..base import MXNetError

CODECS = ('none', 'fp16', 'int8', '2bit')

#: analytic encoded payload size, bits per element (excluding per-block
#: scales — those are accounted separately by wire_bytes)
BITS_PER_ELEM = {'fp16': 16, 'int8': 8, '2bit': 2}


def resolve(compression_params, default_type=None):
    """Validate ``compression_params`` (a dict with ``type`` and
    optional ``threshold``/``block_size``) into a plain
    ``{'type', 'threshold', 'block'}`` spec, or None when compression
    is off. ``compression_params=None`` falls back to the
    ``MXTPU_COMPRESSION`` / ``MXTPU_COMPRESSION_THRESHOLD`` /
    ``MXTPU_COMPRESSION_BLOCK`` knobs (``default_type`` overrides the
    first). Unknown ctype strings raise an actionable MXNetError."""
    from .. import config as _config
    if compression_params is None:
        ctype = default_type if default_type is not None \
            else _config.get('MXTPU_COMPRESSION')
        if not ctype or ctype == 'none':
            return None
        compression_params = {'type': ctype}
    ctype = compression_params.get('type', '2bit')
    if ctype not in CODECS:
        raise MXNetError(
            f"gradient compression type {ctype!r} is not supported "
            f"(supported: {', '.join(repr(c) for c in CODECS)}). "
            f"'fp16' truncates to half precision, 'int8' rounds against "
            f"a per-block max-abs scale, '2bit' is the reference "
            f"kvstore's sign+threshold quantizer.")
    if ctype == 'none':
        return None
    threshold = float(compression_params.get(
        'threshold', _config.get('MXTPU_COMPRESSION_THRESHOLD')))
    block = int(compression_params.get(
        'block_size', _config.get('MXTPU_COMPRESSION_BLOCK')))
    if threshold <= 0:
        raise MXNetError(
            f"gradient compression threshold must be > 0, got "
            f"{threshold!r}")
    if block < 0:
        raise MXNetError(
            f"gradient compression block_size must be >= 0 "
            f"(0 = one per-tensor scale), got {block!r}")
    return {'type': ctype, 'threshold': threshold, 'block': block}


def _block_scale(x, block):
    """Per-block max-abs scale of ``x`` broadcast back to x's shape.
    Blocks tile the LAST dim when it divides evenly; otherwise one
    per-tensor scale (keeps the codec shape-agnostic — ragged tails
    would force gather/pad inside the compiled step). ``block=0`` is
    the explicit per-tensor mode. Zero blocks get scale 1.0 so the
    quantizer never divides by zero."""
    if block and x.ndim and x.shape[-1] % block == 0 and \
            x.shape[-1] >= block:
        nb = x.shape[-1] // block
        v = x.reshape(x.shape[:-1] + (nb, block))
        s = jnp.max(jnp.abs(v), axis=-1, keepdims=True)
        s = jnp.where(s > 0, s, 1.0)
        return jnp.broadcast_to(s, v.shape).reshape(x.shape)
    s = jnp.max(jnp.abs(x)) if x.size else jnp.float32(1.0)
    return jnp.where(s > 0, s, 1.0)


def n_scales(shape, block):
    """How many per-block fp32 scales the encoded form of a tensor with
    ``shape`` carries (the wire-overhead half of ``wire_bytes``)."""
    if not shape:
        return 1
    last = shape[-1]
    size = 1
    for d in shape:
        size *= d
    if block and last % block == 0 and last >= block:
        return size // block
    return 1


def encode_decode(x, ctype, threshold=0.5, block=256):
    """In-graph quantize→dequantize round trip: the fp32 value the far
    end of the compressed exchange would decode. Pure jnp (traceable
    inside pjit; no env/config reads — jit-purity rule). Non-finite
    inputs propagate to the output (see module docstring)."""
    x = x.astype(jnp.float32)
    if ctype == 'fp16':
        # fp16 truncation propagates NaN/Inf natively (overflow -> inf)
        return x.astype(jnp.float16).astype(jnp.float32)
    if ctype == 'int8':
        s = _block_scale(x, block) / 127.0
        q = jnp.clip(jnp.round(x / s), -127.0, 127.0)
        dec = q * s
    elif ctype == '2bit':
        # reference semantics: {-t, 0, +t} against the (per-block
        # scaled) threshold; block=0 -> s=1.0 -> the kvstore's absolute
        # threshold (test_kvstore.py compute_expected_2bit_quantization)
        s = _block_scale(x, block) if block else jnp.float32(1.0)
        t = threshold * s
        dec = jnp.where(x >= t, t, jnp.where(x <= -t, -t, 0.0))
    else:
        raise MXNetError(f"encode_decode: unknown codec {ctype!r}")
    # comparisons against NaN are all False -> a poisoned gradient
    # would silently decode to 0; re-inject so the guard sees it
    return jnp.where(jnp.isfinite(x), dec, x)


def wire_bytes(shape, ctype, block=256):
    """Analytic encoded bytes of one tensor on the wire: payload bits
    plus one fp32 scale per block (fp16 carries none). The uncompressed
    reference is ``4 * n`` fp32 bytes."""
    size = 1
    for d in tuple(shape):
        size *= d
    if ctype == 'none' or not ctype:
        return 4 * size
    bits = BITS_PER_ELEM[ctype]
    payload = (size * bits + 7) // 8
    scales = 0 if ctype == 'fp16' else 4 * n_scales(tuple(shape), block)
    if ctype == '2bit' and not block:
        scales = 0          # absolute threshold: no scales on the wire
    return payload + scales


def compression_ratio(shape, ctype, block=256):
    """fp32 bytes / encoded bytes for one tensor (>= 1.0)."""
    return wire_bytes(shape, 'none') / max(1, wire_bytes(
        shape, ctype, block))
