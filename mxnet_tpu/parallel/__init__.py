"""Parallelism over the TPU device mesh.

TPU-native replacement for the reference's kvstore/ps-lite/NCCL stack
(SURVEY §2.5): a `jax.sharding.Mesh` with named axes (dp/tp/pp/sp) plus
pjit/shard_map; XLA emits the collectives over ICI/DCN.

- mesh:        mesh construction helpers + global default mesh
- collectives: axis-name bookkeeping + psum/all_gather wrappers
- step:        compiled data/tensor-parallel training step builder
- dist:        multi-process init (jax.distributed), launch.py analog,
               elastic membership side channel (heartbeats, peer-loss
               detection, re-form barrier — MXTPU_ELASTIC)
- ring_attention: sequence-parallel ring attention over ppermute
"""
from .mesh import (make_mesh, default_mesh, set_default_mesh, mesh_shape,
                   data_parallel_spec, replicate_spec)
from . import collectives
from .step import ShardedTrainStep, compose_zero_spec, zero3_layout
from . import dist
from .ring_attention import ring_attention
from .pipeline import (pipeline_forward, pipeline_loss_fn,
                       pipeline_composite_loss, PipelineTrainStep,
                       stack_stage_params, split_layers_into_stages)
