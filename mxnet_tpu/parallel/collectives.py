"""Collective-communication surface.

The reference exposes push/pull (ps-lite) and NCCL allreduce; on TPU the
collectives are XLA ops inside compiled programs. This module provides:
- axis-name bookkeeping so layers (SyncBatchNorm) know which mesh axis is
  the data axis while tracing inside shard_map;
- thin wrappers over lax collectives usable in custom shard_map kernels;
- scheduling helpers for the ZeRO-3 per-layer all-gather pipeline
  (``ordered_barrier``, ``group_params_by_layer``): the gathers inside
  the compiled step are chained to EACH OTHER (layer k+1's gather
  depends on layer k's gather, not on layer k's compute), so XLA's
  latency-hiding scheduler can prefetch the next layer's parameters
  while the current layer computes.
"""
from __future__ import annotations

import re
import threading

import jax
from jax import lax

_tls = threading.local()


def _stack():
    if not hasattr(_tls, 'axes'):
        _tls.axes = []
    return _tls.axes


class data_axis:
    """Context manager declaring the active data-parallel axis name while
    tracing inside shard_map/pjit."""

    def __init__(self, name='dp'):
        self.name = name

    def __enter__(self):
        _stack().append(self.name)
        return self

    def __exit__(self, *exc):
        _stack().pop()


def current_data_axis():
    s = _stack()
    return s[-1] if s else None


def psum(x, axis_name):
    return lax.psum(x, axis_name)


def pmean(x, axis_name):
    return lax.pmean(x, axis_name)

def pmax(x, axis_name):
    return lax.pmax(x, axis_name)


def all_gather(x, axis_name, axis=0, tiled=True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, scatter_dimension=0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension,
                            tiled=True)


def ppermute(x, axis_name, perm):
    return lax.ppermute(x, axis_name, perm)


def axis_index(axis_name):
    return lax.axis_index(axis_name)


def axis_size(axis_name):
    return lax.axis_size(axis_name) if hasattr(lax, 'axis_size') else \
        lax.psum(1, axis_name)


# ---------------------------------------------------------------------------
# ZeRO-3 gather scheduling helpers
# ---------------------------------------------------------------------------

@jax.custom_vjp
def _opt_barrier(xs):
    return lax.optimization_barrier(xs)


def _opt_barrier_fwd(xs):
    return lax.optimization_barrier(xs), None


def _opt_barrier_bwd(_, cts):
    # identity cotangents: the barrier orders the forward schedule; the
    # backward regathers replay through jax.checkpoint with the same
    # forward-side barriers, so no extra fence is needed here
    return (tuple(cts),)


_opt_barrier.defvjp(_opt_barrier_fwd, _opt_barrier_bwd)


def ordered_barrier(*arrays):
    """Identity on ``arrays`` that makes every output depend on every
    input in the compiled schedule (``lax.optimization_barrier``), with
    a differentiation rule (the raw barrier has none in this jax).

    ZeRO-3 uses it to chain per-layer all-gathers: feeding layer k+1's
    sharded params through a barrier together with one leaf of layer
    k's GATHERED params makes gather(k+1) wait for gather(k) — but not
    for layer k's matmuls — so the gathers issue one layer ahead of the
    compute that consumes them."""
    if len(arrays) == 1:
        return (_opt_barrier((arrays[0],))[0],)
    return _opt_barrier(tuple(arrays))


def _natural_key(s):
    """Sort key treating digit runs numerically: layer2 < layer10."""
    return tuple(int(t) if t.isdigit() else t
                 for t in re.split(r'(\d+)', s))


_LAYER_RE = re.compile(r'^(.*?(?:layer|block|stage|cell|stack)\d+)')


def group_params_by_layer(names):
    """[(group_key, [param_name, ...]), ...] — parameters bucketed by
    the layer-ish prefix of their name (``...layerN``/``blockN``/... if
    present, else the name minus its final ``_kind`` token), groups and
    members in natural (digit-aware) order. This is the unit of the
    ZeRO-3 all-gather pipeline: one chained gather per group, ordered
    to approximate first-use order in a sequential model."""
    groups = {}
    for n in names:
        m = _LAYER_RE.match(n)
        key = m.group(1) if m else \
            (n.rsplit('_', 1)[0] if '_' in n else n)
        groups.setdefault(key, []).append(n)
    return [(k, sorted(groups[k], key=_natural_key))
            for k in sorted(groups, key=_natural_key)]
