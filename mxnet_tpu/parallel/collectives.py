"""Collective-communication surface.

The reference exposes push/pull (ps-lite) and NCCL allreduce; on TPU the
collectives are XLA ops inside compiled programs. This module provides:
- axis-name bookkeeping so layers (SyncBatchNorm) know which mesh axis is
  the data axis while tracing inside shard_map;
- thin wrappers over lax collectives usable in custom shard_map kernels.
"""
from __future__ import annotations

import threading

import jax
from jax import lax

_tls = threading.local()


def _stack():
    if not hasattr(_tls, 'axes'):
        _tls.axes = []
    return _tls.axes


class data_axis:
    """Context manager declaring the active data-parallel axis name while
    tracing inside shard_map/pjit."""

    def __init__(self, name='dp'):
        self.name = name

    def __enter__(self):
        _stack().append(self.name)
        return self

    def __exit__(self, *exc):
        _stack().pop()


def current_data_axis():
    s = _stack()
    return s[-1] if s else None


def psum(x, axis_name):
    return lax.psum(x, axis_name)


def pmean(x, axis_name):
    return lax.pmean(x, axis_name)

def pmax(x, axis_name):
    return lax.pmax(x, axis_name)


def all_gather(x, axis_name, axis=0, tiled=True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, scatter_dimension=0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension,
                            tiled=True)


def ppermute(x, axis_name, perm):
    return lax.ppermute(x, axis_name, perm)


def axis_index(axis_name):
    return lax.axis_index(axis_name)


def axis_size(axis_name):
    return lax.axis_size(axis_name) if hasattr(lax, 'axis_size') else \
        lax.psum(1, axis_name)
