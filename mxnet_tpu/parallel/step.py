"""Compiled sharded training step — the performance path.

This is the TPU-native realisation of the north star (BASELINE.json): the
whole train step (forward + backward + optimizer update + gradient
all-reduce) is ONE pjit-compiled XLA program per step. Parameters are
replicated (DP) or sharded (TP via param_specs) over the mesh; the batch is
sharded over the 'dp' axis; XLA inserts the gradient all-reduce over ICI.
Buffer donation on params/optimizer state gives the reference's
static-alloc in-place update behavior (ref: CachedOp static_alloc,
src/imperative/cached_op.cc:525).

ZeRO-1 (default on whenever the dp axis has >1 devices, gate with
MXTPU_ZERO=0 or zero=False): the fp32 masters and optimizer moments are
dp-SHARDED PartitionSpecs instead of replicated, so the grad all-reduce
becomes a reduce-scatter, each device updates only its 1/dp slice, and
the updated params all-gather back — same wire bytes, 1/dp optimizer
math and state HBM per device. See the mxnet_tpu_comm_* telemetry
contract for the per-run accounting.

ZeRO-3 / FSDP (MXTPU_ZERO=3 or zero=3): the PERSISTENT parameters
themselves (and the fp32 masters) additionally live dp-sharded between
steps (Rajbhandari et al. 2020 stage 3; Zhao et al. 2023 FSDP). Inside
the compiled step each layer's params are all-gathered on first use —
the gathers are chained per layer (``collectives.ordered_barrier``) so
layer k+1's gather overlaps layer k's compute, not one monolithic
up-front gather — and the gathered copies are NOT saved as autodiff
residuals (``jax.checkpoint`` with a ``save_any_names_but_these``
policy on the gather outputs): the backward pass regathers, so full
copies exist only transiently. Gradients reduce-scatter straight into
the shard-local update and the updated params are written back SHARDED
(no trailing all-gather — the next step's per-layer gathers do that
work). Net: param + master + optimizer persistent HBM all drop to
~1/dp, at the cost of one extra all-gather of the params per step (the
backward regather) in ring wire bytes.

Gradient compression + hierarchical collectives (ISSUE 12): with
``compression_params={'type': 'fp16'|'int8'|'2bit'}`` (or
``MXTPU_COMPRESSION``) the gradient exchange gains an error-feedback
quantization epilogue INSIDE the compiled step:
``dec = Q^-1(Q(grad + residual))`` feeds the optimizer and
``residual = grad + residual - dec`` persists per-param as SHARDED
optimizer-side state (donated, checkpointed in the layout-independent
states payload). When the dp axis spans multiple hosts (or
``MXTPU_HIERARCHICAL_DP`` forces a split), the axis decomposes into
(cross-host ``<dp>h``, intra-host ``<dp>i``) sub-axes: ZeRO shards and
the param all-gathers stay on the fast intra-host ICI hop, and only
the (compressed) gradient exchange crosses the slow DCN hop — the
ZeRO++-style hpZ tradeoff: state memory drops 1/h instead of 1/dp in
exchange for zero cross-host param traffic. The non-finite guard
reduces over the DECODED grads (and the residual epilogue), so a
poisoned step still skips on device with the residual writeback gated.
"""
from __future__ import annotations

import functools
from contextlib import nullcontext

import jax
import jax.numpy as jnp
import numpy as onp
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError, state as _flags, telem_flags as _telem
from ..ndarray.ndarray import NDArray
from ..resilience import faults as _faults
from ..telemetry import trace as _trace, flight as _flight, \
    memory as _memory, compile as _compile
from .. import random as _random
from ..ops import rowsparse as _rowsparse
from . import compression as _compression
from .collectives import group_params_by_layer, ordered_barrier
from .mesh import default_mesh


def _devices_span_processes(devices):
    """Does this device set include OTHER processes' devices? A
    process-LOCAL placement (e.g. an elastic survivor training on its
    own devices while jax.distributed is still initialized) must not
    pay — or wedge inside — cross-process collectives."""
    if jax.process_count() <= 1:
        return False
    try:
        me = jax.process_index()
        return any(d.process_index != me for d in devices)
    except Exception:
        return True


def _sharding_spans_processes(sharding):
    try:
        devices = sharding.device_set
    except Exception:
        return jax.process_count() > 1
    return _devices_span_processes(devices)


def _put_replicated(x, sharding):
    """Place parameter/optimizer data with a (possibly multi-host) sharding.
    Process-SPANNING sharding: broadcast process 0's value first, so every
    worker starts from identical parameters regardless of local RNG state —
    the analog of the reference's kvstore.init broadcast from worker 0
    (ref: src/kvstore/kvstore_dist.h InitImpl). A process-LOCAL sharding
    in a multi-process world gets NO broadcast: its step never crosses
    processes (independent replicas — e.g. an elastic survivor beside a
    dead world, or drill workers), so identical init is the caller's
    choice (seed identically, or sync via a dist kvstore), and the
    broadcast collective is exactly what a dead peer would wedge."""
    if _sharding_spans_processes(sharding):
        from jax.experimental import multihost_utils
        # lint: host-sync-ok param (re)placement runs at build/restore/re-form, not per step
        x = multihost_utils.broadcast_one_to_all(onp.asarray(x))
        x = onp.asarray(x)  # lint: host-sync-ok cold path, see above
    return jax.device_put(x, sharding)


def _put_batch(x, sharding):
    """Place a batch with the dp sharding. Single-process: the array is the
    global batch. Multi-process: each process holds its OWN shard (the
    reference's per-worker data partition, tools/launch.py semantics), and
    the global batch is their concatenation over the dp axis."""
    if _sharding_spans_processes(sharding):
        return jax.make_array_from_process_local_data(
            # lint: host-sync-ok the batch arrives host-resident from the io pipeline; h2d staging
            sharding, onp.asarray(x))
    return jax.device_put(x, sharding)


def _local_value(arr):
    """A fully-addressable view of a replicated global array (loss outputs
    span all processes; every device holds the same value)."""
    if jax.process_count() > 1 and not arr.is_fully_addressable:
        return arr.addressable_data(0)
    return arr


def device_nbytes(arr):
    """Bytes of ``arr`` ONE device physically holds: the local shard for
    a sharded global array, the full buffer for replicated/host arrays —
    the unit of the per-device residency accounting (ZeRO gauges)."""
    shards = getattr(arr, 'addressable_shards', None)
    if shards:
        return shards[0].data.nbytes
    return int(arr.size) * jnp.dtype(arr.dtype).itemsize


def compose_zero_spec(shape, base_spec, dp_axis, dp_size):
    """ZeRO layout for an optimizer-state/master tensor: compose a dp
    shard onto the parameter's (tp) PartitionSpec. Picks the first dim
    not already claimed by another mesh axis whose size splits EVENLY
    over dp. None when nothing is shardable (scalars, sub-dp-size and
    ragged tensors stay replicated — the ±slack of the 1/dp footprint;
    ZeRO-3 recovers the ragged ones via flatten+pad, see
    ``zero3_layout``).

    A base spec that itself proposes ``dp_axis`` on a non-divisible dim
    raises MXNetError up front: this jax refuses uneven NamedShardings
    at device_put/jit time with an opaque size error, so composing such
    a spec would only defer the failure."""
    spec = list(base_spec) + [None] * (len(shape) - len(base_spec))
    for i, s in enumerate(spec):
        # already sharded over dp (fsdp-style param_specs): the state
        # inherits the param's own 1/dp layout — composing again would
        # produce an invalid duplicate-axis spec
        if s == dp_axis or (isinstance(s, (tuple, list)) and dp_axis in s):
            if dp_size > 1 and shape[i] % dp_size != 0:
                raise MXNetError(
                    f"compose_zero_spec: spec {tuple(base_spec)!r} shards "
                    f"dim {i} (size {shape[i]}) over the {dp_size}-device "
                    f"'{dp_axis}' axis, but {shape[i]} is not divisible "
                    f"by {dp_size} — XLA refuses uneven shardings. Pad "
                    f"the dim, drop '{dp_axis}' from the spec, or let "
                    f"ZeRO-3 flatten+pad it (zero3_layout).")
            return None
    for i, s in enumerate(spec):
        if s is not None or shape[i] < dp_size \
                or shape[i] % dp_size != 0:
            continue
        spec[i] = dp_axis
        return P(*spec)
    return None


def zero3_layout(shape, base_spec, dp_axis, dp_size):
    """Persistent ZeRO-3 layout for one parameter. Returns a dict:

    - ``{'mode': 'dim', 'spec': P(...), 'gather_spec': P(...)}`` — an
      exactly-divisible free dim shards over dp (composed with any tp
      dims the param already claims); the param/master/moments live in
      logical shape with that spec, and the in-step gather restores
      ``gather_spec`` (the tp-only layout the forward computes in).
    - ``{'mode': 'flat', 'size': s, 'padded': p, 'pad': p - s}`` — no
      dim divides evenly: the fp32 master + moments live as a 1-D
      buffer padded to a dp multiple and sharded ``P(dp)``; the
      compute-dtype param keeps a replicated logical copy (these are
      the ragged stragglers — the pad bytes are reported by
      ``opt_state_bytes_per_device``). Never chosen for tp-sharded
      params (flattening would destroy the tp layout).
    - ``{'mode': 'repl'}`` — too small to shard; fully replicated.
    """
    spec = list(base_spec) + [None] * (len(shape) - len(base_spec))

    def _trim(entries):
        entries = list(entries)
        while entries and entries[-1] is None:
            entries.pop()
        return P(*entries)

    for i, s in enumerate(spec):
        if s == dp_axis or (isinstance(s, (tuple, list)) and dp_axis in s):
            # user proposed the dp shard (fsdp-style): validate and keep
            compose_zero_spec(shape, base_spec, dp_axis, dp_size)
            gspec = [None if ss == dp_axis else
                     (tuple(a for a in ss if a != dp_axis) or None
                      if isinstance(ss, (tuple, list)) else ss)
                     for ss in spec]
            return {'mode': 'dim', 'spec': P(*spec),
                    'gather_spec': _trim(gspec)}
    composed = compose_zero_spec(shape, base_spec, dp_axis, dp_size)
    if composed is not None:
        return {'mode': 'dim', 'spec': composed,
                'gather_spec': _trim(spec)}
    size = int(onp.prod(shape)) if shape else 1
    if size >= dp_size and all(s is None for s in spec):
        padded = -(-size // dp_size) * dp_size
        return {'mode': 'flat', 'size': size, 'padded': padded,
                'pad': padded - size}
    return {'mode': 'repl'}


def split_dp_mesh(mesh, dp_axis, n_hosts):
    """Rebuild ``mesh`` with its ``dp_axis`` split into
    (``<dp>h`` cross-host, ``<dp>i`` intra-host) sub-axes of extents
    (n_hosts, dp//n_hosts) — dp-major device order, so each host group
    is a contiguous run along the original axis (the order
    ``dist.host_topology`` validated). Other axes are untouched."""
    from jax.sharding import Mesh
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    dp = shape.get(dp_axis, 1)
    if n_hosts <= 1 or dp % n_hosts != 0:
        raise MXNetError(
            f"split_dp_mesh: cannot split the {dp}-device {dp_axis!r} "
            f"axis into {n_hosts} host groups")
    names, dims = [], []
    for name, size in zip(mesh.axis_names, mesh.devices.shape):
        if name == dp_axis:
            names += [dp_axis + 'h', dp_axis + 'i']
            dims += [n_hosts, dp // n_hosts]
        else:
            names.append(name)
            dims.append(size)
    return Mesh(mesh.devices.reshape(tuple(dims)), tuple(names))


def _sgd_init(p):
    return (jnp.zeros_like(p),)


def _sgd_update(p, g, s, lr, momentum=0.9, wd=0.0):
    mom, = s
    g = g + wd * p
    new_mom = momentum * mom - lr * g
    return p + new_mom, (new_mom,)


def _adam_init(p):
    return (jnp.zeros_like(p), jnp.zeros_like(p), jnp.zeros((), jnp.int32))


def _adam_update(p, g, s, lr, beta1=0.9, beta2=0.999, eps=1e-8, wd=0.0):
    m, v, t = s
    t = t + 1
    g = g + wd * p
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * jnp.square(g)
    mhat = m / (1 - beta1 ** t.astype(jnp.float32))
    vhat = v / (1 - beta2 ** t.astype(jnp.float32))
    return p - lr * mhat / (jnp.sqrt(vhat) + eps), (m, v, t)


def _adamw_update(p, g, s, lr, beta1=0.9, beta2=0.999, eps=1e-8, wd=0.01,
                  eta=1.0):
    # reference semantics (src/operator/contrib/adamw.cc, the GluonNLP
    # BERTAdam recipe): NO bias correction, decoupled wd scaled by lr —
    # kept identical to ops/optimizer_ops.py adamw_update so the Trainer
    # and ShardedTrainStep paths produce the same trajectory
    # (tests/test_gradients.py parity check)
    m, v, t = s
    t = t + 1
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * jnp.square(g)
    return p - eta * (lr * m / (jnp.sqrt(v) + eps) + wd * lr * p), \
        (m, v, t)


def _lamb_update(p, g, s, lr, beta1=0.9, beta2=0.999, eps=1e-6, wd=0.01):
    m, v, t = s
    t = t + 1
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * jnp.square(g)
    mhat = m / (1 - beta1 ** t.astype(jnp.float32))
    vhat = v / (1 - beta2 ** t.astype(jnp.float32))
    update = mhat / (jnp.sqrt(vhat) + eps) + wd * p
    r1 = jnp.linalg.norm(p.reshape(-1))
    r2 = jnp.linalg.norm(update.reshape(-1))
    ratio = jnp.where((r1 > 0) & (r2 > 0), r1 / r2, 1.0)
    return p - lr * ratio * update, (m, v, t)


_OPTS = {
    'sgd': (_sgd_init, _sgd_update),
    'adam': (_adam_init, _adam_update),
    'adamw': (_adam_init, _adamw_update),
    'lamb': (_adam_init, _lamb_update),
}


class ShardedTrainStep:
    """One-pjit-call training step for a Gluon block over a device mesh.

    Usage:
        step = ShardedTrainStep(net, loss_fn, 'adam',
                                optimizer_params={'lr': 1e-3}, mesh=mesh)
        loss = step(data, label)      # NDArrays; params updated in place
    """

    def __init__(self, block, loss_fn, optimizer='sgd', optimizer_params=None,
                 mesh=None, dp_axis='dp', param_specs=None, donate=True,
                 grad_dtype=None, zero=None, compression_params=None,
                 guard=None, hierarchy=None):
        self.block = block
        self.loss_fn = loss_fn
        self.dp_axis = dp_axis
        self.optimizer_params = dict(optimizer_params or {})
        self.lr = self.optimizer_params.pop('learning_rate',
                                            self.optimizer_params.pop('lr', 0.01))
        # reference Optimizer(lazy_update=...): lazy (default) updates
        # only the live rows of row_sparse-grad params inside the step;
        # False forces the exact densified path (bit-identical to dense
        # training — the parity oracle, like MXTPU_SPARSE_EXACT)
        self._lazy_sparse = bool(self.optimizer_params.pop(
            'lazy_update', True))
        self._sparse_names = []
        self._sparse_prev_stats = None
        if optimizer not in _OPTS:
            raise ValueError(f"ShardedTrainStep supports {sorted(_OPTS)}")
        self._opt_init, self._opt_update = _OPTS[optimizer]
        self.param_specs = param_specs or {}
        self.donate = donate
        # error-feedback gradient compression (ISSUE 12): routed for
        # real — validated into a codec spec here, applied as the
        # quantize/decode epilogue inside the compiled step; only a
        # genuinely unknown ctype string still raises
        self.compression = _compression.resolve(compression_params)
        self._requested_hierarchy = hierarchy
        self._adopt_mesh(mesh if mesh is not None else default_mesh())
        dp_size = self._dp_size
        if zero is None:
            from .. import config as _cfg
            zero = _cfg.get('MXTPU_ZERO')
        stage = int(zero) if not isinstance(zero, bool) else int(bool(zero))
        if stage not in (0, 1, 3):
            raise MXNetError(
                f"zero={zero!r}: supported ZeRO stages are 0 (off), 1 "
                f"(sharded optimizer state) and 3 (sharded params + "
                f"grads + state / FSDP); stage 2 has no separate "
                f"meaning on the GSPMD path (gradients already "
                f"reduce-scatter under stage 1).")
        # ZeRO-1: default-on when a >1-device dp axis exists (the fp32
        # masters + Adam moments then live 1/dp per device). ZeRO-3
        # additionally shards the persistent params (gathered per layer
        # on use inside the step). The REQUESTED stage is kept so an
        # elastic reset_mesh() re-derives the effective stage at the
        # survivor world's dp degree.
        self._requested_stage = stage
        self.zero_stage = stage if dp_size > 1 else 0
        # MXTPU_REMAT (ISSUE 18): activation-remat policy for the
        # forward, read once at construction so the build signature and
        # the checkpoint seam agree for this step's lifetime
        from .. import config as _remat_cfg
        self._remat_policy = _remat_cfg.get('MXTPU_REMAT')
        self._spans_processes = self._mesh_spans_processes()
        self.zero = self.zero_stage > 0
        self._params = None       # list[(name, Parameter)]
        self._master = None       # fp32 master copies of bf16/fp16 params
        self._opt_state = None
        self._residual = None     # error-feedback residuals (compression)
        self._compiled = None
        self._alias = None        # name-stable jit-boundary key aliases
        self._alias_rev = None
        self._step_count = 0
        self._pending_states = None   # restored blob awaiting first build
        self._cost_args = None        # avals for cost_analysis()
        # resilience.NonFiniteGuard: the pjit step then also reduces
        # isfinite over loss + every grad and gates the whole writeback
        # on device; the guard reads the flag one step deferred
        self._guard = guard
        if guard is not None:
            guard.add_post_restore_hook(self._replace_params_on_mesh)

    def _adopt_mesh(self, mesh):
        """Adopt ``mesh``, decomposing the dp axis into (cross-host,
        intra-host) sub-axes when a hierarchy exists (real multi-host
        process topology, or ``hierarchy=``/``MXTPU_HIERARCHICAL_DP``
        forcing a synthetic split). Sets the axis bookkeeping every
        later layout decision reads:

        - ``_dp_axes``   — axis names the BATCH shards over (the full
          dp extent either way);
        - ``_shard_axis``/``_shard_size`` — the axis ZeRO shards over
          (intra-host under hierarchy: params/masters/moments replicate
          across hosts so no param all-gather ever crosses DCN);
        - ``_cross_axis``/``_cross_size`` — the slow hop the
          (compressible) gradient exchange crosses (None when flat).
        """
        from . import dist as _dist
        shape = dict(zip(mesh.axis_names, mesh.devices.shape))
        dp = int(shape.get(self.dp_axis, 1))
        H, h = 1, dp
        if dp > 1 and self.dp_axis in shape:
            idx = mesh.axis_names.index(self.dp_axis)
            lead = [0] * len(mesh.axis_names)
            col = []
            for i in range(dp):
                lead[idx] = i
                col.append(mesh.devices[tuple(lead)])
            H, h = _dist.dp_host_split(col, force=self._requested_hierarchy)
        if H > 1:
            for pat, spec in (self.param_specs or {}).items():
                if self.dp_axis in str(spec):
                    raise MXNetError(
                        f"hierarchical dp: param_spec {pat!r} proposes "
                        f"the {self.dp_axis!r} axis, which is split "
                        f"into ({self.dp_axis}h, {self.dp_axis}i) "
                        f"sub-axes under MXTPU_HIERARCHICAL_DP — use "
                        f"{self.dp_axis}i for fsdp-style sharding, or "
                        f"force the flat topology (hierarchy=1).")
            mesh = split_dp_mesh(mesh, self.dp_axis, H)
            self._dp_axes = (self.dp_axis + 'h', self.dp_axis + 'i')
            self._shard_axis = self.dp_axis + 'i'
            self._cross_axis = self.dp_axis + 'h'
        else:
            self._dp_axes = (self.dp_axis,)
            self._shard_axis = self.dp_axis
            self._cross_axis = None
        self.mesh = mesh
        self._dp_size = dp
        self._shard_size = h
        self._cross_size = H
        return mesh

    def _mesh_spans_processes(self):
        """Does this step's mesh include other processes' devices? Then
        every step is a cross-process collective — one that a lost peer
        wedges forever, which is why dispatch refuses to enter it once
        the membership layer has declared a loss."""
        try:
            devices = list(self.mesh.devices.flat)
        except Exception:
            return jax.process_count() > 1
        return _devices_span_processes(devices)

    # ------------------------------------------------------------------
    def _collect(self):
        params = sorted(self.block.collect_params().items())
        trainable = [(n, p) for n, p in params if p.grad_req != 'null']
        frozen = [(n, p) for n, p in params if p.grad_req == 'null']
        return trainable, frozen

    def _resolve_param_specs(self, names):
        """name -> PartitionSpec. A spec key matches a parameter by exact
        name or as a regex via re.search (so plain substrings keep
        working). Unmatched specs and conflicting matches warn; the full
        mapping is kept on self.param_spec_report for inspection."""
        import re
        import warnings
        mapping = {n: P() for n in names}
        matched_by = {n: None for n in names}
        report = {}
        for pat, spec in self.param_specs.items():
            hits = [n for n in names
                    if n == pat or re.search(str(pat), n) is not None]
            report[pat] = hits
            if not hits:
                warnings.warn(
                    f"ShardedTrainStep: param_spec {pat!r} matched no "
                    f"parameter (have e.g. {sorted(names)[:5]})",
                    RuntimeWarning)
            for n in hits:
                if matched_by[n] is not None and mapping[n] != spec:
                    warnings.warn(
                        f"ShardedTrainStep: parameter {n!r} matched both "
                        f"{matched_by[n]!r} and {pat!r}; using {pat!r}",
                        RuntimeWarning)
                mapping[n] = spec
                matched_by[n] = pat
        self.param_spec_report = report
        return mapping

    def _spec_for(self, name):
        if getattr(self, '_spec_map', None) is not None and \
                name in self._spec_map:
            return self._spec_map[name]
        return P()  # replicated

    def _build(self, example_inputs, example_labels):
        trainable, frozen = self._collect()
        t_names = [n for n, _ in trainable]
        f_names = [n for n, _ in frozen]
        self._spec_map = self._resolve_param_specs(t_names + f_names)
        # low-precision trainables keep a persistent fp32 master copy
        # (the reference's create_state_multi_precision,
        # python/mxnet/optimizer/optimizer.py:52): without it, updates
        # below the bf16 ulp of the weight are lost to re-rounding.
        master_names = frozenset(
            n for n, p in trainable
            if jnp.dtype(p.data()._data.dtype).itemsize < 4
            and jnp.issubdtype(p.data()._data.dtype, jnp.floating))
        block = self.block
        loss_fn = self.loss_fn
        opt_update = self._opt_update
        opt_kwargs = self.optimizer_params
        n_inputs = len(example_inputs)

        def forward_loss(t_params, f_params, inputs, labels, key,
                         fault_scale, row_tangents=None):
            all_params = dict(t_params)
            all_params.update(f_params)
            name_to_param = dict(trainable + frozen)
            proxies = {}
            for n, p in name_to_param.items():
                proxies[n] = NDArray(all_params[n])
                p._set_trace_proxy(proxies[n])
            # RowSparse capture (ISSUE 19): armed INSIDE this function —
            # which jax.checkpoint re-traces during backward — so the
            # table identities the embedding op matches on are always
            # the CURRENT trace's tracers. Each captured lookup routes
            # through the dedup-first gather, adds its slice of the
            # zero row tangent (whose cotangent IS the RowSparse row
            # block), and records the live ids for the optimizer.
            cap = None
            if row_tangents is not None:
                cap = _rowsparse.trace_capture(
                    {n: all_params[n] for n in row_tangents},
                    tangents=row_tangents, budgets=sparse_budgets)
            prev = _flags.is_training
            _flags.is_training = True
            try:
                with _random.key_provider(_random.TraceKeyProvider(key)), \
                        (cap if cap is not None else nullcontext()):
                    out = block.forward(*[NDArray(x) for x in inputs])
                    outs = out if isinstance(out, (list, tuple)) else (out,)
                    loss = loss_fn(*outs, *[NDArray(l) for l in labels])
            finally:
                _flags.is_training = prev
                for p in name_to_param.values():
                    p._clear_trace_proxy()
            # fault_scale is 1.0 on every normal step (an exact-identity
            # multiply); an injected step.dispatch:nan passes NaN here,
            # poisoning the loss AND (via the chain rule) every gradient
            # regardless of the model's input dtypes — int-token models
            # like BERT included
            loss_val = jnp.mean(loss._data) * fault_scale
            aux = {n: proxies[n]._data for n in f_names}
            if cap is not None:
                return loss_val, (aux, cap.results())
            return loss_val, aux

        # ------------------------------------------------------------------
        # RowSparse fast path (ISSUE 19): parameters declared
        # grad_stype='row_sparse' (Embedding(sparse_grad=True)) carry
        # (unique row ids, row-block values) gradients and live-rows-only
        # optimizer updates. Budgets — the static worst-case unique-row
        # counts per lookup — are discovered with one abstract
        # jax.eval_shape trace (no compile, no FLOPs) before the real
        # program is built.
        from .. import config as _cfg
        sparse_on = bool(_cfg.get('MXTPU_SPARSE'))
        sparse_exact = bool(_cfg.get('MXTPU_SPARSE_EXACT')) \
            or not self._lazy_sparse
        sparse_cap = int(_cfg.get('MXTPU_SPARSE_ROWS'))
        table_axis = str(_cfg.get('MXTPU_SPARSE_TABLE_AXIS') or '') or None
        name_to_p = dict(trainable)
        s_candidates = [
            n for n, p in trainable
            if getattr(p, '_grad_stype', 'default') == 'row_sparse'
            and len(tuple(p.data().shape)) == 2]
        sparse_budgets = {}          # name -> [per-lookup row budget]
        sparse_id_counts = {}        # name -> flat ids per step (pre-dedup)
        if sparse_on and s_candidates:
            discovered = {}

            def _discover(t_params, f_params, inputs, labels, key,
                          fault_scale):
                cap = _rowsparse.trace_capture(
                    {n: t_params[n] for n in s_candidates})
                with cap:
                    forward_loss(t_params, f_params, inputs, labels,
                                 key, fault_scale)
                for cn, slot in cap.slots.items():
                    discovered[cn] = list(slot.call_sizes)
                return jnp.zeros(())

            t_avals = {n: jax.ShapeDtypeStruct(
                tuple(p.data().shape), p.data()._data.dtype)
                for n, p in trainable}
            f_avals = {n: jax.ShapeDtypeStruct(
                tuple(p.data().shape), p.data()._data.dtype)
                for n, p in frozen}
            jax.eval_shape(
                _discover, t_avals, f_avals,
                tuple(jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
                      for x in example_inputs),
                tuple(jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
                      for x in example_labels),
                jax.random.PRNGKey(0), jnp.float32(1.0))
            for n in s_candidates:
                sizes = discovered.get(n) or []
                if not sizes:
                    continue     # never looked up through embedding
                vocab = int(name_to_p[n].data().shape[0])
                buds = [min(s, vocab) for s in sizes]
                if sparse_cap and sum(buds) > sparse_cap:
                    continue     # budget over ceiling: dense fallback
                sparse_budgets[n] = buds
                sparse_id_counts[n] = int(sum(sizes))
        s_names = sorted(sparse_budgets)
        self._sparse_names = s_names
        self._sparse_budgets = sparse_budgets
        self._sparse_id_counts = sparse_id_counts
        self._sparse_exact = sparse_exact
        # model-parallel table sharding: a divisible vocab shards
        # P(table_axis) and XLA inserts the all-to-all feature exchange
        # for remote rows; ragged vocabularies keep the replicated
        # compute copy (their fp32 state still shards through ZeRO-3's
        # flat padded stores)
        self._sparse_table_axis = None
        sparse_table_sharded = set()
        if table_axis and s_names:
            if table_axis in (self.dp_axis, self._shard_axis,
                              self._cross_axis):
                raise MXNetError(
                    f"MXTPU_SPARSE_TABLE_AXIS={table_axis!r} collides "
                    f"with the data-parallel axis — pick a model "
                    f"axis (e.g. 'tp').")
            tshape = dict(zip(self.mesh.axis_names,
                              self.mesh.devices.shape))
            tsize = int(tshape.get(table_axis, 0))
            if tsize > 1:
                for n in s_names:
                    vocab = int(name_to_p[n].data().shape[0])
                    if vocab % tsize == 0 and \
                            self._spec_for(n) == P():
                        self._spec_map[n] = P(table_axis)
                        sparse_table_sharded.add(n)
                if sparse_table_sharded:
                    self._sparse_table_axis = table_axis
        self._sparse_sig = {
            'mode': 'exact' if sparse_exact else 'lazy',
            'table_axis': self._sparse_table_axis,
            'tables': {n: int(sum(sparse_budgets[n])) for n in s_names},
        } if s_names else None

        # shardings. The batch shards over the FULL dp extent either
        # way; ZeRO layouts shard over the intra-host sub-axis when the
        # hierarchy is active (see _adopt_mesh), so param traffic never
        # crosses the DCN hop.
        mesh = self.mesh
        repl = NamedSharding(mesh, P())
        batch_sh = NamedSharding(mesh, P(self._dp_axes))
        shard_axis, shard_size = self._shard_axis, self._shard_size

        t_shardings = {n: NamedSharding(mesh, self._spec_for(n))
                       for n in t_names}
        f_shardings = {n: NamedSharding(mesh, self._spec_for(n))
                       for n in f_names}
        # ZeRO-1 (Rajbhandari et al., 2020, stage 1): the fp32 masters and
        # Adam moments shard 1/dp over the dp axis (composed with any tp
        # dims the param already shards). The update then reads a
        # dp-SHARDED gradient — the constraint below turns the plain
        # all-reduce into reduce-scatter — and out_shardings all-gather
        # the updated param back to its replicated/tp layout. GSPMD fuses
        # and overlaps both collectives with backward compute.
        shapes = {n: tuple(p.data().shape) for n, p in trainable}
        stage3 = self.zero_stage == 3
        zero_specs = {n: None for n in t_names}
        z3 = {}
        if stage3:
            # ZeRO-3: every trainable gets a persistent layout — dim
            # (sharded in logical shape), flat (fp32 store padded to a
            # dp multiple) or repl (too small)
            for n in t_names:
                z3[n] = zero3_layout(shapes[n], self._spec_for(n),
                                     shard_axis, shard_size)
                if z3[n]['mode'] == 'dim':
                    zero_specs[n] = z3[n]['spec']
        elif self.zero:
            for n in t_names:
                zero_specs[n] = compose_zero_spec(
                    shapes[n], self._spec_for(n), shard_axis,
                    shard_size)
        self.zero_specs = zero_specs
        self.zero3_layouts = z3
        self._shapes = shapes
        self._zero_label = 'zero3' if stage3 else \
            ('zero1' if self.zero else 'off')
        flat_meta = {n: z3[n] for n in t_names
                     if stage3 and z3[n]['mode'] == 'flat'}
        dim_names = [n for n in t_names
                     if stage3 and z3[n]['mode'] == 'dim']
        # flat params: the compute-dtype logical copy stays replicated;
        # the fp32 master IS the (padded, dp-sharded) persistent store,
        # so they join master_names regardless of dtype
        master_names = frozenset(master_names) | frozenset(flat_meta)
        if stage3:
            # persistent params live dp-sharded between steps
            for n in dim_names:
                t_shardings[n] = NamedSharding(mesh, z3[n]['spec'])
        flat_sh = NamedSharding(mesh, P(shard_axis))
        zero_shardings = {
            n: (flat_sh if n in flat_meta else
                NamedSharding(mesh, zero_specs[n])
                if zero_specs[n] is not None else t_shardings[n])
            for n in t_names}
        # optimizer state shards like its parameter (ZeRO: like its
        # slice). ZeRO-3 flat params carry flat (padded) moments — put
        # them in place before the shardings are derived from them.
        for n, fz in flat_meta.items():
            self._opt_state[n] = self._opt_init(
                jnp.zeros((fz['padded'],), jnp.float32))
        state_shardings = {
            n: tuple((repl if s.ndim == 0 else zero_shardings[n])
                     for s in self._opt_state[n])
            for n in t_names}

        master_shardings = {n: zero_shardings[n] for n in master_names}
        shard_constraint = {n: zero_shardings[n] for n in t_names
                            if zero_specs[n] is not None}

        # error-feedback compression: one fp32 residual per trainable,
        # persisted in the SAME layout the grad is consumed in (the
        # zero shard / flat store / replicated) so acc = g + r is a
        # local elementwise add with no extra collective
        comp = self.compression
        comp_on = comp is not None
        ctype = comp['type'] if comp_on else 'none'
        cthreshold = comp['threshold'] if comp_on else 0.0
        cblock = comp['block'] if comp_on else 0
        residual_shapes = {}
        residual_shardings = {}
        if comp_on:
            for n in t_names:
                fz = flat_meta.get(n)
                residual_shapes[n] = (fz['padded'],) if fz is not None \
                    else shapes[n]
                residual_shardings[n] = zero_shardings[n]
        self._residual_shapes = residual_shapes
        self._residual_shardings = residual_shardings

        # ZeRO-3 per-layer gather pipeline: one chained all-gather per
        # layer group, in (heuristic) first-use order
        layer_groups = group_params_by_layer(dim_names) if dim_names \
            else []
        self._layer_groups = layer_groups
        gather_ns = {n: NamedSharding(mesh, z3[n]['gather_spec'])
                     for n in dim_names}

        if stage3 and dim_names:
            def gather_all(t_params):
                """All-gather the dim-sharded params layer by layer:
                each group's gather is barrier-chained to the PREVIOUS
                group's gather (not its compute), so XLA can prefetch
                layer k+1's params while layer k computes; the gathered
                values are checkpoint-named so the remat policy below
                drops them from the autodiff residuals (the backward
                pass regathers)."""
                gathered = dict(t_params)
                token = None
                for _gname, names in layer_groups:
                    vals = [t_params[n] for n in names]
                    if token is not None:
                        out = ordered_barrier(*(vals + [token]))
                        vals = list(out[:-1])
                    vals = [checkpoint_name(
                        jax.lax.with_sharding_constraint(v, gather_ns[n]),
                        'zero3_gather')
                        for n, v in zip(names, vals)]
                    for n, v in zip(names, vals):
                        gathered[n] = v
                    token = vals[0]
                return gathered

            def forward_sharded(t_params, f_params, inputs, labels, key,
                                fault_scale, row_tangents=None):
                return forward_loss(gather_all(t_params), f_params,
                                    inputs, labels, key, fault_scale,
                                    row_tangents)

            loss_base = forward_sharded
            # ZeRO-3 floor: whatever the remat policy, the gathered
            # params are NEVER kept as autodiff residuals
            base_policy = \
                jax.checkpoint_policies.save_any_names_but_these(
                    'zero3_gather')
        else:
            loss_base = forward_loss
            base_policy = None

        # MXTPU_REMAT (ISSUE 18): parameterized activation remat of the
        # forward. 'none' keeps the historical behavior bit-for-bit
        # (checkpoint only as the ZeRO-3 gather-drop floor above);
        # 'layer' saves only matmul outputs without batch dims — the
        # classic per-layer checkpoint trade (~1 extra forward of FLOPs
        # for O(layers) activation HBM; the gathers stay dropped since
        # an all-gather is not a dot); 'aggressive' saves nothing.
        # Remat never changes values, only what backward recomputes —
        # tests assert loss parity across all three policies, and
        # memory_analysis() cross-validates the HBM deltas.
        remat = self._remat_policy
        if remat == 'layer':
            loss_forward = jax.checkpoint(
                loss_base,
                policy=jax.checkpoint_policies
                .dots_with_no_batch_dims_saveable)
        elif remat == 'aggressive':
            loss_forward = jax.checkpoint(
                loss_base,
                policy=jax.checkpoint_policies.nothing_saveable)
        elif base_policy is not None:
            loss_forward = jax.checkpoint(loss_base, policy=base_policy)
        else:
            loss_forward = loss_base

        guard_on = self._guard is not None

        def train_step(t_params, f_params, master, opt_state, residual,
                       inputs, labels, key, lr, fault_scale):
            if s_names:
                # RowSparse tables ride as zero tangents: the embedding
                # lookup adds tangent[live-row slice] to the gathered
                # rows (the table itself is stop_gradient-ed in the
                # capture), so d loss/d tangent IS the deduped row-block
                # gradient — no table-shaped cotangent ever exists
                tangents = {n: jnp.zeros(
                    (sum(sparse_budgets[n]), shapes[n][1]), jnp.float32)
                    for n in s_names}
                (loss_val, (aux, srec)), (grads, g_rows) = \
                    jax.value_and_grad(
                        loss_forward, argnums=(0, 6), has_aux=True)(
                            t_params, f_params, inputs, labels, key,
                            fault_scale, tangents)
            else:
                (loss_val, aux), grads = jax.value_and_grad(
                    loss_forward, has_aux=True)(t_params, f_params,
                                                inputs, labels, key,
                                                fault_scale)
                srec, g_rows = {}, {}
            new_params = {}
            new_master = {}
            new_state = {}
            new_residual = {}
            sparse_stats = {}
            ok = jnp.isfinite(loss_val) if guard_on else None
            for n in t_names:
                srn = srec.get(n)
                if srn is not None:
                    vocab, dim = shapes[n]
                    uids = srn['uids']
                    rows = g_rows[n].astype(jnp.float32)
                    if len(sparse_budgets[n]) > 1:
                        # several lookups of the same table in one step:
                        # segment-sum overlapping ids into one block
                        uids, rows, n_live = _rowsparse.merge_row_blocks(
                            uids, rows, vocab)
                    else:
                        n_live = srn['n_live']
                    sparse_stats[n] = n_live
                    if not sparse_exact:
                        # lazy update (reference lazy_update=True /
                        # kvstore row_sparse semantics): gather the live
                        # rows of master + moments, run the SAME
                        # optimizer kernel on the (budget, dim) block,
                        # scatter back. Sentinel slots (uid == vocab)
                        # gather a clipped garbage row whose writeback
                        # XLA's OOB scatter DROPS — dead slots never
                        # touch the table. Moments of absent rows stay
                        # frozen; wd applies to live rows only.
                        fz = flat_meta.get(n)
                        if fz is not None:
                            # zero3 flat padded store: a row is a
                            # contiguous dim-slice of the 1-D buffer
                            fidx = (uids[:, None] * dim + jnp.arange(
                                dim, dtype=jnp.int32)[None, :])

                            def _rget(a, fidx=fidx):
                                return jnp.take(a, fidx, mode='clip')

                            def _rset(a, r, fidx=fidx):
                                return a.at[fidx].set(r, mode='drop')
                        else:
                            def _rget(a, uids=uids):
                                return jnp.take(a, uids, axis=0,
                                                mode='clip')

                            def _rset(a, r, uids=uids):
                                return a.at[uids].set(r, mode='drop')
                        if comp_on:
                            # error-feedback codec on the ROW BLOCK with
                            # per-row scales (block = dim); the residual
                            # stays table-shaped and persistent — only
                            # live rows accumulate/flush error
                            acc = rows + _rget(residual[n])
                            dec = _compression.encode_decode(
                                acc, ctype, cthreshold, dim)
                            new_residual[n] = _rset(residual[n],
                                                    acc - dec)
                            rows = dec
                        if guard_on:
                            ok = jnp.logical_and(
                                ok, jnp.all(jnp.isfinite(rows)))
                        if n in master_names:
                            p32 = master[n]
                        else:
                            p32 = t_params[n].astype(jnp.float32)
                        p_rows = _rget(p32)
                        s_rows = tuple(_rget(s) if s.ndim else s
                                       for s in opt_state[n])
                        nr_, nsr_ = opt_update(p_rows, rows, s_rows, lr,
                                               **opt_kwargs)
                        np_ = _rset(p32, nr_)
                        new_state[n] = tuple(
                            _rset(s, sr) if s.ndim else sr
                            for s, sr in zip(opt_state[n], nsr_))
                        if fz is not None:
                            new_params[n] = np_[:fz['size']].reshape(
                                shapes[n]).astype(t_params[n].dtype)
                            new_master[n] = np_
                        else:
                            new_params[n] = np_.astype(t_params[n].dtype)
                            if n in master_names:
                                new_master[n] = np_
                        continue
                    # exact mode: densify the deduped block into a
                    # table-shaped grad and run the regular dense path —
                    # bit-identical trajectories to dense training (the
                    # parity oracle). The WIRE exchange still happened
                    # on row blocks (the tangent cotangent), only the
                    # local update is dense.
                    g32 = jnp.zeros((vocab, dim), jnp.float32) \
                        .at[uids].add(rows, mode='drop')
                else:
                    g32 = grads[n].astype(jnp.float32)
                fz = flat_meta.get(n)
                zsh = shard_constraint.get(n)
                if fz is not None:
                    # ragged param (ZeRO-3 flatten+pad): the grad
                    # flattens and zero-pads into the flat 1/dp layout
                    g32 = jnp.pad(g32.reshape(-1), (0, fz['pad']))
                    g32 = jax.lax.with_sharding_constraint(
                        g32, zero_shardings[n])
                elif zsh is not None:
                    # reduce-scatter: the grad is only ever consumed in
                    # this dp-sharded layout, so the partitioner combines
                    # the backward psum + slice into one reduce-scatter
                    g32 = jax.lax.with_sharding_constraint(g32, zsh)
                if comp_on:
                    # error-feedback quantized exchange epilogue: the
                    # cross-host hop carries Q(g + r); the decoded value
                    # feeds the update and the quantization error r' is
                    # re-offered next step instead of lost (Lin et al.;
                    # Karimireddy et al.). Elementwise on the sharded
                    # grad — adds no collective of its own.
                    acc = g32 + residual[n]
                    g32 = _compression.encode_decode(
                        acc, ctype, cthreshold, cblock)
                    new_residual[n] = acc - g32
                if guard_on:
                    # isfinite over the SHARDED (and, under compression,
                    # DECODED) grad: each device reduces its slice and
                    # GSPMD psums the scalar — never a full-grad rebuild.
                    # encode_decode propagates non-finite inputs, so a
                    # poisoned gradient cannot hide behind the quantizer.
                    ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(g32)))
                if n in master_names:
                    p32 = master[n]
                else:
                    p32 = t_params[n].astype(jnp.float32)
                    if zsh is not None:
                        p32 = jax.lax.with_sharding_constraint(p32, zsh)
                np_, ns_ = opt_update(p32, g32, opt_state[n], lr, **opt_kwargs)
                if fz is not None:
                    # updated flat master -> refresh the replicated
                    # logical compute-dtype copy (slice off the pad)
                    new_params[n] = np_[:fz['size']].reshape(
                        shapes[n]).astype(t_params[n].dtype)
                    new_master[n] = np_
                else:
                    new_params[n] = np_.astype(t_params[n].dtype)
                    if n in master_names:
                        new_master[n] = np_
                new_state[n] = ns_
            new_f = {n: aux.get(n, f_params[n]) for n in f_names}
            if guard_on:
                # non-finite guard fused into the pjit step: a bad step
                # writes back the OLD params/master/state/aux on device —
                # a no-op update inside the same XLA program, no host
                # round-trip on the happy path. The residual writeback
                # is gated too: a NaN residual must never outlive the
                # skipped step that produced it.
                new_params = {n: jnp.where(ok, new_params[n], t_params[n])
                              for n in t_names}
                new_master = {n: jnp.where(ok, new_master[n], master[n])
                              for n in new_master}
                new_state = {
                    n: tuple(jnp.where(ok, ns_, os_) for ns_, os_ in
                             zip(new_state[n], opt_state[n]))
                    for n in t_names}
                new_residual = {n: jnp.where(ok, nr, residual[n])
                                for n, nr in new_residual.items()}
                new_f = {n: jnp.where(ok, new_f[n], f_params[n])
                         for n in f_names}
                outs = (new_params, new_f, new_master, new_state,
                        new_residual, loss_val, ok)
            else:
                outs = (new_params, new_f, new_master, new_state,
                        new_residual, loss_val)
            if s_names:
                # per-table live-row counts as a last (replicated)
                # output — the telemetry side reads them one step
                # deferred, never stalling the dispatch
                outs = outs + (sparse_stats,)
            return outs
        # Name-stable jit boundary: the pytree dict keys of every param
        # container land in the lowered module's arg metadata and hence
        # the persistent XLA cache key. gluon's auto-naming counter
        # (bertforpretraining0_, ...3_, ...) would churn that key across
        # processes for structurally identical models, so each name is
        # aliased to a positional token derived from sorted order —
        # identical relative order for any two models differing only in
        # prefix — and the real names never cross into the traced
        # program. ``_alias_enc``/``_alias_dec`` translate at the call
        # site; the jitted function holds the reverse map in closure.
        alias = {n: f'p{i:04d}'
                 for i, n in enumerate(sorted(set(t_names) | set(f_names)))}
        rev = {t: n for n, t in alias.items()}
        self._alias, self._alias_rev = alias, rev

        def _enc(d):
            return {alias[n]: v for n, v in d.items()}

        def _dec(d):
            return {rev[t]: v for t, v in d.items()}

        def stable_step(t_params, f_params, master, opt_state, residual,
                        inputs, labels, key, lr, fault_scale):
            out = train_step(_dec(t_params), _dec(f_params), _dec(master),
                             _dec(opt_state), _dec(residual),
                             inputs, labels, key, lr, fault_scale)
            return tuple(_enc(o) if isinstance(o, dict) else o
                         for o in out)

        in_shardings = (_enc(t_shardings), _enc(f_shardings),
                        _enc(master_shardings), _enc(state_shardings),
                        _enc(residual_shardings),
                        tuple(batch_sh for _ in example_inputs),
                        tuple(batch_sh for _ in example_labels),
                        repl, repl, repl)
        out_shardings = (_enc(t_shardings), _enc(f_shardings),
                         _enc(master_shardings), _enc(state_shardings),
                         _enc(residual_shardings), repl)
        if guard_on:
            out_shardings = out_shardings + (repl,)
        if s_names:
            out_shardings = out_shardings + (
                {alias[n]: repl for n in s_names},)
        donate = (0, 2, 3, 4) if self.donate else ()
        self._compiled = jax.jit(stable_step, in_shardings=in_shardings,
                                 out_shardings=out_shardings,
                                 donate_argnums=donate)
        self._master_names = master_names
        self._master_shardings = master_shardings
        self._t_names = t_names
        self._f_names = f_names
        self._trainable = trainable
        self._frozen = frozen
        self._t_shardings = t_shardings
        self._f_shardings = f_shardings
        self._batch_sh = batch_sh
        self._zero_shardings = zero_shardings
        self._state_shardings = state_shardings
        self._flat_meta = flat_meta
        # Per-step collective accounting (mxnet_tpu_comm_* contract):
        # ring-algorithm wire bytes per device — all_reduce(N) costs
        # 2*(dp-1)/dp*N while reduce_scatter(N)+all_gather(N) cost
        # (dp-1)/dp*N each, so ZeRO-1 provably moves the SAME total as
        # the replicated path. ZeRO-3 is honestly MORE: each dim-sharded
        # param all-gathers twice per step (forward use + backward
        # regather under the remat policy) in the compute dtype, and its
        # fp32 grad reduce-scatters once; flat params reduce-scatter the
        # padded fp32 grad and gather the updated flat master back to
        # the replicated logical copy. Analytic (XLA does not expose
        # per-collective byte counters), recorded once per step in
        # __call__, per-layer in self._gather_plan.
        #
        # Hierarchy decomposition (H hosts x h devices, dp = H*h): the
        # GRADIENT exchange splits into an intra-host reduce-scatter
        # ((h-1)/h * N on the ICI hop) plus a cross-host all-reduce of
        # the 1/h partial (2*(H-1)/H * N/h on the DCN hop — the ONLY
        # cross-host traffic, and the hop the codec shrinks: its
        # operand is the encoded payload). Param writebacks/gathers
        # stay entirely on the intra hop because the ZeRO shard degree
        # is h (states replicate across hosts — ZeRO++-style hpZ).
        # `_comm_plan` keeps the kind-aggregated view (back-compat);
        # `_hop_plan` carries (kind, axis) for per-hop telemetry.
        dp = self._dp_size
        H, h = self._cross_size, self._shard_size
        hier = H > 1

        def _ring(k):
            return (k - 1) / k if k > 1 else 0.0

        ring = _ring(h) if hier else _ring(dp)   # the shard/param hop
        ring_h = _ring(H)
        intra_axis = self._shard_axis
        cross_axis = self._cross_axis or self.dp_axis
        plan = {}
        hop_plan = {}
        comp_raw = 0.0          # fp32 bytes the compressed hop replaces
        comp_enc = 0.0          # encoded bytes it actually carries

        def _add(kind, axis, nbytes, cnt):
            b, c = plan.get(kind, (0.0, 0))
            plan[kind] = (b + nbytes, c + cnt)
            b, c = hop_plan.get((kind, axis), (0.0, 0))
            hop_plan[(kind, axis)] = (b + nbytes, c + cnt)

        # RowSparse side ledger: per-hop sparse wire bytes and the
        # dense-equivalent bytes the same exchange would have moved —
        # the measurable shrink sparse_report()/dryrun assert on
        sparse_hop = {}
        sparse_dense_hop = {}

        def _sadd(axis, nbytes, dense_nbytes):
            sparse_hop[axis] = sparse_hop.get(axis, 0.0) + nbytes
            sparse_dense_hop[axis] = \
                sparse_dense_hop.get(axis, 0.0) + dense_nbytes

        param_nbytes = {}
        for n, p in trainable:
            size = int(onp.prod(p.data().shape)) if p.data().shape else 1
            nbytes = size * jnp.dtype(p.data()._data.dtype).itemsize
            param_nbytes[n] = nbytes
            fz = flat_meta.get(n)
            enc = _compression.wire_bytes(
                shapes[n] if fz is None else (fz['padded'],),
                ctype, cblock) if comp_on else None
            if stage3 and n in gather_ns:
                _add('all_gather', intra_axis, 2 * ring * nbytes, 2)
                grad_raw = size * 4
            elif fz is not None:
                _add('all_gather', intra_axis, ring * fz['padded'] * 4, 1)
                grad_raw = fz['padded'] * 4
            elif zero_specs[n] is not None:
                _add('all_gather', intra_axis, ring * nbytes, 1)
                grad_raw = nbytes
            elif dp > 1:
                grad_raw = nbytes
            else:
                continue
            # the gradient exchange itself
            if n in s_names:
                # RowSparse exchange: the wire carries (int32 ids +
                # row-block values) instead of the table-shaped grad —
                # exchange bytes scale with the live-row budget, not the
                # vocab. Exact mode densifies LOCALLY after the row
                # exchange, so the wire shrink holds for both modes;
                # only the lazy codec re-encodes the rows (per-row
                # scales, block = dim) for the cross-host hop.
                B = sum(sparse_budgets[n])
                dim = shapes[n][1]
                row_raw = B * (dim * 4 + 4)
                row_enc = (_compression.wire_bytes((B, dim), ctype, dim)
                           + B * 4) if comp_on and not sparse_exact \
                    else row_raw
                if hier:
                    if h > 1:
                        _add('reduce_scatter', intra_axis,
                             ring * row_raw, 1)
                        _sadd(intra_axis, ring * row_raw,
                              ring * grad_raw)
                    cross_enc = 2 * ring_h * row_enc / h
                    _add('all_reduce', cross_axis, cross_enc, 1)
                    _sadd(cross_axis, cross_enc,
                          2 * ring_h * (enc if comp_on else grad_raw)
                          / h)
                    comp_raw += 2 * ring_h * row_raw / h
                    comp_enc += cross_enc
                else:
                    _add('all_reduce', intra_axis, 2 * ring * row_enc, 1)
                    _sadd(intra_axis, 2 * ring * row_enc,
                          2 * ring * (enc if comp_on else grad_raw))
                    comp_raw += 2 * ring * row_raw
                    comp_enc += 2 * ring * row_enc
            elif hier:
                if h > 1:
                    _add('reduce_scatter', intra_axis, ring * grad_raw, 1)
                cross_raw = 2 * ring_h * grad_raw / h
                cross_enc = 2 * ring_h * (enc if comp_on else grad_raw) / h
                _add('all_reduce', cross_axis, cross_enc, 1)
                comp_raw += cross_raw
                comp_enc += cross_enc
            elif zero_specs[n] is not None or fz is not None \
                    or (stage3 and n in gather_ns):
                wire = enc if comp_on else grad_raw
                _add('reduce_scatter', intra_axis, ring * wire, 1)
                comp_raw += ring * grad_raw
                comp_enc += ring * wire
            else:
                wire = enc if comp_on else grad_raw
                _add('all_reduce', intra_axis, 2 * ring * wire, 1)
                comp_raw += 2 * ring * grad_raw
                comp_enc += 2 * ring * wire
        # table-axis feature exchange (model-parallel tables): the
        # forward gathers remote rows and the backward scatters their
        # updates — one all-to-all pair per step, bytes proportional to
        # the live-row budget in the compute dtype (+ the id vector)
        for n in sparse_table_sharded:
            tsize = int(dict(zip(self.mesh.axis_names,
                                 self.mesh.devices.shape))[table_axis])
            B = sum(sparse_budgets[n])
            dim = shapes[n][1]
            itemsize = jnp.dtype(
                name_to_p[n].data()._data.dtype).itemsize
            a2a = 2 * _ring(tsize) * B * (dim * itemsize + 4)
            _add('all_to_all', table_axis, a2a, 2)
            _sadd(table_axis, a2a, a2a)
        self._comm_plan = plan
        self._hop_plan = hop_plan
        self._sparse_hop = sparse_hop
        self._sparse_dense_hop = sparse_dense_hop
        self._comp_plan = {
            'codec': ctype, 'raw_bytes': comp_raw, 'encoded_bytes':
            comp_enc, 'axis': cross_axis if hier else intra_axis,
        } if comp_on else None
        # per-layer gather bytes (zero3): [(layer, bytes/step, gathers)]
        self._gather_plan = [
            (gname, 2 * ring * sum(param_nbytes[n] for n in names), 2)
            for gname, names in layer_groups]

    # ------------------------------------------------------------------
    def init(self, *example_inputs):
        """Force parameter init (deferred shapes) by one eager forward."""
        rec = _flags.is_recording
        _flags.is_recording = False
        try:
            self.block(*example_inputs)
        finally:
            _flags.is_recording = rec

    def _alias_enc(self, d):
        """Real-name dict -> positional-token dict (the compiled step's
        name-stable pytree keys; see the aliasing note in _build)."""
        a = self._alias
        return {a[n]: v for n, v in d.items()}

    def _alias_dec(self, d):
        """Positional-token dict -> real-name dict."""
        r = self._alias_rev
        return {r[t]: v for t, v in d.items()}

    def _build_signature(self, in_datas, lab_datas):
        """Structured compile-ledger signature of the step program:
        per-batch-arg shape/dtype (+ the dp batch sharding) and the flag
        knobs that change the compiled HLO — ZeRO stage, compression
        codec, guard, donation, mesh layout, parameter count."""
        batch_spec = None
        try:
            batch_spec = str(getattr(self._batch_sh, 'spec',
                                     self._batch_sh))
        except Exception:
            pass
        args = [_compile.arg_sig(f'data{i}', x.shape, x.dtype,
                                 sharding=batch_spec,
                                 donated=False)
                for i, x in enumerate(in_datas)]
        args += [_compile.arg_sig(f'label{i}', x.shape, x.dtype,
                                  sharding=batch_spec, donated=False)
                 for i, x in enumerate(lab_datas)]
        try:
            mesh_shape = {str(k): int(v)
                          for k, v in dict(self.mesh.shape).items()}
        except Exception:
            mesh_shape = None
        from ..ops import autotune as _autotune
        return _compile.signature(args=args, flags={
            'zero': self._zero_label,
            'codec': self.compression['type']
            if self.compression is not None else None,
            'guard': self._guard is not None,
            'donate': bool(self.donate),
            'params': len(self._t_names or ()) + len(self._f_names or ()),
            'mesh': mesh_shape,
            'remat': self._remat_policy,
            # RowSparse fast path (ISSUE 19): mode + per-table row
            # budgets — a batch-shape change that moves a budget is a
            # legitimate recompile, and the ledger should say why
            'sparse': getattr(self, '_sparse_sig', None),
            # kernel block shapes the Pallas calls in this program
            # resolved to (env/db/default) — ISSUE 18: a DB-sourced
            # shape change is then a visible churn axis in the ledger,
            # not a silent recompile
            'autotune': _autotune.decision_flags() or None,
        })

    def __call__(self, inputs, labels, lr=None):
        cctx = None
        try:
            with _trace.span('step.dispatch', step=self._step_count):
                if self._compiled is None:
                    # compile ledger: everything from here to the first
                    # dispatch (where jit lazily lowers and
                    # backend-compiles) is compile time, and a stall
                    # anywhere inside the window classifies as COMPILING
                    # in the watchdog's stall verdict. Opened INSIDE the
                    # step.dispatch span: both sides end in-span, and a
                    # window straddling the span boundary corrupts the
                    # chrome B/E nesting.
                    cctx = _compile.begin('step:train_step')
                return self._call_traced(inputs, labels, lr, cctx)
        except BaseException:
            _compile.abort(cctx)
            raise

    def _call_traced(self, inputs, labels, lr=None, cctx=None):
        if self._guard is not None:
            # deferred read of the previous step's finiteness flag; a
            # rollback restores params/states/RNG and the post-restore
            # hook re-places them on the mesh — the CURRENT batch then
            # trains against the restored weights (fwd+bwd happen below,
            # after the restore, so nothing here is stale)
            self._guard.pre_step()
        fault = _faults.fire('step.dispatch')
        if self._spans_processes:
            # a process-spanning step IS a collective: once the
            # membership side channel has declared a peer lost, entering
            # it would wedge this process forever — fail fast instead
            # (ElasticController.pre_step turns the same signal into
            # commit + re-form before dispatch ever gets here)
            from ..resilience.elastic import raise_if_peer_lost
            raise_if_peer_lost()
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        if not isinstance(labels, (list, tuple)):
            labels = [labels]
        in_datas = tuple(x._data if isinstance(x, NDArray) else x
                         for x in inputs)
        lab_datas = tuple(x._data if isinstance(x, NDArray) else x
                          for x in labels)
        # 1.0 on normal steps (exact-identity multiply on the loss); an
        # injected step.dispatch:nan flips it to NaN inside the compiled
        # step, so loss AND every gradient go non-finite even for
        # int-input models (BERT token ids)
        fault_scale = jnp.asarray(
            float('nan') if fault == 'nan' else 1.0, jnp.float32)
        if self._compiled is None:
            trainable, frozen = self._collect()
            if not trainable and not frozen:
                self.init(*inputs)
                trainable, frozen = self._collect()
            if any(p._data is None for _, p in trainable + frozen):
                self.init(*inputs)
            with _trace.span('optimizer.state_init'):
                self._opt_state = {
                    n: self._opt_init(p.data()._data.astype(jnp.float32))
                    for n, p in trainable}
            self._build(in_datas, lab_datas)
            if cctx is not None:
                _compile.set_signature(
                    cctx, self._build_signature(in_datas, lab_datas))
            # place params on the mesh with their shardings
            with _trace.span('h2d.param_place'), \
                    _memory.oom_guard('h2d.param_place'):
                for n, p in self._trainable:
                    p._data[0]._data = _put_replicated(
                        p.data()._data, self._t_shardings[n])
                for n, p in self._frozen:
                    p._data[0]._data = _put_replicated(
                        p.data()._data, self._f_shardings[n])
                self._master = {
                    n: _put_replicated(
                        self._master_host(n, p.data()._data),
                        self._master_shardings[n])
                    for n, p in self._trainable
                    if n in self._master_names}
                self._opt_state = {
                    n: tuple(_put_replicated(s, sh) for s, sh in
                             zip(self._opt_state[n],
                                 self._state_shardings[n]))
                    for n in self._t_names}
                # error-feedback residuals seed to zero (a restore may
                # overwrite them from the states payload just below)
                self._residual = {
                    n: _put_replicated(
                        onp.zeros(self._residual_shapes[n], onp.float32),
                        self._residual_shardings[n])
                    for n in self._residual_shapes}
            if self._pending_states is not None:
                doc, self._pending_states = self._pending_states, None
                self._apply_states(doc)
            # memory observability: this step's live arrays (params /
            # masters+moments / residuals) become tracked pools for the
            # fallback watermark, and its memory_analysis() feeds the
            # OOM post-mortem's bucket table. Weakly referenced — a
            # rebuilt/dropped step never double-counts or pins arrays.
            _memory.register_provider(self)
            _memory.set_analysis_provider(self.memory_analysis,
                                          owner=self)
            if _telem['on']:
                from .. import telemetry as _telemetry
                _telemetry.set_gauge(
                    'mxnet_tpu_comm_opt_state_bytes_per_device',
                    self.opt_state_bytes_per_device())
                _telemetry.set_gauge(
                    'mxnet_tpu_comm_param_bytes_per_device',
                    self.param_bytes_per_device())
                if self.compression is not None:
                    _telemetry.set_gauge(
                        'mxnet_tpu_comm_residual_bytes_per_device',
                        self.residual_bytes_per_device())
                    cp = self._comp_plan
                    if cp and cp['encoded_bytes']:
                        _telemetry.set_gauge(
                            'mxnet_tpu_comm_compression_ratio',
                            cp['raw_bytes'] / cp['encoded_bytes'])

        t_params = self._alias_enc(
            {n: p.data()._data for n, p in self._trainable})
        f_params = self._alias_enc(
            {n: p.data()._data for n, p in self._frozen})
        master = self._alias_enc(self._master)
        opt_state = self._alias_enc(self._opt_state)
        residual = self._alias_enc(self._residual)
        key = _random.next_key()
        lr_val = jnp.asarray(lr if lr is not None else self.lr, jnp.float32)
        with _trace.span('h2d.batch_put'), \
                _memory.oom_guard('h2d.batch_put'):
            in_datas = tuple(_put_batch(x, self._batch_sh)
                             for x in in_datas)
            lab_datas = tuple(_put_batch(x, self._batch_sh)
                              for x in lab_datas)
        if self._cost_args is None:
            # abstract avals of one step call, kept for cost_analysis()
            self._cost_args = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(jnp.shape(x),
                                               jnp.result_type(x)),
                (t_params, f_params, master, opt_state, residual,
                 in_datas, lab_datas, key, lr_val, fault_scale))
        with _trace.span('step.compiled'), \
                _memory.oom_guard('step.dispatch'):
            out = self._compiled(
                t_params, f_params, master, opt_state, residual,
                in_datas, lab_datas, key, lr_val, fault_scale)
        if cctx is not None:
            # the first dispatch returned: XLA's lower + backend compile
            # are done. Re-stamp the signature first: the lazy trace ran
            # inside the dispatch above, so any Pallas block-size
            # decisions (autotune.resolve) only exist NOW — the pre-trace
            # stamp in the build branch had 'autotune': None.
            _compile.set_signature(
                cctx, self._build_signature(in_datas, lab_datas))
            _compile.end(cctx)
        sparse_stats = None
        if self._sparse_names:
            sparse_stats = self._alias_dec(out[-1])
            out = out[:-1]
        if self._guard is not None:
            new_t, new_f, new_master, new_state, new_residual, loss, ok \
                = out
            self._guard.push_flag(ok)
        else:
            new_t, new_f, new_master, new_state, new_residual, loss = out
        new_t, new_f = self._alias_dec(new_t), self._alias_dec(new_f)
        new_master = self._alias_dec(new_master)
        new_state = self._alias_dec(new_state)
        new_residual = self._alias_dec(new_residual)
        with _trace.span('step.gather'):
            # donate/gather bookkeeping: swap the donated buffers'
            # NDArray views to the program's outputs (host pointer
            # swaps; the all-gather itself ran inside the program)
            for n, p in self._trainable:
                p.data()._data = new_t[n]
            for n, p in self._frozen:
                p.data()._data = new_f[n]
            self._master = new_master
            self._opt_state = new_state
            self._residual = new_residual
        self._step_count += 1
        if self._comm_plan and _trace.enabled():
            # the collectives run INSIDE the compiled program — annotate
            # the trace with the analytic ring-wire plan per step; the
            # stage label separates the zero1 writeback gather from the
            # zero3 per-layer on-use gathers, the axis label separates
            # the intra-host (ici) hop from the cross-host (dcn) hop
            # under the hierarchical decomposition
            for (kind, axis), (nbytes, count) in self._hop_plan.items():
                _trace.instant(f'comm.{kind}', bytes=int(nbytes),
                               count=count, axis=axis,
                               stage=self._zero_label)
            for layer, nbytes, count in self._gather_plan:
                _trace.instant('comm.all_gather', bytes=int(nbytes),
                               count=count, axis=self._shard_axis,
                               stage=self._zero_label, layer=layer)
            if self._comp_plan is not None:
                _trace.instant('comm.compress',
                               bytes=int(self._comp_plan['encoded_bytes']),
                               codec=self._comp_plan['codec'],
                               axis=self._comp_plan['axis'])
                _trace.instant('comm.decompress',
                               bytes=int(self._comp_plan['raw_bytes']),
                               codec=self._comp_plan['codec'],
                               axis=self._comp_plan['axis'])
        if _telem['on'] and self._comm_plan:
            from .. import telemetry as _telemetry
            for (kind, axis), (nbytes, count) in self._hop_plan.items():
                _telemetry.counter(
                    'mxnet_tpu_comm_collective_bytes_total').inc(
                        nbytes, kind=kind, axis=axis,
                        stage=self._zero_label)
                _telemetry.counter('mxnet_tpu_comm_collectives_total').inc(
                    count, kind=kind, axis=axis,
                    stage=self._zero_label)
            if self._comp_plan is not None:
                _telemetry.counter(
                    'mxnet_tpu_comm_compressed_bytes_total').inc(
                        self._comp_plan['encoded_bytes'],
                        codec=self._comp_plan['codec'],
                        axis=self._comp_plan['axis'])
        if sparse_stats is not None:
            prev_stats = self._sparse_prev_stats
            self._sparse_prev_stats = sparse_stats
            if _trace.enabled():
                for axis, nbytes in (self._sparse_hop or {}).items():
                    _trace.instant('sparse.exchange', bytes=int(nbytes),
                                   axis=axis,
                                   tables=len(self._sparse_names))
                _trace.instant(
                    'optimizer.sparse_update',
                    mode='exact' if self._sparse_exact else 'lazy',
                    tables=len(self._sparse_names))
            if _telem['on']:
                from .. import telemetry as _telemetry
                for axis, nbytes in (self._sparse_hop or {}).items():
                    _telemetry.counter(
                        'mxnet_tpu_sparse_exchange_bytes_total').inc(
                            nbytes, axis=axis)
                if prev_stats is not None:
                    for n, v in prev_stats.items():
                        # one-step-deferred host read: the PREVIOUS
                        # step's scalar has already materialized, so
                        # this never stalls the step just dispatched
                        live = int(v)
                        dim = self._shapes[n][1]
                        _telemetry.set_gauge(
                            'mxnet_tpu_sparse_live_rows', live, table=n)
                        _telemetry.counter(
                            'mxnet_tpu_sparse_row_bytes_total').inc(
                                live * dim * 4, table=n)
                        ids = self._sparse_id_counts.get(n, 0)
                        if live:
                            _telemetry.set_gauge(
                                'mxnet_tpu_sparse_dedup_ratio',
                                ids / live, table=n)
        loss_nd = NDArray(_local_value(loss))
        _memory.on_step(self._step_count)
        _flight.record_step(self._step_count, loss=loss_nd)
        return loss_nd

    def reset_mesh(self, mesh=None):
        """Adopt a NEW mesh (the elastic re-form path: the survivor
        world's device set after a peer loss, or any deliberate
        resize). Drops the compiled program, shardings and ZeRO layout
        — all rebuilt at the new dp degree on the next ``__call__`` —
        while carrying the training state across:

        - parameters gather to host (when addressable) and re-place
          with the new shardings at the next step;
        - optimizer state + fp32 masters ride the layout-independent
          ``get_states_bytes`` payload (the same contract checkpoints
          use), so dp=N ZeRO shards re-scatter as dp=M — or fully
          replicated — without precision loss;
        - when the old world's arrays are no longer addressable (their
          processes are gone), state is simply dropped: the caller
          restores the committed checkpoint right after, which is the
          elastic contract's source of truth anyway.
        """
        states = None
        if self._compiled is not None:
            try:
                states = self.get_states_bytes()
            except Exception:
                states = None   # unaddressable shards: restore supplies
            for _n, p in self._trainable + self._frozen:
                d = p.data()._data
                if getattr(d, 'is_fully_addressable', True):
                    p.data()._data = jnp.asarray(onp.asarray(d))
        # re-derive the hierarchy at the new world (survivor topologies
        # may have lost a whole host group)
        self._adopt_mesh(mesh if mesh is not None else default_mesh())
        self.zero_stage = self._requested_stage if self._dp_size > 1 else 0
        self.zero = self.zero_stage > 0
        self._spans_processes = self._mesh_spans_processes()
        self._compiled = None
        self._cost_args = None
        self._master = None
        self._opt_state = None
        self._residual = None
        self._pending_states = None
        if states is not None:
            self.set_states_bytes(states)
        return self

    def _replace_params_on_mesh(self):
        """After an external restore wrote host arrays into the
        parameters (NonFiniteGuard rollback via CheckpointManager), put
        them back on the mesh with the step's shardings — the compiled
        step cannot consume cpu-committed arrays."""
        if self._compiled is None:
            return
        with _memory.oom_guard('checkpoint.restore'):
            for n, p in self._trainable:
                p._data[0]._data = _put_replicated(
                    onp.asarray(p.data()._data), self._t_shardings[n])
            for n, p in self._frozen:
                p._data[0]._data = _put_replicated(
                    onp.asarray(p.data()._data), self._f_shardings[n])

    # ------------------------------------------------------------------
    # optimizer-state introspection + layout-independent checkpointing
    # ------------------------------------------------------------------
    def cost_analysis(self):
        """{'flops', 'bytes'} of ONE compiled step from XLA's own
        cost_analysis — the deterministic device-side half of the
        per-step attribution report (telemetry.attribution joins it
        with the measured wall-time spans). Lowers/compiles the step
        once more from stored avals (cached by the persistent
        compilation cache when enabled); None before the first step or
        when the backend exposes no cost model."""
        if self._compiled is None or self._cost_args is None:
            return None
        from ..telemetry import attribution as _attribution
        try:
            compiled = self._compiled.lower(*self._cost_args).compile()
        except Exception:
            return None
        return _attribution.xla_cost(compiled)

    def memory_pools(self):
        """This step's live persistent arrays as named residency pools
        for ``telemetry.memory``'s fallback watermark:
        ``{'params', 'optimizer_state', 'residuals'} ->
        {array_name: jax array}``. Per-device byte accounting happens in
        the memory module (``entry_nbytes`` — the local shard for
        sharded arrays, so ZeRO residency is *measured*, not derived)."""
        pools = {'params': {}, 'optimizer_state': {}, 'residuals': {}}
        for n, p in (self._trainable or []) + (self._frozen or []):
            if p._data is not None:
                pools['params'][n] = p.data()._data
        for n, m in (self._master or {}).items():
            pools['optimizer_state'][f'master/{n}'] = m
        for n, st in (self._opt_state or {}).items():
            for i, s in enumerate(st):
                pools['optimizer_state'][f'moment{i}/{n}'] = s
        for n, r in (self._residual or {}).items():
            pools['residuals'][n] = r
        return pools

    def memory_analysis(self, peak_bytes=None):
        """Per-device memory attribution — the ``cost_analysis()``
        sibling (ISSUE 14). Joins the measured residency pools (local
        shard bytes of every live param/master/moment/residual), the
        ZeRO-3 per-layer layout + gather-plan accounting, and XLA's own
        compiled-program memory analysis into a bucket table

            params / optimizer_state / residuals / io_leases /
            activations_temp

        whose sum reconstructs the measured peak by construction:
        ``activations_temp`` is the explicit residual (peak minus the
        tracked persistent buckets), exactly how the wall-time report
        defines ``compute`` — with ``measured_fraction`` stating how
        much of the peak the tracked pools explain. ``peak_bytes``
        defaults to the backend allocator's peak where exposed, else
        the fallback watermark high-water mark (so on CPU the table is
        still honest: the residual is then ~0 and the buckets ARE the
        measurement). None before the first step."""
        if self._compiled is None:
            return None
        pools = self.memory_pools()
        buckets = {
            'params': _memory.pool_nbytes(pools.get('params')),
            'optimizer_state':
                _memory.pool_nbytes(pools.get('optimizer_state')),
            'residuals': _memory.pool_nbytes(pools.get('residuals')),
            'io_leases': _memory.pool_bytes_by_name('io_leases'),
        }
        persistent = sum(buckets.values())
        source = 'fallback'
        if peak_bytes is None:
            stats = _memory.device_memory_stats()
            if stats is not None and stats.get('peak_bytes_in_use'):
                peak_bytes = int(stats['peak_bytes_in_use'])
                source = 'memory_stats'
            else:
                peak_bytes = max(_memory.peak_bytes(), persistent)
        peak_bytes = max(int(peak_bytes), persistent)
        buckets['activations_temp'] = peak_bytes - persistent
        # per-layer persistent residency: the same layer grouping the
        # ZeRO-3 gather pipeline schedules by, summed over the layer's
        # params + masters + moments + residuals (per-device bytes) —
        # with the analytic gather wire plan alongside so the
        # remat-policy sweep can weigh persistent vs transient per layer
        per_layer = {}
        by_param = {}
        for pool in pools.values():
            for aname, arr in pool.items():
                pname = aname.split('/', 1)[-1]
                by_param[pname] = by_param.get(pname, 0) \
                    + _memory.entry_nbytes(arr)
        for gname, names in group_params_by_layer(self._t_names or []):
            per_layer[gname] = sum(by_param.get(n, 0) for n in names)
        self.opt_state_bytes_per_device()       # refreshes pad bytes
        out = {
            'peak_bytes_per_device': peak_bytes,
            'source': source,
            'buckets_bytes': buckets,
            'bucket_fractions': {
                k: round(v / peak_bytes, 4) if peak_bytes else 0.0
                for k, v in buckets.items()},
            'bucket_sum_over_peak':
                round(sum(buckets.values()) / peak_bytes, 4)
                if peak_bytes else 0.0,
            'measured_fraction':
                round(min(persistent, peak_bytes) / peak_bytes, 4)
                if peak_bytes else 0.0,
            'zero_stage': self.zero_stage,
            'dp': self._dp_size,
            'compression': self.compression['type']
            if self.compression else None,
            'pad_bytes': getattr(self, 'opt_state_pad_bytes', 0),
            'per_layer_bytes': per_layer,
            'host_rss_bytes': _memory.host_rss_bytes(),
        }
        if getattr(self, '_gather_plan', None):
            out['gather_bytes_per_layer'] = {
                str(layer): int(nbytes)
                for layer, nbytes, _c in self._gather_plan}
        xla = self._xla_memory_analysis()
        if xla:
            out['xla'] = xla
        return out

    def _xla_memory_analysis(self):
        """XLA's CompiledMemoryStats for one step program (argument /
        output / temp / generated-code / alias bytes), or None where
        the backend exposes none — reported alongside the measured
        buckets, never substituted for them."""
        if self._compiled is None or self._cost_args is None:
            return None
        try:
            compiled = self._compiled.lower(*self._cost_args).compile()
            ma = compiled.memory_analysis()
        except Exception:
            return None
        out = {}
        for k in ('argument_size_in_bytes', 'output_size_in_bytes',
                  'temp_size_in_bytes', 'alias_size_in_bytes',
                  'generated_code_size_in_bytes'):
            v = getattr(ma, k, None)
            if v is not None:
                out[k] = int(v)
        return out or None

    def _master_host(self, n, arr):
        """Host-side fp32 master for param ``n`` in its PERSISTENT
        layout: logical shape, or flattened + zero-padded to the dp
        multiple for ZeRO-3 flat params."""
        # lint: host-sync-ok master seeding runs once at build/restore, not in the step loop
        a = onp.asarray(arr, onp.float32)
        fz = getattr(self, '_flat_meta', {}).get(n)
        if fz is not None:
            a = onp.pad(a.reshape(-1), (0, fz['pad']))
        return a

    def _leaf_to_logical(self, n, a):
        """Un-flatten a ZeRO-3 flat master/moment back to the param's
        logical shape for the layout-independent states payload."""
        a = onp.asarray(a)
        fz = getattr(self, '_flat_meta', {}).get(n)
        if fz is not None and a.ndim == 1 and a.shape[0] == fz['padded']:
            a = a[:fz['size']].reshape(self._shapes[n])
        return a

    def _leaf_from_logical(self, n, a):
        """Flatten+pad a logical-shape restored master/moment into this
        step's ZeRO-3 flat layout (identity elsewhere, and for the
        shape-() step counters)."""
        a = onp.asarray(a)  # lint: host-sync-ok checkpoint-restore path, not the step loop
        fz = getattr(self, '_flat_meta', {}).get(n)
        if fz is not None and a.shape == self._shapes[n]:
            a = onp.pad(a.reshape(-1).astype(onp.float32, copy=False),
                        (0, fz['pad']))
        return a

    def opt_state_bytes_per_device(self):
        """Bytes of optimizer state (masters + moments) ONE device holds
        — physical ``addressable_shards`` bytes, so ZeRO-3 flat pad
        bytes are included (the per-param breakdown is on
        ``self.opt_state_pad_bytes`` after the first step). Under ZeRO
        this is ~1/dp of the replicated footprint (± the tensors too
        small to shard)."""
        total = 0
        for st in (self._opt_state or {}).values():
            for s in st:
                total += device_nbytes(s)
        for m in (self._master or {}).values():
            total += device_nbytes(m)
        # pad-to-divisible slack of the zero3 flat stores, per device:
        # pad elements * fp32 * (1 master + moment leaves) / dp
        pad = 0
        for n, fz in getattr(self, '_flat_meta', {}).items():
            leaves = 1 + sum(1 for s in self._opt_state[n] if s.ndim)
            pad += fz['pad'] * 4 * leaves // self._dp_size
        self.opt_state_pad_bytes = pad
        return total

    def param_bytes_per_device(self):
        """Bytes of the persistent parameters (trainable + frozen, in
        compute dtype) ONE device holds — under ZeRO-3 the dim-sharded
        params count their 1/dp shard. Masters are accounted by
        ``opt_state_bytes_per_device``; the two sum to the persistent
        model footprint per device."""
        total = 0
        for _n, p in (self._trainable or []) + (self._frozen or []):
            total += device_nbytes(p.data()._data)
        return total

    def gather_bytes_per_step(self):
        """Total analytic ring-wire bytes of the ZeRO-3 per-layer
        param gathers ONE step moves (sum of ``self._gather_plan``;
        0 outside stage 3)."""
        return int(sum(b for _l, b, _c in
                       getattr(self, '_gather_plan', None) or []))

    def residual_bytes_per_device(self):
        """Bytes of error-feedback compression residual ONE device
        holds (0 with compression off). Sharded with the grad layout,
        so ~1/shard-degree of the fp32 gradient footprint."""
        total = 0
        for r in (self._residual or {}).values():
            total += device_nbytes(r)
        return total

    def comm_bytes_per_hop(self):
        """Analytic ring-wire bytes ONE step moves, by mesh hop:
        ``{axis: bytes}``. Flat topologies report one ``dp`` hop;
        hierarchical ones separate the intra-host (``<dp>i``, ICI) hop
        from the cross-host (``<dp>h``, DCN) hop — the latter carries
        the encoded payload under compression, which is the measurable
        wire win."""
        hops = {}
        for (_kind, axis), (nbytes, _c) in \
                (getattr(self, '_hop_plan', None) or {}).items():
            hops[axis] = hops.get(axis, 0) + int(nbytes)
        return hops

    def compression_report(self):
        """{'codec', 'raw_bytes_per_step', 'encoded_bytes_per_step',
        'ratio', 'hierarchy', 'residual_bytes_per_device'} of the
        compressed gradient exchange — None with compression off."""
        cp = getattr(self, '_comp_plan', None)
        if cp is None:
            return None
        return {
            'codec': cp['codec'],
            'raw_bytes_per_step': int(cp['raw_bytes']),
            'encoded_bytes_per_step': int(cp['encoded_bytes']),
            'ratio': cp['raw_bytes'] / max(1.0, cp['encoded_bytes']),
            'axis': cp['axis'],
            'hierarchy': (self._cross_size, self._shard_size),
            'residual_bytes_per_device': self.residual_bytes_per_device(),
        }

    def sparse_layout(self):
        """RowSparse layout description for the checkpoint manifest
        (``optimizer_state_layout.sparse``): update mode, table-shard
        axis and per-table (vocab, dim, live-row budget). None before
        the first build or when no table took the sparse path. The
        state tensors themselves stay table-shaped (lazy updates touch
        rows in place), so dense<->sparse and dp=N<->dp=M restores need
        no layout conversion — this record is provenance, not a
        decoder requirement."""
        if not getattr(self, '_sparse_names', None):
            return None
        return {
            'mode': 'exact' if self._sparse_exact else 'lazy',
            'table_axis': self._sparse_table_axis,
            'tables': {n: {'vocab': int(self._shapes[n][0]),
                           'dim': int(self._shapes[n][1]),
                           'budget': int(sum(self._sparse_budgets[n])),
                           'ids_per_step':
                               int(self._sparse_id_counts.get(n, 0))}
                       for n in self._sparse_names},
        }

    def sparse_report(self):
        """Analytic per-step cost of the RowSparse fast path vs the
        dense path it replaced — None when no table took it.

        - ``update_bytes_per_step``: optimizer-touched bytes (param +
          fp32 master + vector moments rows) across sparse tables;
          lazy mode scales with the live-row budget, exact mode is
          honestly dense (it densifies before the kernel).
        - ``exchange_bytes_per_hop``: analytic ring-wire bytes of the
          row-block gradient exchange by mesh hop, with the
          dense-equivalent bytes the same hop would have moved.
        """
        if not getattr(self, '_sparse_names', None):
            return None
        tables = {}
        upd = dense_upd = 0
        for n in self._sparse_names:
            vocab, dim = self._shapes[n]
            budget = min(int(sum(self._sparse_budgets[n])), int(vocab))
            leaves = 1 + sum(
                1 for s in self._opt_state[n] if getattr(s, 'ndim', 0))
            if n in self._master_names:
                leaves += 1
            per_row = dim * 4 * leaves
            touched = vocab if self._sparse_exact else budget
            tables[n] = {'vocab': int(vocab), 'dim': int(dim),
                         'budget': budget,
                         'update_bytes': touched * per_row,
                         'dense_update_bytes': int(vocab) * per_row}
            upd += touched * per_row
            dense_upd += int(vocab) * per_row
        hops = {axis: {'bytes': int(b),
                       'dense_bytes':
                           int(self._sparse_dense_hop.get(axis, 0))}
                for axis, b in (self._sparse_hop or {}).items()}
        return {
            'mode': 'exact' if self._sparse_exact else 'lazy',
            'table_axis': self._sparse_table_axis,
            'tables': tables,
            'update_bytes_per_step': int(upd),
            'dense_update_bytes_per_step': int(dense_upd),
            'update_shrink': dense_upd / max(1, upd),
            'exchange_bytes_per_hop': hops,
        }

    def get_states_bytes(self):
        """Optimizer state as a layout-independent bytes payload: every
        shard is gathered to host fp32 numpy, so a checkpoint written at
        one dp degree (or under ZeRO) restores at any other — the same
        contract as gluon.Trainer.get_states_bytes, and what
        checkpoint.CheckpointManager snapshots when bound as `trainer=`."""
        import pickle
        if self._compiled is None:
            if self._pending_states is not None:
                # resumed but not yet stepped (e.g. a preemption save in
                # the restore->first-step window): the restored payload
                # IS the current state — hand it back unchanged
                return pickle.dumps(self._pending_states)
            raise MXNetError("get_states_bytes: no optimizer state yet — "
                             "run at least one step first")
        # every leaf gathers to host in LOGICAL shape (zero3 flat
        # stores un-flatten), so the payload restores at any dp/stage
        states = {n: tuple(self._leaf_to_logical(n, s) for s in st)
                  for n, st in self._opt_state.items()}
        master = {n: self._leaf_to_logical(n, m)
                  for n, m in self._master.items()}
        doc = {
            'format': 'sharded_train_step_v1',
            'opt_state': states, 'master': master,
            'step_count': self._step_count,
            'zero': self.zero, 'stage': self.zero_stage,
            'dp': self._dp_size}
        if self._residual:
            # error-feedback residuals ride the layout-independent
            # payload in LOGICAL shape (flat stores un-flatten), so a
            # compressed run restores its exact error state at any dp
            # degree; an uncompressed restore target simply drops them
            doc['residual'] = {n: self._leaf_to_logical(n, r)
                               for n, r in self._residual.items()}
            doc['compression'] = dict(self.compression)
        sp = self.sparse_layout()
        if sp is not None:
            # provenance only: sparse state tensors are table-shaped,
            # so restore needs no conversion in either direction
            doc['sparse'] = sp
        return pickle.dumps(doc)

    def set_states_bytes(self, blob):
        """Restore a get_states_bytes() payload, scattering each tensor
        into THIS step's current layout (replicated, tp, or ZeRO 1/dp —
        the saved layout does not have to match)."""
        import pickle
        doc = pickle.loads(blob)
        if doc.get('format') != 'sharded_train_step_v1':
            raise MXNetError(
                f"set_states_bytes: not a ShardedTrainStep payload "
                f"(format={doc.get('format')!r})")
        if self._compiled is None:
            self._pending_states = doc   # applied right after first build
            return
        self._apply_states(doc)

    def _apply_states(self, doc):
        # restore re-place is a burst of device allocations over a
        # device already holding the pre-restore state — an OOM here
        # must leave the same forensics as one mid-step
        with _memory.oom_guard('checkpoint.restore'):
            self._apply_states_guarded(doc)

    def _apply_states_guarded(self, doc):
        for n, st in doc['opt_state'].items():
            if n not in self._opt_state:
                raise MXNetError(f"set_states_bytes: unknown parameter "
                                 f"{n!r} in restored optimizer state")
            self._opt_state[n] = tuple(
                _put_replicated(self._leaf_from_logical(n, s), sh)
                for s, sh in zip(st, self._state_shardings[n]))
        restored_master = doc.get('master', {})
        for n, m in restored_master.items():
            if n in self._master_names:
                self._master[n] = _put_replicated(
                    self._leaf_from_logical(n, m),
                    self._master_shardings[n])
        # zero3 flat masters with no saved counterpart (payload written
        # under zero off/1, where the param carried the value itself):
        # reseed from the CURRENT param so the flat store matches the
        # restored weights instead of keeping a pre-restore value
        for n, p in self._trainable or []:
            if n in self._flat_meta and n not in restored_master \
                    and n in self._master_names:
                self._master[n] = _put_replicated(
                    # lint: host-sync-ok restore-time reseed, runs once per restore
                    self._master_host(n, onp.asarray(p.data()._data)),
                    self._master_shardings[n])
        # error-feedback residuals: restored when the payload carries
        # them (scattered into THIS step's layout), deterministically
        # reseeded to zero otherwise (a payload saved without
        # compression has no error state to carry — documented
        # trajectory note in README "Gradient compression")
        if self._residual is not None and self._residual_shapes:
            restored_res = doc.get('residual', {})
            for n in self._residual_shapes:
                if n in restored_res:
                    self._residual[n] = _put_replicated(
                        self._leaf_from_logical(n, restored_res[n]),
                        self._residual_shardings[n])
                else:
                    self._residual[n] = _put_replicated(
                        onp.zeros(self._residual_shapes[n], onp.float32),
                        self._residual_shardings[n])
        self._step_count = int(doc.get('step_count', self._step_count))
