"""Compiled sharded training step — the performance path.

This is the TPU-native realisation of the north star (BASELINE.json): the
whole train step (forward + backward + optimizer update + gradient
all-reduce) is ONE pjit-compiled XLA program per step. Parameters are
replicated (DP) or sharded (TP via param_specs) over the mesh; the batch is
sharded over the 'dp' axis; XLA inserts the gradient all-reduce over ICI.
Buffer donation on params/optimizer state gives the reference's
static-alloc in-place update behavior (ref: CachedOp static_alloc,
src/imperative/cached_op.cc:525).

ZeRO-1 (default on whenever the dp axis has >1 devices, gate with
MXTPU_ZERO=0 or zero=False): the fp32 masters and optimizer moments are
dp-SHARDED PartitionSpecs instead of replicated, so the grad all-reduce
becomes a reduce-scatter, each device updates only its 1/dp slice, and
the updated params all-gather back — same wire bytes, 1/dp optimizer
math and state HBM per device. See the mxnet_tpu_comm_* telemetry
contract for the per-run accounting.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as onp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError, state as _flags, telem_flags as _telem
from ..ndarray.ndarray import NDArray
from ..resilience import faults as _faults
from ..telemetry import trace as _trace, flight as _flight
from .. import random as _random
from .mesh import default_mesh


def _put_replicated(x, sharding):
    """Place parameter/optimizer data with a (possibly multi-host) sharding.
    Multi-process: broadcast process 0's value first, so every worker starts
    from identical parameters regardless of local RNG state — the analog of
    the reference's kvstore.init broadcast from worker 0
    (ref: src/kvstore/kvstore_dist.h InitImpl)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        x = multihost_utils.broadcast_one_to_all(onp.asarray(x))
        x = onp.asarray(x)
    return jax.device_put(x, sharding)


def _put_batch(x, sharding):
    """Place a batch with the dp sharding. Single-process: the array is the
    global batch. Multi-process: each process holds its OWN shard (the
    reference's per-worker data partition, tools/launch.py semantics), and
    the global batch is their concatenation over the dp axis."""
    if jax.process_count() > 1:
        return jax.make_array_from_process_local_data(
            sharding, onp.asarray(x))
    return jax.device_put(x, sharding)


def _local_value(arr):
    """A fully-addressable view of a replicated global array (loss outputs
    span all processes; every device holds the same value)."""
    if jax.process_count() > 1 and not arr.is_fully_addressable:
        return arr.addressable_data(0)
    return arr


def compose_zero_spec(shape, base_spec, dp_axis, dp_size):
    """ZeRO-1 layout for an optimizer-state/master tensor: compose a dp
    shard onto the parameter's (tp) PartitionSpec. Picks the first dim
    not already claimed by another mesh axis whose size splits evenly
    over dp; falls back to a padded (ragged) shard when only an uneven
    dim is available. None when nothing is shardable (scalars and
    sub-dp-size tensors stay replicated — they are the ±padding slack in
    the 1/dp state-footprint accounting)."""
    spec = list(base_spec) + [None] * (len(shape) - len(base_spec))
    for s in spec:
        # already sharded over dp (fsdp-style param_specs): the state
        # inherits the param's own 1/dp layout — composing again would
        # produce an invalid duplicate-axis spec
        if s == dp_axis or (isinstance(s, (tuple, list)) and dp_axis in s):
            return None
    for exact in (True, False):
        for i, s in enumerate(spec):
            if s is not None or shape[i] < dp_size:
                continue
            if exact and shape[i] % dp_size != 0:
                continue
            spec[i] = dp_axis
            return P(*spec)
    return None


def _sgd_init(p):
    return (jnp.zeros_like(p),)


def _sgd_update(p, g, s, lr, momentum=0.9, wd=0.0):
    mom, = s
    g = g + wd * p
    new_mom = momentum * mom - lr * g
    return p + new_mom, (new_mom,)


def _adam_init(p):
    return (jnp.zeros_like(p), jnp.zeros_like(p), jnp.zeros((), jnp.int32))


def _adam_update(p, g, s, lr, beta1=0.9, beta2=0.999, eps=1e-8, wd=0.0):
    m, v, t = s
    t = t + 1
    g = g + wd * p
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * jnp.square(g)
    mhat = m / (1 - beta1 ** t.astype(jnp.float32))
    vhat = v / (1 - beta2 ** t.astype(jnp.float32))
    return p - lr * mhat / (jnp.sqrt(vhat) + eps), (m, v, t)


def _adamw_update(p, g, s, lr, beta1=0.9, beta2=0.999, eps=1e-8, wd=0.01,
                  eta=1.0):
    # reference semantics (src/operator/contrib/adamw.cc, the GluonNLP
    # BERTAdam recipe): NO bias correction, decoupled wd scaled by lr —
    # kept identical to ops/optimizer_ops.py adamw_update so the Trainer
    # and ShardedTrainStep paths produce the same trajectory
    # (tests/test_gradients.py parity check)
    m, v, t = s
    t = t + 1
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * jnp.square(g)
    return p - eta * (lr * m / (jnp.sqrt(v) + eps) + wd * lr * p), \
        (m, v, t)


def _lamb_update(p, g, s, lr, beta1=0.9, beta2=0.999, eps=1e-6, wd=0.01):
    m, v, t = s
    t = t + 1
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * jnp.square(g)
    mhat = m / (1 - beta1 ** t.astype(jnp.float32))
    vhat = v / (1 - beta2 ** t.astype(jnp.float32))
    update = mhat / (jnp.sqrt(vhat) + eps) + wd * p
    r1 = jnp.linalg.norm(p.reshape(-1))
    r2 = jnp.linalg.norm(update.reshape(-1))
    ratio = jnp.where((r1 > 0) & (r2 > 0), r1 / r2, 1.0)
    return p - lr * ratio * update, (m, v, t)


_OPTS = {
    'sgd': (_sgd_init, _sgd_update),
    'adam': (_adam_init, _adam_update),
    'adamw': (_adam_init, _adamw_update),
    'lamb': (_adam_init, _lamb_update),
}


class ShardedTrainStep:
    """One-pjit-call training step for a Gluon block over a device mesh.

    Usage:
        step = ShardedTrainStep(net, loss_fn, 'adam',
                                optimizer_params={'lr': 1e-3}, mesh=mesh)
        loss = step(data, label)      # NDArrays; params updated in place
    """

    def __init__(self, block, loss_fn, optimizer='sgd', optimizer_params=None,
                 mesh=None, dp_axis='dp', param_specs=None, donate=True,
                 grad_dtype=None, zero=None, compression_params=None,
                 guard=None):
        self.block = block
        self.loss_fn = loss_fn
        self.mesh = mesh if mesh is not None else default_mesh()
        self.dp_axis = dp_axis
        self.optimizer_params = dict(optimizer_params or {})
        self.lr = self.optimizer_params.pop('learning_rate',
                                            self.optimizer_params.pop('lr', 0.01))
        if optimizer not in _OPTS:
            raise ValueError(f"ShardedTrainStep supports {sorted(_OPTS)}")
        self._opt_init, self._opt_update = _OPTS[optimizer]
        self.param_specs = param_specs or {}
        self.donate = donate
        if compression_params is not None and \
                compression_params.get('type', '2bit') != 'none':
            # surfaced, not silently dropped: the GSPMD path has no
            # kvstore push where compress_decompress could hook in — the
            # gradient reduction is an XLA collective inside the step
            raise MXNetError(
                f"gradient compression "
                f"(type={compression_params.get('type', '2bit')!r}) is not "
                f"supported on the GSPMD/ShardedTrainStep path: the "
                f"gradient all-reduce is emitted by XLA inside the "
                f"compiled step, so there is no kvstore push to compress. "
                f"Use the kvstore Trainer path (multi-copy or "
                f"dist_sync), or drop compression_params.")
        dp_size = dict(self.mesh.shape).get(self.dp_axis, 1)
        if zero is None:
            from .. import config as _cfg
            zero = _cfg.get('MXTPU_ZERO')
        # ZeRO-1: default-on when a >1-device dp axis exists (the fp32
        # masters + Adam moments then live 1/dp per device)
        self.zero = bool(zero) and dp_size > 1
        self._dp_size = dp_size
        self._params = None       # list[(name, Parameter)]
        self._master = None       # fp32 master copies of bf16/fp16 params
        self._opt_state = None
        self._compiled = None
        self._step_count = 0
        self._pending_states = None   # restored blob awaiting first build
        self._cost_args = None        # avals for cost_analysis()
        # resilience.NonFiniteGuard: the pjit step then also reduces
        # isfinite over loss + every grad and gates the whole writeback
        # on device; the guard reads the flag one step deferred
        self._guard = guard
        if guard is not None:
            guard.add_post_restore_hook(self._replace_params_on_mesh)

    # ------------------------------------------------------------------
    def _collect(self):
        params = sorted(self.block.collect_params().items())
        trainable = [(n, p) for n, p in params if p.grad_req != 'null']
        frozen = [(n, p) for n, p in params if p.grad_req == 'null']
        return trainable, frozen

    def _resolve_param_specs(self, names):
        """name -> PartitionSpec. A spec key matches a parameter by exact
        name or as a regex via re.search (so plain substrings keep
        working). Unmatched specs and conflicting matches warn; the full
        mapping is kept on self.param_spec_report for inspection."""
        import re
        import warnings
        mapping = {n: P() for n in names}
        matched_by = {n: None for n in names}
        report = {}
        for pat, spec in self.param_specs.items():
            hits = [n for n in names
                    if n == pat or re.search(str(pat), n) is not None]
            report[pat] = hits
            if not hits:
                warnings.warn(
                    f"ShardedTrainStep: param_spec {pat!r} matched no "
                    f"parameter (have e.g. {sorted(names)[:5]})",
                    RuntimeWarning)
            for n in hits:
                if matched_by[n] is not None and mapping[n] != spec:
                    warnings.warn(
                        f"ShardedTrainStep: parameter {n!r} matched both "
                        f"{matched_by[n]!r} and {pat!r}; using {pat!r}",
                        RuntimeWarning)
                mapping[n] = spec
                matched_by[n] = pat
        self.param_spec_report = report
        return mapping

    def _spec_for(self, name):
        if getattr(self, '_spec_map', None) is not None and \
                name in self._spec_map:
            return self._spec_map[name]
        return P()  # replicated

    def _build(self, example_inputs, example_labels):
        trainable, frozen = self._collect()
        t_names = [n for n, _ in trainable]
        f_names = [n for n, _ in frozen]
        self._spec_map = self._resolve_param_specs(t_names + f_names)
        # low-precision trainables keep a persistent fp32 master copy
        # (the reference's create_state_multi_precision,
        # python/mxnet/optimizer/optimizer.py:52): without it, updates
        # below the bf16 ulp of the weight are lost to re-rounding.
        master_names = frozenset(
            n for n, p in trainable
            if jnp.dtype(p.data()._data.dtype).itemsize < 4
            and jnp.issubdtype(p.data()._data.dtype, jnp.floating))
        block = self.block
        loss_fn = self.loss_fn
        opt_update = self._opt_update
        opt_kwargs = self.optimizer_params
        n_inputs = len(example_inputs)

        def forward_loss(t_params, f_params, inputs, labels, key,
                         fault_scale):
            all_params = dict(t_params)
            all_params.update(f_params)
            name_to_param = dict(trainable + frozen)
            proxies = {}
            for n, p in name_to_param.items():
                proxies[n] = NDArray(all_params[n])
                p._set_trace_proxy(proxies[n])
            prev = _flags.is_training
            _flags.is_training = True
            try:
                with _random.key_provider(_random.TraceKeyProvider(key)):
                    out = block.forward(*[NDArray(x) for x in inputs])
                    outs = out if isinstance(out, (list, tuple)) else (out,)
                    loss = loss_fn(*outs, *[NDArray(l) for l in labels])
            finally:
                _flags.is_training = prev
                for p in name_to_param.values():
                    p._clear_trace_proxy()
            # fault_scale is 1.0 on every normal step (an exact-identity
            # multiply); an injected step.dispatch:nan passes NaN here,
            # poisoning the loss AND (via the chain rule) every gradient
            # regardless of the model's input dtypes — int-token models
            # like BERT included
            loss_val = jnp.mean(loss._data) * fault_scale
            aux = {n: proxies[n]._data for n in f_names}
            return loss_val, aux

        # shardings
        mesh = self.mesh
        repl = NamedSharding(mesh, P())
        batch_sh = NamedSharding(mesh, P(self.dp_axis))

        t_shardings = {n: NamedSharding(mesh, self._spec_for(n))
                       for n in t_names}
        f_shardings = {n: NamedSharding(mesh, self._spec_for(n))
                       for n in f_names}
        # ZeRO-1 (Rajbhandari et al., 2020, stage 1): the fp32 masters and
        # Adam moments shard 1/dp over the dp axis (composed with any tp
        # dims the param already shards). The update then reads a
        # dp-SHARDED gradient — the constraint below turns the plain
        # all-reduce into reduce-scatter — and out_shardings all-gather
        # the updated param back to its replicated/tp layout. GSPMD fuses
        # and overlaps both collectives with backward compute.
        zero_specs = {n: None for n in t_names}
        if self.zero:
            shapes = {n: tuple(p.data().shape) for n, p in trainable}
            for n in t_names:
                zero_specs[n] = compose_zero_spec(
                    shapes[n], self._spec_for(n), self.dp_axis,
                    self._dp_size)
        self.zero_specs = zero_specs
        zero_shardings = {
            n: (NamedSharding(mesh, zero_specs[n])
                if zero_specs[n] is not None else t_shardings[n])
            for n in t_names}
        # optimizer state shards like its parameter (ZeRO: like its slice)
        state_shardings = {
            n: tuple((repl if s.ndim == 0 else zero_shardings[n])
                     for s in self._opt_state[n])
            for n in t_names}

        master_shardings = {n: zero_shardings[n] for n in master_names}
        shard_constraint = {n: zero_shardings[n] for n in t_names
                            if zero_specs[n] is not None}

        guard_on = self._guard is not None

        def train_step(t_params, f_params, master, opt_state, inputs,
                       labels, key, lr, fault_scale):
            (loss_val, aux), grads = jax.value_and_grad(
                forward_loss, has_aux=True)(t_params, f_params, inputs,
                                            labels, key, fault_scale)
            new_params = {}
            new_master = {}
            new_state = {}
            ok = jnp.isfinite(loss_val) if guard_on else None
            for n in t_names:
                g32 = grads[n].astype(jnp.float32)
                if guard_on:
                    ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(g32)))
                zsh = shard_constraint.get(n)
                if zsh is not None:
                    # reduce-scatter: the grad is only ever consumed in
                    # this dp-sharded layout, so the partitioner combines
                    # the backward psum + slice into one reduce-scatter
                    g32 = jax.lax.with_sharding_constraint(g32, zsh)
                if n in master_names:
                    p32 = master[n]
                else:
                    p32 = t_params[n].astype(jnp.float32)
                    if zsh is not None:
                        p32 = jax.lax.with_sharding_constraint(p32, zsh)
                np_, ns_ = opt_update(p32, g32, opt_state[n], lr, **opt_kwargs)
                new_params[n] = np_.astype(t_params[n].dtype)
                if n in master_names:
                    new_master[n] = np_
                new_state[n] = ns_
            new_f = {n: aux.get(n, f_params[n]) for n in f_names}
            if guard_on:
                # non-finite guard fused into the pjit step: a bad step
                # writes back the OLD params/master/state/aux on device —
                # a no-op update inside the same XLA program, no host
                # round-trip on the happy path
                new_params = {n: jnp.where(ok, new_params[n], t_params[n])
                              for n in t_names}
                new_master = {n: jnp.where(ok, new_master[n], master[n])
                              for n in new_master}
                new_state = {
                    n: tuple(jnp.where(ok, ns_, os_) for ns_, os_ in
                             zip(new_state[n], opt_state[n]))
                    for n in t_names}
                new_f = {n: jnp.where(ok, new_f[n], f_params[n])
                         for n in f_names}
                return (new_params, new_f, new_master, new_state,
                        loss_val, ok)
            return new_params, new_f, new_master, new_state, loss_val
        in_shardings = (t_shardings, f_shardings, master_shardings,
                        state_shardings,
                        tuple(batch_sh for _ in example_inputs),
                        tuple(batch_sh for _ in example_labels),
                        repl, repl, repl)
        out_shardings = (t_shardings, f_shardings, master_shardings,
                         state_shardings, repl)
        if guard_on:
            out_shardings = out_shardings + (repl,)
        donate = (0, 2, 3) if self.donate else ()
        self._compiled = jax.jit(train_step, in_shardings=in_shardings,
                                 out_shardings=out_shardings,
                                 donate_argnums=donate)
        self._master_names = master_names
        self._master_shardings = master_shardings
        self._t_names = t_names
        self._f_names = f_names
        self._trainable = trainable
        self._frozen = frozen
        self._t_shardings = t_shardings
        self._f_shardings = f_shardings
        self._batch_sh = batch_sh
        self._zero_shardings = zero_shardings
        self._state_shardings = state_shardings
        # Per-step collective accounting (mxnet_tpu_comm_* contract):
        # ring-algorithm wire bytes per device, so ZeRO provably moves the
        # SAME total as the replicated path — all_reduce(N) costs
        # 2*(dp-1)/dp*N while reduce_scatter(N)+all_gather(N) cost
        # (dp-1)/dp*N each. Analytic (XLA does not expose per-collective
        # byte counters), recorded once per step in __call__.
        dp = self._dp_size
        ring = (dp - 1) / dp if dp > 1 else 0.0
        plan = {}
        for n, p in trainable:
            size = int(onp.prod(p.data().shape)) if p.data().shape else 1
            nbytes = size * jnp.dtype(p.data()._data.dtype).itemsize
            if zero_specs[n] is not None:
                for kind in ('reduce_scatter', 'all_gather'):
                    b, c = plan.get(kind, (0.0, 0))
                    plan[kind] = (b + ring * nbytes, c + 1)
            elif dp > 1:
                b, c = plan.get('all_reduce', (0.0, 0))
                plan['all_reduce'] = (b + 2 * ring * nbytes, c + 1)
        self._comm_plan = plan

    # ------------------------------------------------------------------
    def init(self, *example_inputs):
        """Force parameter init (deferred shapes) by one eager forward."""
        rec = _flags.is_recording
        _flags.is_recording = False
        try:
            self.block(*example_inputs)
        finally:
            _flags.is_recording = rec

    def __call__(self, inputs, labels, lr=None):
        with _trace.span('step.dispatch', step=self._step_count):
            return self._call_traced(inputs, labels, lr)

    def _call_traced(self, inputs, labels, lr=None):
        if self._guard is not None:
            # deferred read of the previous step's finiteness flag; a
            # rollback restores params/states/RNG and the post-restore
            # hook re-places them on the mesh — the CURRENT batch then
            # trains against the restored weights (fwd+bwd happen below,
            # after the restore, so nothing here is stale)
            self._guard.pre_step()
        fault = _faults.fire('step.dispatch')
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        if not isinstance(labels, (list, tuple)):
            labels = [labels]
        in_datas = tuple(x._data if isinstance(x, NDArray) else x
                         for x in inputs)
        lab_datas = tuple(x._data if isinstance(x, NDArray) else x
                          for x in labels)
        # 1.0 on normal steps (exact-identity multiply on the loss); an
        # injected step.dispatch:nan flips it to NaN inside the compiled
        # step, so loss AND every gradient go non-finite even for
        # int-input models (BERT token ids)
        fault_scale = jnp.asarray(
            float('nan') if fault == 'nan' else 1.0, jnp.float32)
        if self._compiled is None:
            trainable, frozen = self._collect()
            if not trainable and not frozen:
                self.init(*inputs)
                trainable, frozen = self._collect()
            if any(p._data is None for _, p in trainable + frozen):
                self.init(*inputs)
            with _trace.span('optimizer.state_init'):
                self._opt_state = {
                    n: self._opt_init(p.data()._data.astype(jnp.float32))
                    for n, p in trainable}
            self._build(in_datas, lab_datas)
            # place params on the mesh with their shardings
            with _trace.span('h2d.param_place'):
                for n, p in self._trainable:
                    p._data[0]._data = _put_replicated(
                        p.data()._data, self._t_shardings[n])
                for n, p in self._frozen:
                    p._data[0]._data = _put_replicated(
                        p.data()._data, self._f_shardings[n])
                self._master = {
                    n: _put_replicated(p.data()._data.astype(jnp.float32),
                                       self._master_shardings[n])
                    for n, p in self._trainable
                    if n in self._master_names}
                self._opt_state = {
                    n: tuple(_put_replicated(s, sh) for s, sh in
                             zip(self._opt_state[n],
                                 self._state_shardings[n]))
                    for n in self._t_names}
            if self._pending_states is not None:
                doc, self._pending_states = self._pending_states, None
                self._apply_states(doc)
            if _telem['on']:
                from .. import telemetry as _telemetry
                _telemetry.set_gauge(
                    'mxnet_tpu_comm_opt_state_bytes_per_device',
                    self.opt_state_bytes_per_device())

        t_params = {n: p.data()._data for n, p in self._trainable}
        f_params = {n: p.data()._data for n, p in self._frozen}
        key = _random.next_key()
        lr_val = jnp.asarray(lr if lr is not None else self.lr, jnp.float32)
        with _trace.span('h2d.batch_put'):
            in_datas = tuple(_put_batch(x, self._batch_sh)
                             for x in in_datas)
            lab_datas = tuple(_put_batch(x, self._batch_sh)
                              for x in lab_datas)
        if self._cost_args is None:
            # abstract avals of one step call, kept for cost_analysis()
            self._cost_args = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(jnp.shape(x),
                                               jnp.result_type(x)),
                (t_params, f_params, self._master, self._opt_state,
                 in_datas, lab_datas, key, lr_val, fault_scale))
        with _trace.span('step.compiled'):
            out = self._compiled(
                t_params, f_params, self._master, self._opt_state,
                in_datas, lab_datas, key, lr_val, fault_scale)
        if self._guard is not None:
            new_t, new_f, new_master, new_state, loss, ok = out
            self._guard.push_flag(ok)
        else:
            new_t, new_f, new_master, new_state, loss = out
        with _trace.span('step.gather'):
            # donate/gather bookkeeping: swap the donated buffers'
            # NDArray views to the program's outputs (host pointer
            # swaps; the all-gather itself ran inside the program)
            for n, p in self._trainable:
                p.data()._data = new_t[n]
            for n, p in self._frozen:
                p.data()._data = new_f[n]
            self._master = new_master
            self._opt_state = new_state
        self._step_count += 1
        if self._comm_plan and _trace.enabled():
            # the collectives run INSIDE the compiled program — annotate
            # the trace with the analytic ring-wire plan per step
            for kind, (nbytes, count) in self._comm_plan.items():
                _trace.instant(f'comm.{kind}', bytes=int(nbytes),
                               count=count, axis=self.dp_axis)
        if _telem['on'] and self._comm_plan:
            from .. import telemetry as _telemetry
            for kind, (nbytes, count) in self._comm_plan.items():
                _telemetry.counter(
                    'mxnet_tpu_comm_collective_bytes_total').inc(
                        nbytes, kind=kind, axis=self.dp_axis)
                _telemetry.counter('mxnet_tpu_comm_collectives_total').inc(
                    count, kind=kind, axis=self.dp_axis)
        loss_nd = NDArray(_local_value(loss))
        _flight.record_step(self._step_count, loss=loss_nd)
        return loss_nd

    def _replace_params_on_mesh(self):
        """After an external restore wrote host arrays into the
        parameters (NonFiniteGuard rollback via CheckpointManager), put
        them back on the mesh with the step's shardings — the compiled
        step cannot consume cpu-committed arrays."""
        if self._compiled is None:
            return
        for n, p in self._trainable:
            p._data[0]._data = _put_replicated(
                onp.asarray(p.data()._data), self._t_shardings[n])
        for n, p in self._frozen:
            p._data[0]._data = _put_replicated(
                onp.asarray(p.data()._data), self._f_shardings[n])

    # ------------------------------------------------------------------
    # optimizer-state introspection + layout-independent checkpointing
    # ------------------------------------------------------------------
    def cost_analysis(self):
        """{'flops', 'bytes'} of ONE compiled step from XLA's own
        cost_analysis — the deterministic device-side half of the
        per-step attribution report (telemetry.attribution joins it
        with the measured wall-time spans). Lowers/compiles the step
        once more from stored avals (cached by the persistent
        compilation cache when enabled); None before the first step or
        when the backend exposes no cost model."""
        if self._compiled is None or self._cost_args is None:
            return None
        from ..telemetry import attribution as _attribution
        try:
            compiled = self._compiled.lower(*self._cost_args).compile()
        except Exception:
            return None
        return _attribution.xla_cost(compiled)

    def opt_state_bytes_per_device(self):
        """Bytes of optimizer state (masters + moments) ONE device holds.
        Under ZeRO-1 this is ~1/dp of the replicated footprint (± the
        tensors too small/ragged to shard)."""
        total = 0
        for st in (self._opt_state or {}).values():
            for s in st:
                total += s.addressable_shards[0].data.nbytes
        for m in (self._master or {}).values():
            total += m.addressable_shards[0].data.nbytes
        return total

    def get_states_bytes(self):
        """Optimizer state as a layout-independent bytes payload: every
        shard is gathered to host fp32 numpy, so a checkpoint written at
        one dp degree (or under ZeRO) restores at any other — the same
        contract as gluon.Trainer.get_states_bytes, and what
        checkpoint.CheckpointManager snapshots when bound as `trainer=`."""
        import pickle
        if self._compiled is None:
            if self._pending_states is not None:
                # resumed but not yet stepped (e.g. a preemption save in
                # the restore->first-step window): the restored payload
                # IS the current state — hand it back unchanged
                return pickle.dumps(self._pending_states)
            raise MXNetError("get_states_bytes: no optimizer state yet — "
                             "run at least one step first")
        states = {n: tuple(onp.asarray(s) for s in st)
                  for n, st in self._opt_state.items()}
        master = {n: onp.asarray(m) for n, m in self._master.items()}
        return pickle.dumps({
            'format': 'sharded_train_step_v1',
            'opt_state': states, 'master': master,
            'step_count': self._step_count,
            'zero': self.zero, 'dp': self._dp_size})

    def set_states_bytes(self, blob):
        """Restore a get_states_bytes() payload, scattering each tensor
        into THIS step's current layout (replicated, tp, or ZeRO 1/dp —
        the saved layout does not have to match)."""
        import pickle
        doc = pickle.loads(blob)
        if doc.get('format') != 'sharded_train_step_v1':
            raise MXNetError(
                f"set_states_bytes: not a ShardedTrainStep payload "
                f"(format={doc.get('format')!r})")
        if self._compiled is None:
            self._pending_states = doc   # applied right after first build
            return
        self._apply_states(doc)

    def _apply_states(self, doc):
        for n, st in doc['opt_state'].items():
            if n not in self._opt_state:
                raise MXNetError(f"set_states_bytes: unknown parameter "
                                 f"{n!r} in restored optimizer state")
            self._opt_state[n] = tuple(
                _put_replicated(onp.asarray(s), sh)
                for s, sh in zip(st, self._state_shardings[n]))
        for n, m in doc.get('master', {}).items():
            if n in self._master_names:
                self._master[n] = _put_replicated(
                    onp.asarray(m), self._master_shardings[n])
        self._step_count = int(doc.get('step_count', self._step_count))
