"""mx.operator — user-defined operators in Python (ref: python/mxnet/operator.py,
src/operator/custom/custom.cc).

The reference runs Python custom ops on a dedicated worker thread so they keep
dependency-engine semantics (src/operator/custom/custom-inl.h:76). Here the
eager path simply calls the user's ``forward`` inline — jax's async dispatch
means the surrounding ops are already futures, and the custom op acts as a
host-side sync point exactly like the reference's engine callback. When
autograd is recording, the user's ``backward`` is recorded on the tape as the
node's vjp, so custom ops compose with the rest of the graph.

Inside a hybridized/jitted trace a Python custom op cannot run natively on
the TPU; it is bridged with ``jax.pure_callback`` + ``jax.custom_vjp`` so the
traced program calls back into Python — the TPU analog of the reference's
custom-op worker thread crossing the engine boundary. Note: this requires a
runtime with host-callback support (CPU and standard TPU PjRt have it; some
tunneled backends do not — use eager mode there).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Type

import numpy as onp

__all__ = ['CustomOp', 'CustomOpProp', 'register', 'get_registered_op',
           'list_registered_ops']


class CustomOp:
    """Base class for user operator implementations
    (ref: python/mxnet/operator.py CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Write `src` into `dst` honoring the OpReqType
        (ref: include/mxnet/op_attr_types.h:46 kNullOp/kWriteTo/kAddTo)."""
        if req == 'null':
            return
        from .ndarray.ndarray import NDArray
        src_data = src._data if isinstance(src, NDArray) else src
        if req == 'add':
            dst._data = dst._data + src_data
        else:  # 'write' / 'inplace'
            dst._data = src_data


class CustomOpProp:
    """Operator properties: shapes/types/arity + factory
    (ref: python/mxnet/operator.py CustomOpProp)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), \
            [in_type[0]] * len(self.list_auxiliary_states())

    def list_arguments(self):
        return ['data']

    def list_outputs(self):
        return ['output']

    def list_auxiliary_states(self):
        return []

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


_registry: Dict[str, Type[CustomOpProp]] = {}


def register(reg_name):
    """Decorator registering a CustomOpProp subclass under `op_type`
    (ref: python/mxnet/operator.py register)."""
    def do_register(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise TypeError("can only register subclasses of CustomOpProp")
        _registry[reg_name] = prop_cls
        return prop_cls
    return do_register


def get_registered_op(op_type) -> Type[CustomOpProp]:
    if op_type not in _registry:
        raise ValueError(
            f"custom op type '{op_type}' is not registered "
            f"(known: {sorted(_registry)})")
    return _registry[op_type]


def list_registered_ops() -> List[str]:
    return sorted(_registry)


def _make_prop(op_type, kwargs) -> CustomOpProp:
    prop_cls = get_registered_op(op_type)
    # the reference marshals user kwargs through the C API as strings
    # (src/operator/custom/custom.cc ParamParser); keep that contract
    return prop_cls(**{k: str(v) for k, v in kwargs.items()})


def _invoke_traced(op_type, prop, op, in_data, aux, out_shapes, out_types):
    """Trace-time bridge: the jitted program calls back into the Python op
    via jax.pure_callback, with jax.custom_vjp routing cotangents through the
    user's ``backward`` — the TPU analog of the reference's custom-op worker
    thread crossing the engine boundary (src/operator/custom/custom-inl.h:76)."""
    import jax
    import jax.numpy as jnp
    from .base import state
    from .ndarray.ndarray import NDArray

    n_in = len(in_data)
    n_aux = len(aux)
    n_out = len(out_shapes)
    out_avals = tuple(jax.ShapeDtypeStruct(tuple(s), onp.dtype(t))
                      for s, t in zip(out_shapes, out_types))
    is_train = state.is_training
    need_top = prop.need_top_grad_

    def _host_arrays(arrs):
        return [NDArray(jnp.asarray(a)) for a in arrs]

    def host_forward(*arrs):
        rec, state.is_recording = state.is_recording, False
        try:
            nds = _host_arrays(arrs[:n_in])
            auxs = _host_arrays(arrs[n_in:])
            outs = [NDArray(jnp.zeros(a.shape, a.dtype)) for a in out_avals]
            op.forward(is_train=is_train, req=['write'] * n_out,
                       in_data=nds, out_data=outs, aux=auxs)
            return tuple(onp.asarray(o.asnumpy(), dtype=a.dtype)
                         for o, a in zip(outs, out_avals))
        finally:
            state.is_recording = rec

    def host_backward(*arrs):
        rec, state.is_recording = state.is_recording, False
        try:
            nds = _host_arrays(arrs[:n_in])
            auxs = _host_arrays(arrs[n_in:n_in + n_aux])
            outs = _host_arrays(arrs[n_in + n_aux:n_in + n_aux + n_out])
            cts = _host_arrays(arrs[n_in + n_aux + n_out:])
            in_grad = [NDArray(jnp.zeros_like(a._data)) for a in nds]
            op.backward(req=['write'] * n_in,
                        out_grad=cts if need_top else [],
                        in_data=nds, out_data=outs, in_grad=in_grad, aux=auxs)
            return tuple(onp.asarray(g.asnumpy(), dtype=n._data.dtype)
                         for g, n in zip(in_grad, nds))
        finally:
            state.is_recording = rec

    @jax.custom_vjp
    def f(*datas):
        return jax.pure_callback(host_forward, out_avals, *datas)

    def f_fwd(*datas):
        outs = jax.pure_callback(host_forward, out_avals, *datas)
        return outs, (datas, outs)

    def f_bwd(res, cts):
        datas, outs = res
        in_avals = tuple(jax.ShapeDtypeStruct(d.shape, d.dtype)
                         for d in datas[:n_in])
        grads = jax.pure_callback(host_backward, in_avals,
                                  *datas, *outs, *cts)
        return tuple(grads) + tuple(jnp.zeros_like(d) for d in datas[n_in:])

    f.defvjp(f_fwd, f_bwd)

    out = f(*[a._data for a in in_data + aux])
    out_nd = [NDArray(o) for o in out]
    return out_nd[0] if n_out == 1 else tuple(out_nd)


def invoke_custom(inputs, op_type: Optional[str] = None, **kwargs):
    """nd.Custom implementation: eager dispatch of a registered custom op,
    recording the user-defined backward on the autograd tape
    (ref: src/operator/custom/custom.cc Forward/Backward)."""
    import jax.numpy as jnp
    from . import _imperative
    from .base import state
    from .ndarray.ndarray import NDArray, _wrap

    import jax

    if op_type is None:
        raise ValueError("nd.Custom requires op_type=")
    prop = _make_prop(op_type, kwargs)

    n_args = len(prop.list_arguments())
    n_aux = len(prop.list_auxiliary_states())
    if len(inputs) != n_args + n_aux:
        raise ValueError(
            f"custom op '{op_type}' expects {n_args} args + {n_aux} aux "
            f"states, got {len(inputs)} inputs")
    in_data = list(inputs[:n_args])
    aux = list(inputs[n_args:])

    in_shapes = [tuple(a.shape) for a in in_data]
    in_shapes_out, out_shapes, _ = prop.infer_shape(in_shapes)
    in_types = [a.dtype for a in in_data]
    _, out_types, _ = prop.infer_type(in_types)
    n_out = len(prop.list_outputs())
    if len(out_shapes) != n_out or len(out_types) != n_out:
        raise ValueError(
            f"custom op '{op_type}': infer_shape/infer_type returned "
            f"{len(out_shapes)}/{len(out_types)} outputs but list_outputs() "
            f"declares {n_out}")

    op = prop.create_operator(None, in_shapes_out, in_types)

    if any(isinstance(a._data, jax.core.Tracer) for a in in_data + aux):
        return _invoke_traced(op_type, prop, op, in_data, aux,
                              out_shapes, out_types)

    out_data = [_wrap(jnp.zeros(s, dtype=onp.dtype(t)))
                for s, t in zip(out_shapes, out_types)]

    is_train = state.is_training
    rec = state.is_recording
    recording = rec and any(a._in_graph for a in in_data)
    # the op's own backward is the gradient; internal nd ops inside the
    # user's forward must not land on the tape
    state.is_recording = False
    try:
        op.forward(is_train=is_train, req=['write'] * len(out_data),
                   in_data=in_data, out_data=out_data, aux=aux)
    finally:
        state.is_recording = rec
    if recording:
        need_top = prop.need_top_grad_

        def vjp_fn(ct_struct):
            cts = ct_struct if isinstance(ct_struct, tuple) else (ct_struct,)
            out_grad = [_wrap(c) for c in cts] if need_top else []
            in_grad = [_wrap(jnp.zeros_like(a._data)) for a in in_data]
            brec, state.is_recording = state.is_recording, False
            try:
                op.backward(req=['write'] * len(in_grad), out_grad=out_grad,
                            in_data=in_data, out_data=out_data,
                            in_grad=in_grad, aux=aux)
            finally:
                state.is_recording = brec
            return tuple(g._data for g in in_grad)

        _imperative.record_node(in_data, out_data, vjp_fn, fn=None,
                                name=f"Custom[{op_type}]",
                                tuple_out=len(out_data) > 1)

    return out_data[0] if len(out_data) == 1 else tuple(out_data)
