"""Imperative runtime: eager op dispatch + autograd tape.

TPU-native analog of src/imperative/imperative.cc. The reference pushes each
op into a C++ dependency engine; here, jax's async dispatch IS the engine —
every op call returns immediately with a future-backed jax.Array, ordering is
data-flow, and `wait_to_read` == `block_until_ready` (ref:
include/mxnet/ndarray.h:368). Autograd is a Python tape of `jax.vjp`
closures (ref: Imperative::RecordOp, include/mxnet/imperative.h:140).
"""
from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

import jax

from .base import (state, MXNetError, prof_flags, record_op_use,
                   telem_flags as _telem)


class TapeNode:
    __slots__ = ("inputs", "outputs", "vjp_fn", "fn", "name", "tuple_out")

    def __init__(self, inputs, outputs, vjp_fn, fn=None, name="",
                 tuple_out=False):
        self.inputs = inputs      # list of NDArray
        self.outputs = outputs    # list of NDArray
        self.vjp_fn = vjp_fn      # cotangent(s) -> input cotangents
        self.fn = fn              # pure fn over jax arrays (for create_graph)
        self.name = name
        self.tuple_out = tuple_out  # fn returned a tuple (vs single array)


class _Tape(threading.local):
    def __init__(self):
        self.nodes: List[TapeNode] = []
        self.retained = False  # a retain_graph backward keeps nodes alive

    def clear(self):
        self.nodes = []
        self.retained = False


tape = _Tape()


def invoke(fn: Callable, args: tuple, kwargs: dict):
    """Dispatch `fn` (a pure function over jax arrays) on NDArray arguments.

    Returns (raw jax output(s), tensor inputs, vjp_fn-or-None, pure_fn).
    """
    from .ndarray.ndarray import NDArray

    tensor_inputs: List[Any] = []
    spec_args = []
    for a in args:
        if isinstance(a, NDArray):
            spec_args.append(len(tensor_inputs))
            tensor_inputs.append(a)
        else:
            spec_args.append((a,))
    spec_kwargs = {}
    for k, v in kwargs.items():
        if isinstance(v, NDArray):
            spec_kwargs[k] = len(tensor_inputs)
            tensor_inputs.append(v)
        else:
            spec_kwargs[k] = (v,)

    def g(*datas):
        call_args = [datas[s] if isinstance(s, int) else s[0] for s in spec_args]
        call_kwargs = {k: (datas[s] if isinstance(s, int) else s[0])
                       for k, s in spec_kwargs.items()}
        return fn(*call_args, **call_kwargs)

    datas = tuple(t._data for t in tensor_inputs)
    recording = state.is_recording and any(t._in_graph for t in tensor_inputs)

    if _telem['on']:
        from . import telemetry as _telemetry
        _telemetry.inc('mxnet_tpu_imperative_ops_total')

    try:
        if prof_flags['op']:
            out = _invoke_profiled(fn, g, datas, tensor_inputs, recording)
            record_op_use(fn)   # after dispatch: a raising op is not covered
            return out
        if not recording:
            out = g(*datas)
            record_op_use(fn)
            return out, tensor_inputs, None, g
        out_data, vjp_fn = jax.vjp(g, *datas)
        record_op_use(fn)
        return out_data, tensor_inputs, vjp_fn, g
    except MXNetError:
        raise
    except jax.errors.JAXTypeError:
        # tracer-leak / concretization errors carry jax-specific remedies
        # (and framework code dispatches on them, e.g. the trainer's
        # fused-update probe) — pass them through untranslated
        raise
    except (TypeError, ValueError, ZeroDivisionError) as e:
        # the reference surfaces op failures as MXNetError (engine
        # on_complete callbacks, ref: src/engine/threaded_engine.cc
        # ExecuteOprBlock exception capture); the imperative dispatch here
        # is synchronous so the raise happens at invoke, not at
        # wait_to_read — but the type and the recovered-engine behavior
        # match (tests/test_exc_handling.py)
        name = getattr(fn, '__name__', str(fn))
        raise MXNetError(f"Error in operator {name}: {e}") from e


def _invoke_profiled(fn, g, datas, tensor_inputs, recording):
    """invoke() with per-op timing rows (ref: the reference wraps every
    engine push in a profiler entry, src/profiler/profiler.h:299
    PROFILER_MESSAGE). Timing covers dispatch; with profile_sync (or
    aggregate_stats) the op is blocked to completion first, giving true
    device time at the cost of pipelining."""
    import time as _time
    from . import profiler as _profiler
    t0 = _time.perf_counter()
    if not recording:
        out, vjp_fn = g(*datas), None
    else:
        out, vjp_fn = jax.vjp(g, *datas)
    if prof_flags['sync']:
        jax.block_until_ready(out)
    dur_us = (_time.perf_counter() - t0) * 1e6
    _profiler.record_op(getattr(fn, '__name__', str(fn)), dur_us)
    return out, tensor_inputs, vjp_fn, g


def record_node(tensor_inputs, outputs, vjp_fn, fn=None, name="",
                tuple_out=None):
    if tuple_out is None:
        tuple_out = len(outputs) > 1
    node = TapeNode(list(tensor_inputs), list(outputs), vjp_fn, fn, name,
                    tuple_out)
    for o in outputs:
        o._in_graph = True
    tape.nodes.append(node)
    return node


def _is_float0(x):
    return getattr(x, 'dtype', None) is not None and str(x.dtype) == 'float0'


def _accumulate(grad_map, heads, head_grads, nodes, create_graph):
    """Reverse sweep over `nodes`, filling grad_map (id(ndarray) -> NDArray)."""
    import jax.numpy as jnp
    from .ndarray.ndarray import NDArray, _wrap

    for node in reversed(nodes):
        cts = []
        touched = False
        for o in node.outputs:
            ct = grad_map.get(id(o))
            if ct is None:
                cts.append(None)
            else:
                touched = True
                cts.append(ct)
        if not touched or node.vjp_fn is None:
            continue
        ct_arrs = [c if c is not None else _wrap(jnp.zeros_like(o._data))
                   for c, o in zip(cts, node.outputs)]

        if create_graph and node.fn is not None:
            n_in = len(node.inputs)
            node_fn = node.fn

            tuple_out = node.tuple_out

            def bwd(*datas, _n_in=n_in, _fn=node_fn, _tup=tuple_out):
                in_datas = datas[:_n_in]
                ct_datas = datas[_n_in:]
                _, vjp2 = jax.vjp(_fn, *in_datas)
                ct_s = tuple(ct_datas) if _tup else ct_datas[0]
                return vjp2(ct_s)

            out_data, t_inputs, vjp_fn2, gfn = invoke(
                bwd, tuple(node.inputs) + tuple(ct_arrs), {})
            in_ct_arrs = [None if _is_float0(d) else _wrap(d) for d in out_data]
            if vjp_fn2 is not None:
                rec_outs = [a if a is not None else _wrap(d)
                            for a, d in zip(in_ct_arrs, out_data)]
                record_node(t_inputs, rec_outs, vjp_fn2, gfn,
                            "grad_" + node.name)
        else:
            ct_struct = (tuple(c._data for c in ct_arrs) if node.tuple_out
                         else ct_arrs[0]._data)
            in_cts = node.vjp_fn(ct_struct)
            in_ct_arrs = [None if _is_float0(d) else _wrap(d) for d in in_cts]

        for inp, ict in zip(node.inputs, in_ct_arrs):
            if ict is None:
                continue
            prev = grad_map.get(id(inp))
            if prev is None:
                grad_map[id(inp)] = ict
            else:
                grad_map[id(inp)] = prev + ict


def _seed_heads(heads, head_grads):
    import jax.numpy as jnp
    from .ndarray.ndarray import NDArray, _wrap
    grad_map = {}
    for h, hg in zip(heads, head_grads):
        if isinstance(hg, NDArray):
            g = hg
        elif hg is None:
            g = _wrap(jnp.ones_like(h._data))
        else:
            g = _wrap(hg)
        prev = grad_map.get(id(h))
        grad_map[id(h)] = g if prev is None else prev + g
    return grad_map


def _ancestors(nodes, heads):
    """Nodes reachable backwards from heads (the subgraph this backward
    consumes — other recorded subgraphs stay on the tape, matching the
    reference's per-graph backward semantics)."""
    needed = {id(h) for h in heads}
    marked = []
    for node in reversed(nodes):
        if any(id(o) in needed for o in node.outputs):
            marked.append(node)
            for i in node.inputs:
                needed.add(id(i))
    marked.reverse()
    return marked


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Reverse pass writing into leaf `.grad` arrays (ref:
    Imperative::Backward, src/imperative/imperative.cc:280)."""
    if not isinstance(heads, (list, tuple)):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]

    nodes = _ancestors(tape.nodes, heads)
    grad_map = _seed_heads(heads, head_grads)
    rec = state.is_recording
    state.is_recording = False
    try:
        _accumulate(grad_map, heads, head_grads, nodes, create_graph=False)
    finally:
        state.is_recording = rec

    seen = set()
    for node in nodes:
        for arr in node.inputs + node.outputs:
            if id(arr) in seen:
                continue
            seen.add(id(arr))
            if arr._grad is not None and id(arr) in grad_map:
                _write_grad(arr, grad_map[id(arr)])
    for h in heads:
        if id(h) not in seen and h._grad is not None and id(h) in grad_map:
            _write_grad(h, grad_map[id(h)])

    if retain_graph:
        tape.retained = True
    else:
        consumed = set(map(id, nodes))
        tape.nodes = [n for n in tape.nodes if id(n) not in consumed]


def _write_grad(arr, g):
    if arr._grad_req == 'add':
        arr._grad._data = arr._grad._data + g._data.astype(arr._grad._data.dtype)
    elif arr._grad_req != 'null':
        arr._grad._data = g._data.astype(arr._grad._data.dtype)


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """autograd.grad (ref: python/mxnet/autograd.py:271); supports
    higher-order gradients via create_graph=True."""
    import jax.numpy as jnp
    from .ndarray.ndarray import _wrap

    single = not isinstance(variables, (list, tuple))
    if not isinstance(heads, (list, tuple)):
        heads = [heads]
    if single:
        variables = [variables]
    if head_grads is None:
        head_grads = [None] * len(heads)
    if retain_graph is None:
        retain_graph = create_graph

    nodes = list(tape.nodes)
    grad_map = _seed_heads(heads, head_grads)

    rec = state.is_recording
    if not create_graph:
        state.is_recording = False
    try:
        _accumulate(grad_map, heads, head_grads, nodes, create_graph)
    finally:
        state.is_recording = rec

    results = []
    for v in variables:
        g = grad_map.get(id(v))
        if g is None:
            g = _wrap(jnp.zeros_like(v._data))
        results.append(g)
    if retain_graph:
        tape.retained = True
    else:
        tape.clear()
    return results[0] if single else results
