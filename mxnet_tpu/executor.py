"""Executor module (ref: python/mxnet/executor.py): re-exports the
Symbol executor and adds the monitor-callback surface. The executor
itself lives in symbol.py (the DAG and its compiled evaluation are one
design unit here); this module keeps the reference's import path
`mx.executor.Executor` working."""
from __future__ import annotations

from .symbol import Executor  # noqa: F401

__all__ = ['Executor']
