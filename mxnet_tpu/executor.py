"""Executor module (ref: python/mxnet/executor.py): re-exports the
Symbol executor and adds the monitor-callback surface. The executor
itself lives in symbol.py (the DAG and its compiled evaluation are one
design unit here); this module keeps the reference's import path
`mx.executor.Executor` working.

With telemetry enabled (MXNET_TPU_TELEMETRY=1), every Executor.forward
reports into mxnet_tpu_executor_forward_total /
mxnet_tpu_executor_forward_seconds — see mxnet_tpu.telemetry."""
from __future__ import annotations

from .symbol import Executor  # noqa: F401

__all__ = ['Executor']
