"""Network visualization (ref: python/mxnet/visualization.py)."""
from __future__ import annotations

from .base import MXNetError


def print_summary(symbol, shape=None, line_length=120, positions=(.44, .64, .74, 1.)):
    """Textual summary of a Symbol graph (ref: visualization.py print_summary)."""
    nodes = []

    def visit(s, depth=0):
        for i in s.inputs:
            visit(i, depth + 1)
        if s not in nodes:
            nodes.append(s)

    visit(symbol)
    line = '_' * line_length
    print(line)
    header = ['Layer (type)', 'Output Shape', 'Param #', 'Previous Layer']
    pos = [int(line_length * p) for p in positions]
    row = ''
    for name, p in zip(header, pos):
        row = row[:p - len(name)] if len(row) > p - len(name) else row
        row += name.ljust(p - len(row))
    print(row)
    print('=' * line_length)
    for node in nodes:
        op = node.op or 'Variable'
        fields = [f"{node.name} ({op})", '', '0',
                  ','.join(i.name for i in node.inputs)]
        row = ''
        for f, p in zip(fields, pos):
            row += str(f).ljust(p - len(row))[:p - len(row)]
        print(row)
    print('=' * line_length)


def plot_network(symbol, title='plot', save_format='pdf', shape=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz rendering; returns a Digraph if graphviz is installed."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise MXNetError("plot_network requires graphviz (not installed); "
                         "use print_summary instead")
    dot = Digraph(name=title)
    def visit(s, seen):
        if id(s) in seen:
            return
        seen.add(id(s))
        dot.node(str(id(s)), f"{s.name}\n{s.op or 'var'}")
        for i in s.inputs:
            visit(i, seen)
            dot.edge(str(id(i)), str(id(s)))
    visit(symbol, set())
    return dot
