"""Bounded retry/backoff for transient failures.

One shared helper so every layer that retries (checkpoint payload
writes on transient FS errors, DataLoader worker respawn after a
crashed fetch) uses the same bounded policy and reports into the same
``mxnet_tpu_resilience_retries_total`` counter — unbounded retry loops
are how a transient failure becomes a silent hang.
"""
from __future__ import annotations

import logging
import time as _time

from ..base import telem_flags as _telem

__all__ = ['retry_call']

_log = logging.getLogger('mxnet_tpu.resilience')


def retry_call(fn, *args, retries=2, backoff_seconds=0.05,
               max_backoff_seconds=2.0, retry_on=(OSError,),
               give_up_on=(), site='', sleep=_time.sleep, **kwargs):
    """Call ``fn(*args, **kwargs)``; on an exception in ``retry_on``,
    retry up to ``retries`` more times with exponential backoff
    (``backoff_seconds * 2**attempt``, capped). Exceptions outside
    ``retry_on`` — or inside ``give_up_on``, which wins even when it is
    a ``retry_on`` subclass (e.g. deterministic DataError under a broad
    ``retry_on=(Exception,)``) — propagate immediately; the final
    failure propagates with the original traceback after the budget is
    spent — callers get a real error, never a swallowed one."""
    retries = max(0, int(retries))
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            if give_up_on and isinstance(e, give_up_on):
                raise
            if attempt >= retries:
                raise
            delay = min(backoff_seconds * (2 ** attempt),
                        max_backoff_seconds)
            attempt += 1
            _log.warning(
                "%s: transient failure (%s), retry %d/%d in %.3fs",
                site or getattr(fn, '__name__', 'call'), e, attempt,
                retries, delay)
            if _telem['on']:
                from .. import telemetry as _telemetry
                _telemetry.inc('mxnet_tpu_resilience_retries_total',
                               site=site or 'unknown')
            if delay > 0:
                sleep(delay)
