"""Elastic-training drill: kill a worker, watch the survivor re-form.

The CI-testable half of the elastic story (ISSUE 8 / ROADMAP item 4):
``run_drill`` spawns two real worker processes under ``JAX_PLATFORMS=cpu``
(each with its own jax.distributed rank, membership heartbeat sender and
CheckpointManager), SIGKILLs one mid-run, and asserts the survivor

1. detects the loss on the membership side channel within the peer
   deadline,
2. commits a checkpoint at its last completed step,
3. tears down jax.distributed (bounded — the runtime's shutdown barrier
   would wait for the corpse) and re-forms its mesh at world size 1,
4. resumes from the committed step with a trajectory **bit-identical**
   to a clean single-process run restored from the same checkpoint
   (verified by a third reference process).

It returns the measured MTTR phases (detect / commit / teardown /
restore / first-resumed-step), which ``__graft_entry__.dryrun_multichip``
records each MULTICHIP round and ``tests/test_elastic.py`` asserts in
CI. Workers train on process-LOCAL meshes (this jaxlib's CPU backend
has no cross-process collectives — the same capability gap
tests/test_interop_tools.py skips on); the membership, commit, teardown
and re-form machinery is exactly the multi-host path.

Run a worker by hand::

    python -m mxnet_tpu.resilience.drill --worker --workdir /tmp/d ...
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import time as _time

__all__ = ['run_drill', 'run_churn_drill', 'run_fleet_drill',
           'run_oom_drill', 'run_serving_drill']


def _free_port():
    with socket.socket() as s:
        s.bind(('', 0))
        return s.getsockname()[1]


def _free_port_base(n=2, tries=32):
    """A base port with ``n`` consecutive free ports (the replica
    servers listen on base + rank)."""
    for _ in range(tries):
        base = _free_port()
        ok = True
        for off in range(n):
            try:
                with socket.socket() as s:
                    s.bind(('', base + off))
            except OSError:
                ok = False
                break
        if ok:
            return base
    raise RuntimeError("no consecutive free port range found")


def _data_for(step, batch=16, dim=8):
    """Deterministic per-step batch: the same step index produces the
    same bytes in every process — the precondition for bit-identical
    resume parity."""
    import numpy as onp
    rng = onp.random.RandomState(10_000 + int(step))
    x = rng.randn(batch, dim).astype(onp.float32)
    y = (x.sum(axis=1) > 0).astype(onp.float32)
    return x, y


def _build(workdir, rank, mesh, autosave_steps=None, replication=False,
           ckpt_dir=None):
    """Model + compiled step + checkpoint manager for one worker.
    Explicit prefixes: every process (workers, the reference run) must
    produce identical parameter names for the states payload to apply.
    ``ckpt_dir`` overrides the per-rank default — the churn drill runs
    every incarnation against ONE shared directory (single-writer: only
    rank 0 commits)."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu import checkpoint as _checkpoint
    from mxnet_tpu.parallel import ShardedTrainStep

    mx.random.seed(7)
    net = gluon.nn.HybridSequential(prefix='drill_')
    with net.name_scope():
        net.add(gluon.nn.Dense(16, activation='relu', prefix='fc1_'),
                gluon.nn.Dense(2, prefix='fc2_'))
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    step = ShardedTrainStep(net, loss_fn, 'adam',
                            {'learning_rate': 0.05}, mesh=mesh)
    mgr = _checkpoint.CheckpointManager(
        ckpt_dir or os.path.join(workdir, f'ckpt-rank{rank}'),
        params=net, trainer=step, async_save=False,
        autosave_steps=autosave_steps,
        replication=None if replication else False)
    return net, step, mgr


def _run_step(step, i):
    from mxnet_tpu import nd
    x, y = _data_for(i)
    return float(step(nd.array(x), nd.array(y)).asnumpy())


def _worker(args):
    os.environ['JAX_PLATFORMS'] = 'cpu'
    import faulthandler
    faulthandler.register(signal.SIGUSR1)   # stacks on demand in CI
    import jax
    try:
        jax.config.update('jax_platforms', 'cpu')
    except Exception:
        pass
    from mxnet_tpu.parallel import dist, make_mesh
    from mxnet_tpu.resilience import ElasticController

    from .. import config as _config
    rank = max(0, _config.get('MXNET_TPU_PROC_ID'))
    progress = os.path.join(args.workdir, f'progress-rank{rank}.txt')
    dist.init()
    ms = dist.start_membership(port=args.port,
                               heartbeat_seconds=args.heartbeat,
                               deadline_seconds=args.deadline)
    mesh = make_mesh(devices=jax.local_devices())
    # disk-loss mode: ONE rank owns the checkpoint directory (the
    # standard multi-host pattern — payloads are host-gathered, one
    # writer suffices) and commits every step; every rank runs the
    # replica server, so the owner's commits land on its peers
    owner = args.ckpt_owner if args.disk_loss else None
    is_owner = owner is None or rank == owner
    net, step, mgr = _build(
        args.workdir, rank, mesh,
        autosave_steps=1 if (args.disk_loss and is_owner) else None,
        replication=args.disk_loss)
    ctl = ElasticController(manager=mgr, membership=ms, step=step,
                            commit_on_reform=is_owner)
    ctl.start_monitor()

    marks = {'rank': rank, 'start_wall': _time.time()}
    losses, post = {}, {}
    i = 0
    while i < args.steps:
        resumed = ctl.pre_step()
        if resumed is not None:
            marks['reform'] = ctl.last_reform
            marks['reform_done_wall'] = _time.time()
            marks['resumed_step'] = resumed
            marks['restore_source'] = mgr.last_restore_source
            i = int(resumed)
            continue
        t0 = _time.perf_counter()
        loss = _run_step(step, i + 1)
        dt = _time.perf_counter() - t0
        i += 1
        ctl.beat(i)
        if args.disk_loss and is_owner:
            mgr.maybe_save(i)
            if mgr.replica is not None:
                mgr.replica.wait(timeout=10.0)   # drill determinism only
        losses[i] = float(loss).hex()
        if 'reform' in marks:
            post[i] = float(loss).hex()
            marks.setdefault('first_resumed_step_seconds', dt)
            marks.setdefault('first_resumed_step_wall', _time.time())
        with open(progress, 'w') as f:
            f.write(str(i))
        if args.step_sleep:
            _time.sleep(args.step_sleep)
    ctl.stop_monitor()
    mgr.close()
    out = {'marks': marks, 'losses': losses, 'post': post,
           'world': ms.world_size(), 'reforms': ctl.reforms,
           'peer_losses': ctl.peer_losses}
    with open(os.path.join(args.workdir, f'result-rank{rank}.json'),
              'w') as f:
        json.dump(out, f, indent=1)
    ms.stop()


def _fleet_worker(args):
    """One rank of the fleet-observability drill (ISSUE 13): trains
    with telemetry + tracing armed and the /metrics //healthz //flight
    endpoint up, heartbeats carrying per-step telemetry snapshots.
    After its steps it commits a checkpoint, beats once more (so the
    coordinator's fleet view holds the FINAL per-rank comm totals),
    dumps its rank trace for the stitcher, then holds the endpoints up
    until the parent releases it — the parent's scrape window."""
    os.environ['JAX_PLATFORMS'] = 'cpu'
    import jax
    try:
        jax.config.update('jax_platforms', 'cpu')
    except Exception:
        pass
    from mxnet_tpu.parallel import dist, make_mesh
    from mxnet_tpu.telemetry import fleet, server

    from .. import config as _config
    rank = max(0, _config.get('MXNET_TPU_PROC_ID'))
    progress = os.path.join(args.workdir, f'progress-rank{rank}.txt')
    dist.init()            # membership + fleet attach + endpoint arm
    ms = dist.membership()
    assert ms is not None, "fleet drill needs MXTPU_ELASTIC=1"
    mesh = make_mesh(devices=jax.local_devices())
    net, step, mgr = _build(args.workdir, rank, mesh)
    slow_s = args.slow_ms / 1e3 if rank == args.slow_rank else 0.0
    for i in range(args.steps):
        loss = _run_step(step, i + 1)
        ms.current_step = i + 1
        if slow_s:
            _time.sleep(slow_s)
        if args.step_sleep:
            _time.sleep(args.step_sleep)
        with open(progress, 'w') as f:
            f.write(str(i + 1))
    mgr.save_now(args.steps)          # /healthz last_committed_step
    ms.beat()                         # final snapshot: last step+totals
    fleet.dump_rank_trace(
        os.path.join(args.workdir, f'trace-rank{rank}.json'), ms)
    out = {'rank': rank, 'steps': args.steps, 'loss': float(loss),
           'metrics_port': server.get().port if server.get() else None,
           'snapshot_bytes': fleet.snapshot_bytes(),
           'comm_bytes': fleet.comm_bytes_by_axis(),
           'clock_offset': ms.clock_offset()}
    if rank == 0:
        # wait for the straggler detector to flag the slow rank, then
        # capture the watchdog's ACTUAL stall-report text — the drill
        # asserts the verdict names the rank, not just that a flag is up
        deadline = _time.monotonic() + 30.0
        flagged = None
        while _time.monotonic() < deadline:
            mon = fleet.monitor()
            flagged = mon.straggler() if mon is not None else None
            if flagged is not None:
                break
            _time.sleep(0.05)
        out['straggler'] = flagged
        from .watchdog import StepWatchdog
        wd = StepWatchdog(deadline_seconds=9999.0, membership=ms)
        report = wd._format_report(1.0, args.steps)
        out['watchdog_verdict'] = next(
            (ln for ln in report.split('\n')
             if ln.startswith('verdict:')), '')
        mon = fleet.monitor()
        out['fleet_view'] = mon.view() if mon is not None else None
        from mxnet_tpu.telemetry import flight
        out['flight_events'] = flight.get().events()
    with open(os.path.join(args.workdir, f'result-rank{rank}.json'),
              'w') as f:
        json.dump(out, f, indent=1, default=str)
    release = os.path.join(args.workdir, 'release')
    deadline = _time.monotonic() + 90.0
    while not os.path.exists(release) and _time.monotonic() < deadline:
        _time.sleep(0.05)
    mgr.close()
    ms.stop()


def _prom_value(text, name, **labels):
    """Sum of a metric's samples in Prometheus exposition ``text``
    whose labels are a superset of ``labels`` (None: never seen)."""
    import re as _re
    total, seen = 0.0, False
    for line in text.splitlines():
        if not line.startswith(name) or line.startswith('#'):
            continue
        m = _re.match(r'^([a-z0-9_]+)(?:\{([^}]*)\})?\s+(\S+)$', line)
        if not m or m.group(1) != name:
            continue
        got = dict(_re.findall(r'(\w+)="([^"]*)"', m.group(2) or ''))
        if all(got.get(k) == str(v) for k, v in labels.items()):
            total += float(m.group(3))
            seen = True
    return total if seen else None


def _http_get(url, timeout=5.0):
    import urllib.error
    import urllib.request
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.read().decode()
    except urllib.error.HTTPError as e:
        # /healthz answers 503 when degraded — the body is the document
        return e.read().decode()


def run_fleet_drill(workdir, steps=8, heartbeat=0.2, step_sleep=0.1,
                    slow_rank=1, slow_ms=400, hang_seconds=1.0,
                    timeout=150.0):
    """Two-rank fleet-observability drill (ISSUE 13). Rank ``slow_rank``
    runs slower steps AND an armed ``dist.heartbeat:hang`` fault delays
    its beats, so both straggler signals (step-time skew, snapshot
    staleness) are live. Asserts:

    - /metrics, /healthz and /flight respond on BOTH ranks;
    - the coordinator's fleet view holds both ranks with per-rank skew;
    - the injected straggler is flagged (flight note + anomaly counter)
      and NAMED in the watchdog verdict line;
    - the coordinator's ``mxnet_tpu_fleet_comm_bytes`` gauge for the
      slow rank agrees EXACTLY with that rank's own per-hop
      ``mxnet_tpu_comm_collective_bytes_total`` scrape;
    - the two rank traces stitch (``tools/stitch_traces.py``) into one
      ``check_trace``-clean timeline.

    Returns the measured numbers for PERF_NOTES / dryrun_multichip."""
    os.makedirs(workdir, exist_ok=True)
    jax_port, side_port = _free_port(), _free_port()
    metrics_base = _free_port_base(2)
    env = dict(os.environ)
    env.update({
        'PYTHONPATH': os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))] +
            ([env['PYTHONPATH']] if env.get('PYTHONPATH') else [])),
        'JAX_PLATFORMS': 'cpu',
        'XLA_FLAGS': '--xla_force_host_platform_device_count=2',
        'MXNET_TPU_COORDINATOR': f'localhost:{jax_port}',
        'MXNET_TPU_NUM_PROCS': '2',
        'MXTPU_ELASTIC': '1',
        'MXTPU_ELASTIC_PORT': str(side_port),
        'MXTPU_HEARTBEAT_SECONDS': str(heartbeat),
        # deadline far above the beat-delay fault: the slow rank must
        # look STALE to the fleet detectors, never LOST to membership
        'MXTPU_PEER_DEADLINE_SECONDS': '60',
        'MXNET_TPU_TELEMETRY': '1',
        'MXTPU_TRACE': '1',
        'MXTPU_METRICS_PORT': str(metrics_base),
        'MXTPU_FLIGHT_DIR': workdir,
    })
    base = [sys.executable, '-m', 'mxnet_tpu.resilience.drill',
            '--fleet', '--workdir', workdir, '--steps', str(steps),
            '--port', str(side_port), '--heartbeat', str(heartbeat),
            '--step-sleep', str(step_sleep),
            '--slow-rank', str(slow_rank), '--slow-ms', str(slow_ms)]
    procs, logs = [], []
    for r in range(2):
        e = dict(env)
        e['MXNET_TPU_PROC_ID'] = str(r)
        if r == slow_rank and hang_seconds:
            e['MXTPU_FAULT'] = 'dist.heartbeat:hang'
            e['MXTPU_FAULT_HANG_SECONDS'] = str(hang_seconds)
        log = open(os.path.join(workdir, f'worker-rank{r}.log'), 'wb')
        logs.append(log)
        procs.append(subprocess.Popen(
            base, env=e, stdout=log, stderr=subprocess.STDOUT))

    def _fail(msg):
        for p in procs:
            if p.poll() is None:
                p.kill()
        errs = []
        for i, log in enumerate(logs):
            log.flush()
            try:
                with open(log.name, 'rb') as f:
                    errs.append(f"-- rank {i} log --\n" +
                                f.read().decode(errors='replace')[-3000:])
            except OSError:
                pass
        raise AssertionError(msg + '\n' + '\n'.join(errs))

    try:
        # readiness: both result files exist (written AFTER the final
        # beat + trace dump, so the scrape window sees steady state)
        deadline = _time.monotonic() + timeout
        results = {}
        while _time.monotonic() < deadline and len(results) < 2:
            for r in range(2):
                if r in results:
                    continue
                p = os.path.join(workdir, f'result-rank{r}.json')
                if os.path.exists(p):
                    try:
                        with open(p) as f:
                            results[r] = json.load(f)
                    except (OSError, ValueError):
                        pass
            if any(p.poll() not in (None, 0) for p in procs):
                _fail("fleet drill: a worker died")
            _time.sleep(0.1)
        if len(results) < 2:
            _fail("fleet drill: workers never reached the scrape window")

        ports = {r: metrics_base + r for r in range(2)}
        # 1. every endpoint answers on both ranks
        scraped = {}
        for r in range(2):
            url = f'http://127.0.0.1:{ports[r]}'
            scraped[r] = {
                'metrics': _http_get(url + '/metrics'),
                'healthz': json.loads(_http_get(url + '/healthz')),
                'flight': json.loads(_http_get(url + '/flight')),
            }
            assert 'mxnet_tpu_comm_collective_bytes_total' in \
                scraped[r]['metrics'], (r, scraped[r]['metrics'][:400])
            assert scraped[r]['flight'].get('steps'), \
                f"rank {r} /flight has no step records"
            assert scraped[r]['healthz'].get('last_committed_step') \
                == steps, scraped[r]['healthz']
        # 2. the coordinator's fleet view holds both ranks + skew
        hz0 = scraped[0]['healthz']
        fleet_view = hz0.get('fleet') or {}
        ranks = {int(k) for k in (fleet_view.get('ranks') or {})}
        assert ranks == {0, 1}, fleet_view
        vr = fleet_view['ranks']
        v1 = vr.get(str(slow_rank), vr.get(slow_rank))
        assert v1['step'] == steps, v1
        assert v1.get('skew_ms') is not None and v1['skew_ms'] > 0, v1
        # 3. the injected straggler is flagged and NAMED in the verdict
        r0 = results[0]
        assert r0.get('straggler') and \
            int(r0['straggler']['rank']) == slow_rank, r0.get('straggler')
        assert r0['straggler'].get('snapshot_age_seconds') is not None
        assert f'STRAGGLER SUSPECTED: rank {slow_rank}' in \
            r0.get('watchdog_verdict', ''), r0.get('watchdog_verdict')
        notes = [e for e in r0.get('flight_events', [])
                 if e.get('kind') == 'fleet.straggler'
                 and int(e.get('rank', -1)) == slow_rank]
        assert notes, "no fleet.straggler flight note for the slow rank"
        anomalies = _prom_value(scraped[0]['metrics'],
                                'mxnet_tpu_fleet_anomalies_total',
                                kind='fleet.straggler', rank=slow_rank)
        assert anomalies and anomalies >= 1, anomalies
        # 4. fleet comm gauge == the rank's own per-hop counter scrape
        own = results[slow_rank]['comm_bytes']
        assert own, "slow rank reported no comm bytes"
        agreement = {}
        for axis, nbytes in own.items():
            fleet_val = _prom_value(scraped[0]['metrics'],
                                    'mxnet_tpu_fleet_comm_bytes',
                                    rank=slow_rank, axis=axis)
            own_scrape = _prom_value(
                scraped[slow_rank]['metrics'],
                'mxnet_tpu_comm_collective_bytes_total', axis=axis)
            assert fleet_val == nbytes == own_scrape, \
                (axis, fleet_val, nbytes, own_scrape)
            agreement[axis] = int(nbytes)
        # 5. stitch the two rank traces into one clean timeline
        stitched = os.path.join(workdir, 'fleet_trace.json')
        tools_dir = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), 'tools')
        rc = subprocess.run(
            [sys.executable, os.path.join(tools_dir, 'stitch_traces.py'),
             '-o', stitched,
             os.path.join(workdir, 'trace-rank0.json'),
             os.path.join(workdir, 'trace-rank1.json')],
            capture_output=True, text=True, timeout=60)
        assert rc.returncode == 0, (rc.stdout, rc.stderr)
        rc2 = subprocess.run(
            [sys.executable, os.path.join(tools_dir, 'check_trace.py'),
             stitched],
            capture_output=True, text=True, timeout=60)
        assert rc2.returncode == 0, (rc2.stdout, rc2.stderr)
    finally:
        with open(os.path.join(workdir, 'release'), 'w') as f:
            f.write('done')
        for p in procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()
    return {
        'ok': True,
        'steps': steps,
        'slow_rank': slow_rank,
        'straggler': r0['straggler'],
        'watchdog_verdict': r0['watchdog_verdict'],
        'snapshot_bytes': {r: results[r]['snapshot_bytes']
                           for r in results},
        'comm_agreement': agreement,
        'skew_ms': v1['skew_ms'],
        'clock_offset': results[1].get('clock_offset'),
        'stitched': stitched,
        'healthz_status': {r: scraped[r]['healthz']['status']
                           for r in scraped},
    }


def run_oom_drill(workdir, steps_before=3):
    """OOM forensics drill (ISSUE 14) — no real 16 GB chip required.

    Trains the drill model a few steps with memory watermarking armed,
    then arms the deterministic ``alloc.oom`` fault so the NEXT pass
    through a guarded dispatch site raises a synthetic
    RESOURCE_EXHAUSTED through ``telemetry.memory.oom_guard``. Asserts
    the guard wrote exactly the post-mortem a real allocator
    exhaustion would:

    - the dump validates against the ``mxtpu_oom_v1`` schema,
    - it names the largest live tracked array (with shape/dtype/
      sharding) and carries the watermark ring + bucket analysis,
    - the ``memory.oom`` flight note landed.

    Returns the summary dict ``dryrun_multichip`` prints each
    MULTICHIP round. In-process (the fault is deterministic and the
    exception is caught here) — state is restored on exit."""
    import json

    from mxnet_tpu import config as _config
    from mxnet_tpu.telemetry import flight, memory, trace
    from . import faults

    prev_dir = _config.get('MXTPU_FLIGHT_DIR')
    was_mem, was_trace = memory.enabled(), trace.enabled()
    os.environ['MXTPU_FLIGHT_DIR'] = str(workdir)
    memory.clear()
    memory.enable()
    trace.enable()             # the memory.oom flight note needs the ring
    try:
        from mxnet_tpu.parallel.mesh import default_mesh
        _net, step, mgr = _build(str(workdir), 0, default_mesh())
        for i in range(steps_before):
            _run_step(step, i)
        analysis = step.memory_analysis()
        assert analysis is not None, "no memory_analysis after steps"
        # falsifiable: the buckets must measure THIS step's residency
        # (sum==peak alone holds by construction)
        assert analysis['buckets_bytes']['params'] \
            == step.param_bytes_per_device(), analysis
        assert analysis['buckets_bytes']['optimizer_state'] \
            == step.opt_state_bytes_per_device(), analysis
        faults.arm('alloc.oom', 'raise', window=1)
        err = None
        try:
            _run_step(step, steps_before)
        except faults.InjectedFault as e:
            err = e
        assert err is not None and err.site == 'alloc.oom', \
            "injected alloc.oom did not surface"
        path = memory.default_oom_path()
        assert os.path.exists(path), f"no forensics dump at {path}"
        with open(path) as f:
            doc = json.load(f)
        problems = memory.validate_oom_dump(doc)
        assert not problems, problems
        assert doc['top_arrays'], "dump names no live arrays"
        top = doc['top_arrays'][0]
        live = {}
        for pool in memory.pools().values():
            live.update(pool)
        biggest = max(memory.entry_nbytes(a) for a in live.values())
        peers = {n for n, a in live.items()
                 if memory.entry_nbytes(a) == biggest}
        # the dump's prime suspect IS the largest live allocation
        # (several arrays may tie at the same byte size)
        assert top['nbytes'] == biggest and top['name'] in peers, \
            (top, biggest, sorted(peers))
        notes = [e['kind'] for e in flight.get().events()
                 if e['kind'] == 'memory.oom']
        mgr.close()
        return {
            'ok': True,
            'path': path,
            'site': doc['site'],
            'top_array': {k: top[k] for k in
                          ('pool', 'name', 'nbytes') if k in top},
            'device_bytes': doc['device_bytes'],
            'peak_bytes': doc['peak_bytes'],
            'watermark_samples': len(doc['watermarks']),
            'hints': [h['action'] for h in doc['hints']],
            'flight_noted': bool(notes),
        }
    finally:
        faults.disarm('alloc.oom')
        memory.clear()
        (memory.enable if was_mem else memory.disable)()
        (trace.enable if was_trace else trace.disable)()
        if prev_dir:
            os.environ['MXTPU_FLIGHT_DIR'] = prev_dir
        else:
            os.environ.pop('MXTPU_FLIGHT_DIR', None)


def _reference(args):
    """Clean single-process resume: restore the survivor's committed
    checkpoint and train the remaining steps — the trajectory the
    survivor's post-re-form segment must match bit-for-bit."""
    os.environ['JAX_PLATFORMS'] = 'cpu'
    import jax
    try:
        jax.config.update('jax_platforms', 'cpu')
    except Exception:
        pass
    from mxnet_tpu.parallel import make_mesh

    mesh = make_mesh(devices=jax.local_devices())
    net, step, mgr = _build(args.workdir, args.ref_rank, mesh)
    start = mgr.restore_latest()
    losses = {}
    for i in range(int(start), args.steps):
        losses[i + 1] = float(_run_step(step, i + 1)).hex()
    with open(os.path.join(args.workdir, 'result-reference.json'),
              'w') as f:
        json.dump({'restored_step': start, 'losses': losses}, f, indent=1)
    mgr.close()


def _hosted_steps(nsdir):
    """Committed step numbers under one hosted-replica namespace dir."""
    try:
        import re
        return sorted(int(m.group(1)) for m in
                      (re.match(r'^step_(\d{10})$', n)
                       for n in os.listdir(nsdir)) if m)
    except OSError:
        return []


def _assert_dirs_bit_identical(a, b):
    """Every file under ``a`` must exist under ``b`` with identical
    bytes (and vice versa) — the replica-restore parity check."""
    def walk(root):
        out = {}
        for dirpath, _dirs, files in os.walk(root):
            for fn in files:
                p = os.path.join(dirpath, fn)
                out[os.path.relpath(p, root)] = p
        return out
    fa, fb = walk(a), walk(b)
    assert sorted(fa) == sorted(fb), (sorted(fa), sorted(fb))
    for rel in fa:
        with open(fa[rel], 'rb') as f1, open(fb[rel], 'rb') as f2:
            assert f1.read() == f2.read(), \
                f"{rel} differs between {a} and {b}"


def _wait_progress(path, target, timeout):
    deadline = _time.monotonic() + timeout
    while _time.monotonic() < deadline:
        try:
            with open(path) as f:
                if int(f.read().strip() or 0) >= target:
                    return True
        except (OSError, ValueError):
            pass
        _time.sleep(0.05)
    return False


def run_drill(workdir, steps=14, kill_at=3, heartbeat=0.2, deadline=1.2,
              step_sleep=0.35, timeout=180.0, victim_rank=1,
              disk_loss=False):
    """Run the two-worker SIGKILL drill. Returns a dict with the
    survivor's MTTR phase breakdown and the bit-parity verdict (raises
    AssertionError on any broken guarantee).

    ``disk_loss=True`` is the survivability variant (ISSUE 10): the
    victim rank OWNS the checkpoint directory (commits every step,
    replicated to the peer over the side channel) and its directory is
    **wiped before the SIGKILL** — so the survivor can only resume by
    fetching the newest replicated step from its own hosted replica,
    hash-verified, bit-identical to a clean local restore."""
    os.makedirs(workdir, exist_ok=True)
    jax_port, side_port = _free_port(), _free_port()
    replica_base = _free_port_base(2) if disk_loss else 0
    env = dict(os.environ)
    env.update({
        'PYTHONPATH': os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))] +
            ([env['PYTHONPATH']] if env.get('PYTHONPATH') else [])),
        'JAX_PLATFORMS': 'cpu',
        'XLA_FLAGS': '--xla_force_host_platform_device_count=2',
        'MXNET_TPU_COORDINATOR': f'localhost:{jax_port}',
        'MXNET_TPU_NUM_PROCS': '2',
        'MXTPU_ELASTIC': '1',
        # the membership knobs ride the env so dist.init()'s automatic
        # start_membership and the worker's explicit call agree
        'MXTPU_ELASTIC_PORT': str(side_port),
        'MXTPU_HEARTBEAT_SECONDS': str(heartbeat),
        'MXTPU_PEER_DEADLINE_SECONDS': str(deadline),
    })
    if disk_loss:
        env.update({
            # exercise the AUTO wiring: CheckpointManager attaches the
            # ReplicaManager itself off the membership world + env knobs
            'MXTPU_CHECKPOINT_REPLICAS': '1',
            'MXTPU_REPLICA_PORT_BASE': str(replica_base),
            'MXTPU_REPLICA_TIMEOUT_SECONDS': '5',
        })
    base = [sys.executable, '-m', 'mxnet_tpu.resilience.drill',
            '--workdir', workdir, '--steps', str(steps),
            '--port', str(side_port), '--heartbeat', str(heartbeat),
            '--deadline', str(deadline), '--step-sleep', str(step_sleep)]
    if disk_loss:
        base += ['--disk-loss', '--ckpt-owner', str(victim_rank)]
    procs, logs = [], []
    for r in range(2):
        e = dict(env)
        e['MXNET_TPU_PROC_ID'] = str(r)
        log = open(os.path.join(workdir, f'worker-rank{r}.log'), 'wb')
        logs.append(log)
        procs.append(subprocess.Popen(
            base + ['--worker'], env=e, stdout=log,
            stderr=subprocess.STDOUT))
    survivor_rank = 1 - victim_rank
    victim, survivor = procs[victim_rank], procs[survivor_rank]

    def _fail(msg):
        for p in procs:
            if p.poll() is None:
                p.kill()
        errs = []
        for i, log in enumerate(logs):
            log.flush()
            try:
                with open(log.name, 'rb') as f:
                    errs.append(f"-- rank {i} log --\n" +
                                f.read().decode(errors='replace')[-3000:])
            except OSError:
                pass
        raise AssertionError(msg + '\n' + '\n'.join(errs))

    # let both ranks make real progress before the kill
    for r in range(2):
        if not _wait_progress(
                os.path.join(workdir, f'progress-rank{r}.txt'),
                kill_at, timeout / 2):
            _fail(f"drill: rank {r} never reached step {kill_at}")
    victim_ckpt = os.path.join(workdir, f'ckpt-rank{victim_rank}')
    hosted = os.path.join(workdir, f'ckpt-rank{survivor_rank}',
                          '.replicas', f'rank{victim_rank}')
    if disk_loss:
        # the survivor must already hold a committed replica of the
        # owner's checkpoints before the disaster strikes
        deadline_t = _time.monotonic() + timeout / 2
        while _time.monotonic() < deadline_t:
            if _hosted_steps(hosted):
                break
            _time.sleep(0.05)
        else:
            _fail(f"drill: no committed replica under {hosted}")
        # the disaster: the preemption takes the owner's DISK with it —
        # wipe the whole checkpoint dir (local steps AND its replica
        # root), then SIGKILL. The survivor's only restore source is
        # now its own hosted replica.
        import shutil
        shutil.rmtree(victim_ckpt, ignore_errors=True)
    victim.kill()                       # SIGKILL: no goodbye, no flush
    kill_wall = _time.time()
    victim.wait()
    try:
        survivor.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        _fail("drill: survivor did not exit (re-form wedged?)")
    if survivor.returncode != 0:
        _fail(f"drill: survivor exited rc={survivor.returncode}")
    for log in logs:
        log.close()
    with open(os.path.join(workdir,
                           f'result-rank{survivor_rank}.json')) as f:
        res = json.load(f)
    marks = res['marks']
    assert res['reforms'] == 1 and res['peer_losses'] == 1, res
    assert marks.get('reform', {}).get('world') == 1, marks
    assert res['post'], "survivor recorded no post-re-form steps"
    if disk_loss:
        # the restore bytes must have come through the replica path
        # (there is no other source: the owner's dir was wiped and the
        # survivor never committed) — and the fetched local step must
        # be bit-identical to the hosted replica copy it came from
        src = marks.get('restore_source')
        assert src and src.startswith(f'hosted:rank{victim_rank}'), (
            "survivor did not restore from a peer replica", marks)
        resumed = int(marks['resumed_step'])
        _assert_dirs_bit_identical(
            os.path.join(workdir, f'ckpt-rank{survivor_rank}',
                         f'step_{resumed:010d}'),
            os.path.join(hosted, f'step_{resumed:010d}'))

    # reference: clean restore of the SAME committed checkpoint
    ref_cmd = base + ['--reference', '--ref-rank', str(survivor_rank)]
    e = dict(env)
    e['MXNET_TPU_NUM_PROCS'] = '1'
    e['MXNET_TPU_PROC_ID'] = '0'
    r = subprocess.run(ref_cmd, env=e, capture_output=True, timeout=timeout)
    assert r.returncode == 0, r.stderr.decode(errors='replace')[-3000:]
    with open(os.path.join(workdir, 'result-reference.json')) as f:
        ref = json.load(f)
    assert ref['restored_step'] == marks['resumed_step'], (ref, marks)
    assert res['post'] == ref['losses'], (
        "post-re-form trajectory diverges from a clean restore of the "
        "same checkpoint", res['post'], ref['losses'])

    reform = marks['reform']
    detect_seconds = round(
        marks['reform_done_wall'] - reform['reform_seconds'] - kill_wall, 3)
    mttr = {
        'detect_seconds': detect_seconds,
        'commit_seconds': reform['commit_seconds'],
        'teardown_seconds': reform['teardown_seconds'],
        'restore_seconds': reform['restore_seconds'],
        'reform_seconds': reform['reform_seconds'],
        'first_resumed_step_seconds': round(
            marks.get('first_resumed_step_seconds', 0.0), 3),
        'total_seconds': round(
            marks.get('first_resumed_step_wall',
                      marks['reform_done_wall']) - kill_wall, 3),
    }
    assert detect_seconds <= deadline + max(
        4 * heartbeat, 1.0) + step_sleep + 1.0, (
        f"peer loss detected {detect_seconds}s after the kill — past "
        f"the {deadline}s deadline budget", mttr)
    return {
        'ok': True,
        'committed_step': marks['resumed_step'],
        'post_steps': len(res['post']),
        'bit_identical': True,
        'deadline_seconds': deadline,
        'disk_loss': bool(disk_loss),
        'restore_source': marks.get('restore_source'),
        'mttr': mttr,
    }


# ---------------------------------------------------------------------------
# churn-storm drill (elastic scale-UP): randomized kill/join cycles

_CHURN_SAMPLES = 64      # dataset size behind the ElasticShard
_CHURN_BATCH = 8         # GLOBAL batch — fixed across every world size
_CHURN_SEED = 11         # shard shuffle seed (shared by every process)


class _FileCapacityProvider:
    """The drill's ``CapacityProvider``: decisions land in a JSONL
    ledger the parent process — the drill's 'scheduler' — tails.
    Granted capacity arrives later as a fresh worker process announcing
    JOIN on the side channel, which closes the autoscaler's
    loss -> request -> join -> admit loop with real processes."""

    def __init__(self, path):
        self.path = path

    def request_capacity(self, count, reason):
        self._append({'count': int(count), 'reason': reason})

    def evict(self, rank, reason):
        self._append({'evict': int(rank), 'reason': reason})

    def _append(self, doc):
        doc['wall'] = _time.time()
        with open(self.path, 'a') as f:
            f.write(json.dumps(doc) + '\n')
            f.flush()
            os.fsync(f.fileno())


def _churn_sync(ms, ctl, target):
    """Emulate the collective's step barrier on the side channel: block
    until every OTHER alive rank reports ``target`` done (beats
    piggyback the step counter). Returns False — caller re-enters
    ``pre_step`` — the moment a peer is lost or a JOIN lands, exactly
    when a real collective would abort. Without this lockstep an
    unsynchronized survivor could commit a world-2 step whose partner
    half was never consumed: a silently dropped sample."""
    while True:
        if ms.lost_peers() or ctl._pending_joins(ms):
            return False
        view = ms.view() or {}
        steps = {int(r): int(s)
                 for r, s in (view.get('steps') or {}).items()}
        peers = [int(r) for r in view.get('alive', ())
                 if int(r) != ms.rank]
        if all(steps.get(r, 0) >= target for r in peers):
            return True
        _time.sleep(0.02)


def _churn_worker(args):
    """One churn-drill rank (founding member or JOIN incarnation).

    The data-plane discipline that makes exactly-once provable from the
    on-disk records: each rank appends (step, position, ids) to its
    sample ledger and fsyncs BEFORE beating the step — so a survivor
    can only have committed a world-2 step if the partner's consumption
    record for it is already on disk. A step whose barrier aborts (peer
    lost / JOIN pending) is rolled back (``last_step`` retreats to the
    last synced step) and re-run after the re-form; replaying the
    ledgers is last-record-wins per step."""
    os.environ['JAX_PLATFORMS'] = 'cpu'
    import jax
    try:
        jax.config.update('jax_platforms', 'cpu')
    except Exception:
        pass
    from mxnet_tpu.io import ElasticShard
    from mxnet_tpu.parallel import dist, make_mesh
    from mxnet_tpu.resilience import Autoscaler, ElasticController

    rank, tag = args.rank, args.tag
    ms = dist.Membership(rank, 2, port=args.port,
                         heartbeat_seconds=args.heartbeat,
                         deadline_seconds=args.deadline)
    mesh = make_mesh(devices=jax.local_devices())
    is_owner = rank == 0
    net, step, mgr = _build(args.workdir, rank, mesh,
                            autosave_steps=1 if is_owner else None,
                            ckpt_dir=os.path.join(args.workdir,
                                                  'ckpt-shared'))
    ctl = ElasticController(manager=mgr, membership=ms, step=step,
                            commit_on_reform=is_owner)
    holder = {'shard': ElasticShard(_CHURN_SAMPLES, _CHURN_BATCH,
                                    rank=rank, world=2,
                                    seed=_CHURN_SEED)}
    scaler = None
    if is_owner:
        # the commit manifest carries the data position: any later
        # incarnation reshards from it at its new (rank, world)
        mgr.bind_data_state(lambda: holder['shard'].state())
        scaler = Autoscaler(
            membership=ms,
            provider=_FileCapacityProvider(
                os.path.join(args.workdir, 'capacity-requests.jsonl')),
            target_world=2, cooldown_seconds=1.0, strikes=3)
    progress = os.path.join(args.workdir, f'progress-{tag}.txt')
    release = os.path.join(args.workdir, 'churn-release')
    samples = open(os.path.join(args.workdir, f'samples-{tag}.jsonl'),
                   'a')
    marks = {'tag': tag, 'rank': rank, 'start_wall': _time.time()}
    reforms, losses = [], {}

    def _reseed():
        meta = mgr.last_restored_metadata or {}
        assert meta.get('data'), \
            f"restored manifest carries no data position: {meta}"
        holder['shard'] = ElasticShard.from_state(
            meta['data'], rank=ctl.last_reform['rank'],
            world=ctl.last_reform['world'])

    def _note_progress(done):
        with open(progress, 'w') as f:
            f.write(str(done))

    i = 0
    if args.join:
        resumed = ctl.join()
        marks['admitted_wall'] = _time.time()
        reforms.append(dict(ctl.last_reform,
                            wall=marks['admitted_wall']))
        i = int(resumed or 0)
        _reseed()
        _note_progress(i)
        _atomic_json(os.path.join(args.workdir, f'admitted-{tag}.json'),
                     {'tag': tag, 'resumed': i,
                      'admitted_wall': marks['admitted_wall'],
                      'reform': dict(ctl.last_reform)})
    ctl.start_monitor()
    while True:
        if i >= args.steps:
            if not is_owner:
                break
            # tail guard: the owner keeps its coordinator seat (still
            # servicing admissions + the autoscaler loop) until the
            # parent releases it — a joiner spawned for a late kill
            # must find a live rendezvous even after training is done
            if os.path.exists(release):
                break
        if is_owner:
            scaler.observe()
            if ctl._pending_joins(ms):
                scaler.observe()    # a JOIN landed since the poll
                                    # above: ledger the admit decision
                                    # pre_step is about to honor
        resumed = ctl.pre_step()
        if resumed is not None:
            reforms.append(dict(ctl.last_reform, wall=_time.time()))
            i = int(resumed)
            _reseed()
            continue
        if i >= args.steps:
            _time.sleep(0.05)
            continue
        shard = holder['shard']
        pos = shard.position
        ids = [int(x) for x in shard.next_batch()]
        loss = _run_step(step, i + 1)
        # the consumption record must hit the disk BEFORE the beat that
        # publishes the step: a SIGKILL can then never yield a
        # committed step whose partner block went unrecorded
        samples.write(json.dumps({'step': i + 1, 'position': int(pos),
                                  'ids': ids, 'rank': shard.rank,
                                  'world': shard.world}) + '\n')
        samples.flush()
        os.fsync(samples.fileno())
        ctl.beat(i + 1)
        if not _churn_sync(ms, ctl, i + 1):
            # barrier aborted (peer lost / JOIN pending): the step is
            # NOT committed — retreat to the last synced step so the
            # re-form's commit + restore replays it
            ctl.last_step = i
            continue
        i += 1
        losses[i] = float(loss).hex()
        if is_owner:
            mgr.maybe_save(i)
        _note_progress(i)
        if args.step_sleep:
            _time.sleep(args.step_sleep)
    ctl.stop_monitor()
    samples.close()
    out = {'marks': marks, 'losses': losses, 'reforms': reforms,
           'world': ms.world_size(), 'peer_losses': ctl.peer_losses}
    if is_owner:
        out['autoscaler'] = scaler.decisions
    _atomic_json(os.path.join(args.workdir, f'result-{tag}.json'), out)
    mgr.close()
    ms.stop()


def _churn_baseline(args):
    """Fixed-world reference: one process, no churn, same model and
    per-step data — the trajectory every churn survivor must match
    bit-for-bit."""
    os.environ['JAX_PLATFORMS'] = 'cpu'
    import jax
    try:
        jax.config.update('jax_platforms', 'cpu')
    except Exception:
        pass
    from mxnet_tpu.parallel import make_mesh

    mesh = make_mesh(devices=jax.local_devices())
    bdir = os.path.join(args.workdir, 'baseline')
    os.makedirs(bdir, exist_ok=True)
    net, step, mgr = _build(bdir, 0, mesh)
    losses = {}
    for i in range(args.steps):
        losses[i + 1] = float(_run_step(step, i + 1)).hex()
    mgr.close()
    _atomic_json(os.path.join(args.workdir, 'result-baseline.json'),
                 {'losses': losses})


def run_churn_drill(workdir, steps=30, cycles=3, heartbeat=0.15,
                    deadline=1.2, step_sleep=0.2, seed=23,
                    timeout=420.0):
    """Churn storm (elastic scale-UP acceptance): ``cycles`` randomized
    SIGKILL + rejoin rounds against a two-rank elastic world, then
    prove the storm was harmless:

    1. the owner's loss trajectory is bit-identical to a fixed-world
       run that was never interrupted;
    2. data exactly-once: replaying every incarnation's consumption
       ledger (pruned to each cycle's committed rollback point) covers
       every global batch exactly once — no sample dropped, none seen
       twice — and every record's block matches the deterministic
       world-indexed assignment at its recorded position;
    3. the re-form ledger shows one shrink + one admission per cycle,
       and the autoscaler requested + admitted capacity each time.

    Kill steps are randomized-but-deterministic via the fault
    registry's hash stream (``faults._unit(seed, cycle)``). Returns
    per-cycle MTTR phases (detect / request / rendezvous / admission /
    full restore-world time) for PERF_NOTES."""
    from .faults import _unit
    os.makedirs(workdir, exist_ok=True)
    side_port = _free_port()
    env = dict(os.environ)
    env.update({
        'PYTHONPATH': os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))] +
            ([env['PYTHONPATH']] if env.get('PYTHONPATH') else [])),
        'JAX_PLATFORMS': 'cpu',
        'XLA_FLAGS': '--xla_force_host_platform_device_count=2',
        # process-local meshes by construction: the membership side
        # channel is the only cross-process link (no jax.distributed)
        'MXNET_TPU_NUM_PROCS': '1',
        'MXNET_TPU_PROC_ID': '0',
        'MXTPU_ELASTIC': '0',
    })
    env.pop('MXNET_TPU_COORDINATOR', None)
    base = [sys.executable, '-m', 'mxnet_tpu.resilience.drill',
            '--workdir', workdir, '--steps', str(steps),
            '--port', str(side_port), '--heartbeat', str(heartbeat),
            '--deadline', str(deadline),
            '--step-sleep', str(step_sleep)]
    req_path = os.path.join(workdir, 'capacity-requests.jsonl')
    procs, logs = {}, []

    def _spawn(tag, rank, join=False):
        log = open(os.path.join(workdir, f'worker-{tag}.log'), 'wb')
        logs.append(log)
        cmd = base + ['--churn-worker', '--rank', str(rank),
                      '--tag', tag] + (['--join'] if join else [])
        procs[tag] = subprocess.Popen(cmd, env=env, stdout=log,
                                      stderr=subprocess.STDOUT)

    def _fail(msg):
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        errs = []
        for log in logs:
            log.flush()
            try:
                with open(log.name, 'rb') as f:
                    errs.append(f"-- {os.path.basename(log.name)} --\n"
                                + f.read().decode(
                                    errors='replace')[-3000:])
            except OSError:
                pass
        raise AssertionError(msg + '\n' + '\n'.join(errs))

    def _requests():
        try:
            with open(req_path) as f:
                return [json.loads(ln) for ln in f if ln.strip()]
        except OSError:
            return []

    # randomized-but-deterministic kill schedule: cycle c kills inside
    # the c-th slice of the step budget so cycles never collide
    lo = 3
    span = max(1, (max(4, steps - 4) - lo) // cycles)
    kill_steps = [lo + c * span + int(_unit(seed, c) * span)
                  for c in range(cycles)]

    _spawn('r0', 0)
    _spawn('r1c0', 1)
    cycle_stats = []
    last_resumed = 0
    try:
        for c in range(cycles):
            victim = f'r1c{c}'
            target = min(steps - 2,
                         max(kill_steps[c], last_resumed + 2))
            for tag in ('r0', victim):
                if not _wait_progress(
                        os.path.join(workdir, f'progress-{tag}.txt'),
                        target, timeout / 2):
                    _fail(f"churn: {tag} never reached step {target} "
                          f"(cycle {c})")
            nreq = len(_requests())
            procs[victim].kill()        # SIGKILL mid-step, no flush
            kill_wall = _time.time()
            procs[victim].wait()
            # the autoscaler inside rank 0 must notice the shrink and
            # ask this parent — its capacity provider — for a new rank
            deadline_t = _time.monotonic() + timeout / 4
            while _time.monotonic() < deadline_t:
                if len(_requests()) > nreq:
                    break
                if procs['r0'].poll() is not None:
                    _fail(f"churn: rank 0 died during cycle {c}")
                _time.sleep(0.05)
            else:
                _fail(f"churn: autoscaler never requested capacity "
                      f"after kill {c}")
            request_wall = float(_requests()[-1]['wall'])
            joiner = f'r1c{c + 1}'
            spawn_wall = _time.time()
            _spawn(joiner, 1, join=True)
            admit_path = os.path.join(workdir,
                                      f'admitted-{joiner}.json')
            while _time.monotonic() < deadline_t:
                if os.path.exists(admit_path):
                    break
                if procs[joiner].poll() is not None:
                    _fail(f"churn: joiner {joiner} died before "
                          f"admission")
                _time.sleep(0.05)
            else:
                _fail(f"churn: {joiner} was never admitted")
            with open(admit_path) as f:
                admitted = json.load(f)
            last_resumed = int(admitted['resumed'])
            cycle_stats.append({
                'cycle': c, 'kill_step': target,
                'kill_wall': kill_wall,
                'request_wall': request_wall,
                'spawn_wall': spawn_wall,
                'admitted_wall': float(admitted['admitted_wall']),
                'resumed': last_resumed,
            })
        last_tag = f'r1c{cycles}'
        try:
            rc = procs[last_tag].wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            _fail(f"churn: {last_tag} never finished")
        if rc != 0:
            _fail(f"churn: {last_tag} exited rc={rc}")
        # release the owner's tail guard now every joiner is through
        with open(os.path.join(workdir, 'churn-release'), 'w') as f:
            f.write('done')
        try:
            rc = procs['r0'].wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            _fail("churn: rank 0 never finished")
        if rc != 0:
            _fail(f"churn: rank 0 exited rc={rc}")
        # fixed-world reference trajectory
        r = subprocess.run(
            base + ['--churn-baseline'], env=env,
            capture_output=True, timeout=timeout)
        if r.returncode != 0:
            _fail("churn: baseline run failed\n" +
                  r.stdout.decode(errors='replace')[-3000:] +
                  r.stderr.decode(errors='replace')[-3000:])
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        for log in logs:
            log.close()

    with open(os.path.join(workdir, 'result-r0.json')) as f:
        r0 = json.load(f)
    with open(os.path.join(workdir, 'result-baseline.json')) as f:
        ref = json.load(f)

    # 1. loss parity: the churned trajectory IS the fixed-world one
    assert r0['losses'] == ref['losses'], (
        "churned trajectory diverges from the fixed-world run",
        {k: (r0['losses'].get(k), ref['losses'].get(k))
         for k in set(r0['losses']) | set(ref['losses'])
         if r0['losses'].get(k) != ref['losses'].get(k)})

    # 2. the re-form ledger: one shrink + one admission per cycle
    shrinks = [rf for rf in r0['reforms'] if rf.get('lost')]
    grows = [rf for rf in r0['reforms'] if rf.get('grow')]
    assert len(shrinks) == cycles and len(grows) == cycles, \
        r0['reforms']

    # 3. exactly-once coverage replayed from the consumption ledgers
    from ..io.io import ElasticShard
    exp = ElasticShard(_CHURN_SAMPLES, _CHURN_BATCH, rank=0, world=1,
                       seed=_CHURN_SEED)

    def _records(tag):
        out = {}
        try:
            with open(os.path.join(workdir,
                                   f'samples-{tag}.jsonl')) as f:
                for ln in f:
                    ln = ln.strip()
                    if not ln:
                        continue
                    try:
                        rec = json.loads(ln)
                    except ValueError:
                        continue   # torn final line of a SIGKILL
                    out[int(rec['step'])] = rec   # last record wins
        except OSError:
            pass
        return out

    recs, prune = {'r0': _records('r0')}, {}
    for c in range(cycles + 1):
        tag = f'r1c{c}'
        recs[tag] = _records(tag)
        if c < cycles:
            # a dead incarnation's records past the shrink re-form's
            # committed rollback point were never part of the
            # trajectory: the survivor re-ran those steps itself
            prune[tag] = int(shrinks[c]['resumed_step'])
    for s in range(1, steps + 1):
        base_pos = (s - 1) * _CHURN_BATCH
        want = [int(exp.sample_at(base_pos + j))
                for j in range(_CHURN_BATCH)]
        got = []
        for tag, rs in sorted(recs.items()):
            rec = rs.get(s)
            if rec is None or (tag in prune and s > prune[tag]):
                continue
            per = _CHURN_BATCH // int(rec['world'])
            blk = int(rec['rank']) * per
            assert rec['ids'] == want[blk:blk + per], (
                f"step {s}: {tag} consumed the wrong block", rec, want)
            assert int(rec['position']) == base_pos, (s, rec)
            got.extend(rec['ids'])
        assert sorted(got) == sorted(want), (
            f"step {s}: global batch not covered exactly once",
            {'missing': sorted(set(want) - set(got)),
             'extra': sorted({x for x in got if got.count(x) > 1})})

    # 4. the autoscaler drove every recovery
    ledger = r0.get('autoscaler') or []
    n_req = sum(1 for d in ledger if d['kind'] == 'request_capacity')
    n_adm = sum(1 for d in ledger if d['kind'] == 'admit')
    assert n_req >= cycles and n_adm >= cycles, ledger

    mttr = []
    for c, st in enumerate(cycle_stats):
        shrink, grow = shrinks[c], grows[c]
        mttr.append({
            'cycle': c, 'kill_step': st['kill_step'],
            'detect_seconds': round(
                shrink['wall'] - shrink['reform_seconds']
                - st['kill_wall'], 3),
            'shrink_reform_seconds': shrink['reform_seconds'],
            'request_seconds': round(
                st['request_wall'] - st['kill_wall'], 3),
            'spawn_seconds': round(
                st['spawn_wall'] - st['kill_wall'], 3),
            'rendezvous_seconds': grow['rendezvous_seconds'],
            'admission_seconds': grow['admission_seconds'],
            'restored_world_seconds': round(
                st['admitted_wall'] - st['kill_wall'], 3),
        })
    return {
        'ok': True, 'steps': steps, 'cycles': cycles,
        'kill_steps': [st['kill_step'] for st in cycle_stats],
        'loss_parity': True, 'coverage_exact': True,
        'autoscaler': {'requests': n_req, 'admits': n_adm,
                       'decisions': len(ledger)},
        'mttr': mttr,
    }


def _serve_model():
    """The drill's serving model: tiny token-in/logits-out block. Every
    process builds it identically (auto-named — the jit boundary is
    name-stable, PR 17 satellite), so a checkpoint pushed from one
    process loads into another's block by parameter name."""
    from mxnet_tpu.gluon import nn

    class TinyTok(nn.HybridBlock):
        def __init__(self, vocab=64, dim=8, classes=4, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = nn.Embedding(vocab, dim)
                self.proj = nn.Dense(classes, flatten=False)

        def forward(self, x):
            return self.proj(self.embed(x))

    net = TinyTok()
    net.initialize()
    return net


_SERVE_WORLD = 3      # rank 0 = the router/observer, ranks 1..2 serve


def _serving_worker(args):
    """One serving replica of the drain drill: membership rank
    ``args.rank`` of a 3-rank view (rank 0 is the parent's router),
    warmup through the SHARED persistent compile cache, then a
    PredictServer + hosted ReplicaServer until drained (SIGTERM or
    POST /drain)."""
    os.environ['JAX_PLATFORMS'] = 'cpu'
    from mxnet_tpu import serving
    from mxnet_tpu.parallel import dist
    from mxnet_tpu.telemetry import compile as _compile

    rank = args.rank
    _compile.enable()
    ms = dist.Membership(rank, _SERVE_WORLD, port=args.port,
                         heartbeat_seconds=args.heartbeat,
                         deadline_seconds=args.deadline)
    net = _serve_model()
    engine = serving.InferenceEngine(
        serving.BlockRunner(net), seq_buckets='8,16',
        batch_buckets='1,2,4', deadline_ms=2.0)
    warm = serving.warmup(engine)
    ledger_after_warmup = len(_compile.ledger())
    store = os.path.join(args.workdir, f'store-rank{rank}')
    rs = dist.ReplicaServer(store, port=args.replica_base + rank)
    srv = serving.PredictServer(engine, port=args.serve_base + rank,
                                membership=ms, block=net,
                                replica_root=store)
    srv.install_sigterm()
    ready = {'rank': rank, 'serve_port': srv.port,
             'replica_port': args.replica_base + rank, 'warmup': warm}
    _atomic_json(os.path.join(args.workdir, f'ready-rank{rank}.json'),
                 ready)
    while not srv.draining.is_set():
        _time.sleep(0.05)
    # drain() flushed the engine + left the membership; wait for the
    # listener to retire (drain's final stop()) then report and exit
    deadline = _time.monotonic() + 30.0
    while srv._server is not None and _time.monotonic() < deadline:
        _time.sleep(0.05)
    out = {'rank': rank, 'stats': engine.stats(),
           'ledger_after_warmup': ledger_after_warmup,
           'ledger_final': len(_compile.ledger()),
           'reloaded_step': srv.reloaded_step}
    _atomic_json(os.path.join(args.workdir, f'result-rank{rank}.json'),
                 out)
    rs.stop()
    ms.stop()


def _atomic_json(path, doc):
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(doc, f, indent=1, default=str)
    os.replace(tmp, path)


def run_serving_drill(workdir, requests=90, kill_rank=1, heartbeat=0.1,
                      deadline=2.0, timeout=180.0):
    """Two-replica serving drain drill (ISSUE 17).

    Spawns 2 replica processes (membership ranks 1..2; this process is
    rank 0, the router's observer seat) sharing one persistent compile
    cache dir, storms the fleet through the ``serving.Router``, and
    ``SIGTERM``s rank ``kill_rank`` mid-storm. Asserts:

    - both replicas warmed their full bucket grid before the first
      request (and the SECOND replica's warmup rode the first's
      persistent cache);
    - the storm finishes with **zero failed requests** — predicts that
      hit the dying replica fail over inside the router;
    - zero steady-state recompiles on the survivor (compile ledger is
      flat after warmup);
    - the drained replica LEAVES the membership (a departure, not a
      loss) and the router's set drops it — MTTR is measured from the
      SIGTERM to the router no longer holding the dead rank;
    - a weight push (replica transport + POST /reload) lands on the
      survivor and its predictions flip to the pushed weights exactly.

    Returns the measured numbers for PERF_NOTES / dryrun_multichip."""
    import threading

    import numpy as onp

    from mxnet_tpu import nd, serving
    from mxnet_tpu.parallel import dist

    os.makedirs(workdir, exist_ok=True)
    side_port = _free_port()
    serve_base = _free_port_base(_SERVE_WORLD)
    replica_base = _free_port_base(_SERVE_WORLD)
    cache_dir = os.path.join(workdir, 'xla_cache')
    env = dict(os.environ)
    env.update({
        'PYTHONPATH': os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))] +
            ([env['PYTHONPATH']] if env.get('PYTHONPATH') else [])),
        'JAX_PLATFORMS': 'cpu',
        'MXNET_TPU_TELEMETRY': '1',
        'MXTPU_COMPILE_CACHE_DIR': cache_dir,
        'MXTPU_FLIGHT_DIR': workdir,
    })
    ms = dist.Membership(0, _SERVE_WORLD, port=side_port,
                         heartbeat_seconds=heartbeat,
                         deadline_seconds=deadline)
    base = [sys.executable, '-m', 'mxnet_tpu.resilience.drill',
            '--serve', '--workdir', workdir, '--port', str(side_port),
            '--serve-base', str(serve_base),
            '--replica-base', str(replica_base),
            '--heartbeat', str(heartbeat), '--deadline', str(deadline)]
    procs, logs = {}, []

    def _spawn(r):
        log = open(os.path.join(workdir, f'serve-rank{r}.log'), 'wb')
        logs.append(log)
        procs[r] = subprocess.Popen(base + ['--rank', str(r)], env=env,
                                    stdout=log, stderr=subprocess.STDOUT)

    def _fail(msg):
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        errs = []
        for log in logs:
            log.flush()
            try:
                with open(log.name, 'rb') as f:
                    errs.append(f"-- {log.name} --\n" +
                                f.read().decode(errors='replace')[-3000:])
            except OSError:
                pass
        raise AssertionError(msg + '\n' + '\n'.join(errs))

    def _wait_ready(ready, r, t0):
        while _time.monotonic() - t0 < timeout and r not in ready:
            p = os.path.join(workdir, f'ready-rank{r}.json')
            if os.path.exists(p):
                try:
                    with open(p) as f:
                        ready[r] = json.load(f)
                    break
                except (OSError, ValueError):
                    pass
            if procs[r].poll() is not None:
                _fail(f"serving drill: rank {r} died before ready")
            _time.sleep(0.05)
        if r not in ready:
            _fail(f"serving drill: rank {r} never finished warmup")

    try:
        # replica 1 warms COLD (pays every XLA compile into the shared
        # cache dir), then replica 2 starts and warms WARM — the
        # persistent-cache startup win, measured
        ready, t0 = {}, _time.monotonic()
        _spawn(1)
        _wait_ready(ready, 1, t0)
        _spawn(2)
        _wait_ready(ready, 2, t0)
        for r in (1, 2):
            assert ready[r]['warmup']['buckets'], ready[r]
        assert ready[2]['warmup']['cache']['hits'] > 0, \
            f"warm replica never hit the persistent cache: {ready[2]}"
        survivor = 3 - kill_rank

        # storm through the router; SIGTERM kill_rank a third in
        router = serving.Router(membership=ms, serve_port_base=serve_base,
                                timeout=30.0)
        rng = onp.random.RandomState(7)
        storm = [[int(v) for v in rng.randint(0, 64, rng.randint(1, 17))]
                 for _ in range(requests)]
        failures, t_kill = [], [None]
        lock = threading.Lock()

        def _client(i, seq):
            if i == requests // 3 and t_kill[0] is None:
                with lock:
                    if t_kill[0] is None:
                        t_kill[0] = _time.monotonic()
                        procs[kill_rank].send_signal(signal.SIGTERM)
            try:
                out = router.predict(seq)
                assert len(out) == len(seq), (len(out), len(seq))
            except Exception as e:                    # noqa: BLE001
                failures.append((i, repr(e)))

        threads = [threading.Thread(target=_client, args=(i, s))
                   for i, s in enumerate(storm)]
        for i, t in enumerate(threads):
            t.start()
            if i % 8 == 7:
                _time.sleep(0.02)      # a storm, not one thundering herd
        for t in threads:
            t.join(timeout=60)
        assert not failures, \
            f"{len(failures)} predicts failed: {failures[:5]}"
        assert t_kill[0] is not None, "the kill point never fired"

        # MTTR: SIGTERM -> router no longer holds the drained rank
        t0 = _time.monotonic()
        while _time.monotonic() - t0 < 30.0:
            router.refresh()
            with router._lock:
                gone = kill_rank not in router._replicas
            if gone:
                break
            _time.sleep(0.02)
        assert gone, "router never dropped the drained replica"
        mttr = _time.monotonic() - t_kill[0]
        view = ms.view()
        assert kill_rank in (view.get('left') or []), \
            f"drained rank should be a DEPARTURE, view={view}"

        # weight push: new weights reach the survivor over the replica
        # transport and flip its predictions exactly
        net = _serve_model()
        probe = [1, 2, 3, 5, 7]
        want = onp.asarray(net(nd.array(
            onp.asarray([probe + [0] * 3], 'int32'))).asnumpy())[0, :5]
        push = serving.push_weights(
            net, step=7,
            replicas=[{'host': '127.0.0.1',
                       'replica_port': replica_base + survivor,
                       'serve_port': serve_base + survivor}])
        res = push[serve_base + survivor]
        assert res.get('status') == 200, push
        got = onp.asarray(router.predict(probe), onp.float64)
        assert onp.allclose(got, want, atol=1e-5), (got, want)

        # graceful drain of the survivor ends the exercise
        status, _doc = serving.http_json(
            '127.0.0.1', serve_base + survivor, '/drain', {})
        assert status == 200, status
        results = {}
        t0 = _time.monotonic()
        while _time.monotonic() - t0 < 60.0 and len(results) < 2:
            for r in (1, 2):
                if r in results:
                    continue
                p = os.path.join(workdir, f'result-rank{r}.json')
                if os.path.exists(p):
                    try:
                        with open(p) as f:
                            results[r] = json.load(f)
                    except (OSError, ValueError):
                        pass
            _time.sleep(0.05)
        if len(results) < 2:
            _fail("serving drill: replicas never wrote results")
        for r in (1, 2):
            assert results[r]['ledger_final'] == \
                results[r]['ledger_after_warmup'], \
                f"rank {r} recompiled post-warmup: {results[r]}"
        assert results[survivor]['reloaded_step'] == 7, results[survivor]
        for r, p in procs.items():
            try:
                rc = p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                _fail(f"serving drill: rank {r} never exited")
            assert rc == 0, f"rank {r} exited {rc}"
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        for log in logs:
            log.close()
        ms.stop()
    served = {r: results[r]['stats'] for r in results}
    return {
        'ok': True,
        'requests': requests,
        'failed': 0,
        'failovers': router.failovers,
        'mttr_seconds': round(mttr, 4),
        'warmup': {r: ready[r]['warmup'] for r in ready},
        'stats': served,
        'reloaded_step': results[survivor]['reloaded_step'],
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--worker', action='store_true')
    ap.add_argument('--fleet', action='store_true')
    ap.add_argument('--serve', action='store_true')
    ap.add_argument('--rank', type=int, default=1)
    ap.add_argument('--serve-base', type=int, default=0)
    ap.add_argument('--replica-base', type=int, default=0)
    ap.add_argument('--slow-rank', type=int, default=1)
    ap.add_argument('--slow-ms', type=float, default=0.0)
    ap.add_argument('--reference', action='store_true')
    ap.add_argument('--workdir', required=True)
    ap.add_argument('--steps', type=int, default=10)
    ap.add_argument('--port', type=int, default=0)
    ap.add_argument('--heartbeat', type=float, default=0.2)
    ap.add_argument('--deadline', type=float, default=1.2)
    ap.add_argument('--step-sleep', type=float, default=0.35)
    ap.add_argument('--ref-rank', type=int, default=0)
    ap.add_argument('--disk-loss', action='store_true')
    ap.add_argument('--ckpt-owner', type=int, default=None)
    ap.add_argument('--churn-worker', action='store_true')
    ap.add_argument('--churn-baseline', action='store_true')
    ap.add_argument('--join', action='store_true')
    ap.add_argument('--tag', default='')
    args = ap.parse_args(argv)
    if args.serve:
        _serving_worker(args)
    elif args.churn_worker:
        _churn_worker(args)
    elif args.churn_baseline:
        _churn_baseline(args)
    elif args.fleet and args.worker is False and args.reference is False:
        _fleet_worker(args)
    elif args.worker:
        _worker(args)
    elif args.reference:
        _reference(args)
    else:
        print(json.dumps(run_drill(args.workdir, steps=args.steps,
                                   heartbeat=args.heartbeat,
                                   deadline=args.deadline,
                                   step_sleep=args.step_sleep,
                                   disk_loss=args.disk_loss), indent=1))
    return 0


if __name__ == '__main__':
    sys.exit(main())
