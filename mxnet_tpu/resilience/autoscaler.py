"""Fleet-monitor-driven autoscaler policy loop (elastic scale-up PR).

The mechanism half of elasticity lives elsewhere: the membership side
channel detects loss and admits JOINs (``parallel.dist``), and the
``ElasticController`` re-forms the mesh in either direction
(``resilience.elastic``). This module is the POLICY half: a small,
deterministic decision loop that consumes the ``FleetMonitor``
detectors (chronic straggler, step-time regression, memory imbalance —
ISSUE 12/13) plus membership events (lost peers, pending joins, world
size vs target) and emits three decision kinds through a pluggable
capacity-provider interface:

- ``evict``            — a rank flagged by a detector for ``strikes``
  consecutive observes is asked to leave (the provider decides how:
  SIGTERM for a graceful ``leave()``, a scheduler API call, ...);
- ``request_capacity`` — the world sits below target (a peer was lost,
  or an evict opened a hole): ask the provider for replacement ranks;
- ``admit``            — a JOIN candidate is pending on the side
  channel: advisory (the ``ElasticController`` performs the actual
  admission at the next step boundary), recorded so the ledger shows
  the full loss → request → join → admit causal chain.

Hysteresis keeps the loop stable: detector flags must persist for
``strikes`` consecutive observes before an evict, every decision kind
honors a per-target cooldown (``MXTPU_AUTOSCALE_COOLDOWN_SECONDS``),
and a capacity request stays pending (suppressing re-requests) until a
join shows up or the cooldown expires. Every decision lands in the
in-process ledger (``decisions``), the flight recorder
(``autoscaler.decision`` notes) and the telemetry contract
(``mxnet_tpu_elastic_autoscaler_decisions_total`` by kind) — a
post-mortem can replay exactly why the fleet grew or shrank.

The loop is synchronous (call ``observe()`` from the training loop or
any poll thread): deterministic under test, and the drill's subprocess
spawner is the reference ``CapacityProvider``.
"""
from __future__ import annotations

import logging
import time as _time

from ..base import telem_flags as _telem

__all__ = ['CapacityProvider', 'Autoscaler']

_log = logging.getLogger('mxnet_tpu.resilience')

# detector flag -> decision kind it escalates to after `strikes`
# consecutive flagged observes
_EVICT_FLAGS = ('fleet.straggler', 'fleet.memory_imbalance')
_REQUEST_FLAGS = ('fleet.step_regression',)


class CapacityProvider:
    """The pluggable seam between autoscaler policy and whatever can
    actually grant or revoke ranks (a subprocess spawner in the drill,
    a TPU pod scheduler in production). Implementations must not
    block: decisions are emitted from the observe loop."""

    def request_capacity(self, count, reason):
        """Ask for ``count`` new ranks. Fire-and-forget: granted
        capacity shows up later as JOIN announcements."""
        raise NotImplementedError

    def evict(self, rank, reason):
        """Ask ``rank`` to leave (gracefully when possible — a SIGTERM
        runs its preemption commit)."""
        raise NotImplementedError


class Autoscaler:
    """Deterministic scale policy over fleet detectors + membership.

    Parameters
    ----------
    membership : parallel.dist.Membership, optional
        Defaults to the process-global one, resolved lazily.
    monitor : telemetry.fleet.FleetMonitor, optional
        Defaults to the process-global one (coordinator-side).
    provider : CapacityProvider, optional
        Where evict/request decisions are executed. Without one the
        loop still decides and ledgers (dry-run policy audit).
    target_world : int, optional
        The world size the loop defends. Defaults to the membership
        world at first observe (the nominal fleet).
    cooldown_seconds / strikes / max_world / min_world
        Hysteresis knobs; default from MXTPU_AUTOSCALE_* config.
    """

    def __init__(self, membership=None, monitor=None, provider=None,
                 target_world=None, cooldown_seconds=None, strikes=None,
                 max_world=None, min_world=1):
        from .. import config as _config
        self._membership = membership
        self._monitor = monitor
        self.provider = provider
        self.target_world = int(target_world) if target_world else None
        self.cooldown_seconds = float(
            cooldown_seconds if cooldown_seconds is not None
            else _config.get('MXTPU_AUTOSCALE_COOLDOWN_SECONDS'))
        self.strikes = int(strikes if strikes is not None
                           else _config.get('MXTPU_AUTOSCALE_STRIKES'))
        self.max_world = int(max_world if max_world is not None
                             else _config.get('MXTPU_AUTOSCALE_MAX_WORLD'))
        self.min_world = int(min_world)
        self.decisions = []          # the in-process decision ledger
        self._strikes = {}           # (flag, rank) -> consecutive count
        self._cooldown = {}          # decision key -> monotonic stamp
        self._evicting = set()       # ranks asked to leave, still alive
        self._pending_request = 0    # ranks requested, not yet joined

    # -- wiring ------------------------------------------------------------

    @property
    def membership(self):
        if self._membership is None:
            from ..parallel import dist as _dist
            self._membership = _dist.membership()
        return self._membership

    @property
    def monitor(self):
        if self._monitor is None:
            from ..telemetry import fleet as _fleet
            self._monitor = _fleet.monitor()
        return self._monitor

    # -- the policy loop ---------------------------------------------------

    def observe(self):
        """One poll: read the membership view + detector flags, update
        strike counts, emit any due decisions through the provider and
        the ledger. Returns the decisions made this observe."""
        ms = self.membership
        if ms is None:
            return []
        try:
            view = ms.view() or {}
        except Exception:
            return []
        alive = [int(r) for r in view.get('alive', [])]
        joining = {int(r): float(a)
                   for r, a in view.get('joining', {}).items()}
        world = len(alive)
        if self.target_world is None and world:
            self.target_world = world
        self._evicting &= set(alive)   # departed evictees are done
        out = []
        out += self._observe_detectors(alive, world)
        out += self._observe_membership(alive, joining, world)
        for d in out:
            self._ledger(d)
        return out

    def _observe_detectors(self, alive, world):
        mon = self.monitor
        if mon is None:
            return []
        try:
            ranks = mon.view()['ranks']
        except Exception:
            return []
        out = []
        flagged_now = set()
        for r, st in ranks.items():
            r = int(r)
            for flag in set(st.get('flags') or ()):
                key = (flag, r)
                flagged_now.add(key)
                self._strikes[key] = self._strikes.get(key, 0) + 1
                if self._strikes[key] < self.strikes:
                    continue
                if flag in _EVICT_FLAGS:
                    d = self._decide_evict(r, flag, alive, world)
                elif flag in _REQUEST_FLAGS:
                    d = self._decide_request(
                        1, f'{flag} persisted {self._strikes[key]} '
                        f'observes', world)
                else:
                    d = None
                if d is not None:
                    out.append(d)
        # a flag that cleared resets its strike count — hysteresis is
        # CONSECUTIVE flagged observes, not lifetime totals
        for key in list(self._strikes):
            if key not in flagged_now:
                del self._strikes[key]
        return out

    def _observe_membership(self, alive, joining, world):
        out = []
        for r, age in sorted(joining.items()):
            # advisory: the ElasticController admits at the next step
            # boundary; the ledger records the join being honored (and
            # the pending capacity request it satisfies)
            if not self._cooled(('admit', r)):
                continue
            self._pending_request = max(0, self._pending_request - 1)
            out.append({'kind': 'admit', 'rank': r, 'world': world,
                        'reason': f'join candidate pending '
                                  f'{round(age, 1)}s'})
        target = self.target_world or 0
        if self.max_world:
            target = min(target, self.max_world)
        missing = target - world - len(joining) - self._pending_request
        if missing > 0:
            d = self._decide_request(
                missing, f'world {world} below target {target}', world)
            if d is not None:
                out.append(d)
        return out

    def _decide_evict(self, rank, flag, alive, world):
        if rank not in alive or rank in self._evicting:
            return None
        if world - len(self._evicting) <= self.min_world:
            return None                 # never evict below the floor
        if not self._cooled(('evict', rank)):
            return None
        reason = f'{flag} flagged {self._strikes[(flag, rank)]} ' \
                 f'consecutive observes'
        self._evicting.add(rank)
        if self.provider is not None:
            try:
                self.provider.evict(rank, reason)
            except Exception:
                _log.exception("autoscaler: provider.evict(%s) failed",
                               rank)
        return {'kind': 'evict', 'rank': rank, 'world': world,
                'reason': reason}

    def _decide_request(self, count, reason, world):
        if self.max_world and world + self._pending_request >= \
                self.max_world:
            return None
        if not self._cooled(('request_capacity',)):
            return None
        count = max(1, int(count))
        if self.max_world:
            count = min(count, self.max_world - world)
        self._pending_request += count
        if self.provider is not None:
            try:
                self.provider.request_capacity(count, reason)
            except Exception:
                _log.exception(
                    "autoscaler: provider.request_capacity(%d) failed",
                    count)
        return {'kind': 'request_capacity', 'count': count,
                'world': world, 'reason': reason}

    def _cooled(self, key):
        now = _time.monotonic()
        last = self._cooldown.get(key)
        if last is not None and now - last < self.cooldown_seconds:
            return False
        self._cooldown[key] = now
        return True

    def _ledger(self, decision):
        d = dict(decision)
        d['time'] = _time.time()
        self.decisions.append(d)
        _log.warning("autoscaler: %s (%s)", d['kind'], d['reason'])
        try:
            from ..telemetry import flight as _flight
            _flight.note('autoscaler.decision', **d)
        except Exception:
            pass
        if _telem['on']:
            from .. import telemetry as _telemetry
            _telemetry.inc('mxnet_tpu_elastic_autoscaler_decisions_total',
                           kind=d['kind'])
