"""Training resilience: fault injection, non-finite guard, watchdog.

The detection/recovery half of fault tolerance (checkpointing is the
durability half, see ``mxnet_tpu.checkpoint``): deterministic fault
injection so every recovery path is exercised by real failures in CI
(``faults``), an on-device non-finite guard with skip-step and
auto-rollback policies (``guard``), a heartbeat watchdog that dumps
all-thread stacks when a step wedges (``watchdog``), the shared
bounded retry helper (``retry``), and the elastic commit -> re-form ->
resume controller for multi-host peer loss / preemption (``elastic``,
with the membership side channel in ``parallel.dist``).

Arm faults with ``MXTPU_FAULT=site:kind[:prob[:seed[:first-last]]]``
(see ``faults.sites()`` for the registered sites).
"""
from __future__ import annotations

from . import faults
from .autoscaler import Autoscaler, CapacityProvider
from .elastic import (ElasticController, PeerLossError, Preempted,
                      stall_verdict)
from .faults import InjectedFault
from .guard import NonFiniteGuard
from .retry import retry_call
from .watchdog import StepWatchdog, format_all_stacks

__all__ = ['faults', 'InjectedFault', 'NonFiniteGuard', 'retry_call',
           'StepWatchdog', 'format_all_stacks', 'ElasticController',
           'PeerLossError', 'Preempted', 'stall_verdict',
           'Autoscaler', 'CapacityProvider']

# arm any sites named by the environment at import (the config var is
# read through the declared registry; an empty/unset var arms nothing)
faults.arm_from_env()
