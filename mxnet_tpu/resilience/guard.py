"""Non-finite guard: on-device detection, skip-step, auto-rollback.

A single NaN step silently poisons a multi-hour run: the update applies,
every parameter becomes NaN, and nothing downstream ever says so. The
guard closes that hole in three layers:

1. **On-device detection + skip (free-ish)**: the existing jitted step
   (gluon ``Trainer``'s fused update, ``ShardedTrainStep``'s pjit step)
   additionally reduces ``isfinite`` over the loss and every gradient
   into one scalar flag, and gates the weight/optimizer-state outputs
   with ``where(finite, new, old)`` — a non-finite step is a no-op ON
   DEVICE, inside the same XLA program, before the host ever knows.
   Under ZeRO the reduction runs over the SHARDED (reduce-scattered)
   gradients before any gather — each device scans its 1/dp slice and
   GSPMD psums the scalar flag over dp — and the gate writes back the
   sharded masters/params in place, so the guard composes with ZeRO-1
   and ZeRO-3 at 1/dp cost and zero extra full-tensor traffic.
2. **Deferred host check (no extra sync)**: the flag is a device scalar
   the guard reads at the START of the next step, when the previous
   step's program has long finished — the happy path never blocks on an
   extra device->host sync.
3. **Policy ladder**: each bad step counts
   (``mxnet_tpu_resilience_bad_steps_total``); after
   ``max_consecutive_bad`` (default ``MXTPU_GUARD_MAX_BAD_STEPS`` = 3)
   consecutive bad steps the guard auto-restores the newest committed
   checkpoint via ``CheckpointManager.restore_latest()`` — parameters,
   optimizer state, RNG stream and LR-scheduler position — and training
   continues from known-good state
   (``mxnet_tpu_resilience_rollbacks_total`` /
   ``_last_rollback_step`` / ``_recovery_seconds``).

Usage::

    mgr = checkpoint.CheckpointManager('ckpts/', params=net,
                                       trainer=trainer, autosave_steps=50)
    guard = resilience.NonFiniteGuard(manager=mgr)
    trainer.attach_guard(guard)
    for step in range(1, total + 1):
        ... forward / backward ...
        trainer.step(batch)          # on-device skip + flag for the guard
        guard.observe_loss(loss)     # optional: fold loss finiteness in
        guard.maybe_save(step)       # cadence save, gated on a good flag
"""
from __future__ import annotations

import logging
import time as _time

from ..base import MXNetError, telem_flags as _telem

__all__ = ['NonFiniteGuard']

_log = logging.getLogger('mxnet_tpu.resilience')


class NonFiniteGuard:
    """Supervises one training loop. ``policy``:

    - ``'rollback'`` (default): skip bad steps on device; after
      ``max_consecutive_bad`` consecutive bad steps restore the newest
      committed checkpoint (requires ``manager``).
    - ``'skip'``: only skip (count forever, never restore).
    - ``'raise'``: raise MXNetError after ``max_consecutive_bad``
      consecutive bad steps (for jobs where a supervisor owns restarts).
    """

    def __init__(self, manager=None, max_consecutive_bad=None,
                 policy='rollback'):
        if policy not in ('rollback', 'skip', 'raise'):
            raise MXNetError(
                f"NonFiniteGuard policy must be 'rollback', 'skip' or "
                f"'raise', got {policy!r}")
        if policy == 'rollback' and manager is None:
            raise MXNetError(
                "NonFiniteGuard(policy='rollback') needs a "
                "CheckpointManager to restore from; pass manager=... or "
                "use policy='skip'")
        if max_consecutive_bad is None:
            from .. import config as _config
            max_consecutive_bad = _config.get('MXTPU_GUARD_MAX_BAD_STEPS')
        if int(max_consecutive_bad) < 1:
            raise MXNetError("max_consecutive_bad must be >= 1")
        self.manager = manager
        self.max_consecutive_bad = int(max_consecutive_bad)
        self.policy = policy
        self.consecutive_bad = 0
        self.bad_steps = 0
        self.rollbacks = 0
        self.last_rollback_step = None
        self._pending = []          # device bool scalars (or host bools)
        self._post_restore_hooks = []
        self._save_deferred = False

    # -- flag plumbing (called by Trainer / ShardedTrainStep) -------------

    def push_flag(self, finite_flag):
        """Record one step's on-device finiteness flag (a jax scalar or a
        plain bool). Never blocks — the value is read at the next
        ``pre_step()`` / ``maybe_save()``."""
        self._pending.append(finite_flag)

    def observe_loss(self, loss):
        """Optionally fold a loss value's finiteness into the pending
        flag set (a tiny on-device reduction, read deferred like every
        other flag)."""
        import jax.numpy as jnp
        data = getattr(loss, '_data', loss)
        self._pending.append(jnp.all(jnp.isfinite(
            jnp.asarray(data, jnp.float32))))

    def add_post_restore_hook(self, fn):
        """Run ``fn()`` after every rollback restore (e.g. re-place
        restored parameters onto a device mesh)."""
        self._post_restore_hooks.append(fn)

    def _drain(self):
        """(any_flags, all_finite) over the pending flags; the host reads
        here are of programs that finished a full step ago."""
        if not self._pending:
            return False, True
        flags, self._pending = self._pending, []
        return True, all(bool(f) for f in flags)

    def peek_ok(self):
        """All pending flags finite? (Reads without consuming: the bad
        accounting in pre_step still sees them.) Forces a device sync —
        only used on the sparse checkpoint cadence, never per step."""
        return all(bool(f) for f in self._pending)

    # -- per-step supervision ---------------------------------------------

    def pre_step(self, on_bad=None):
        """Call at the start of every training step. Reads the previous
        step's flag and walks the policy ladder. Returns True when a
        rollback just happened — the caller must treat any state computed
        BEFORE the restore (e.g. gradients from backward) as stale and
        skip applying it. ``on_bad`` (optional) runs once when the
        drained flag was bad, before any rollback — callers use it to
        undo host-side bookkeeping the skipped step already advanced
        (e.g. optimizer update counts)."""
        had, ok = self._drain()
        if not had:
            return False
        if ok:
            self.consecutive_bad = 0
            return False
        if on_bad is not None:
            on_bad()
        self.consecutive_bad += 1
        self.bad_steps += 1
        if _telem['on']:
            from .. import telemetry as _telemetry
            _telemetry.inc('mxnet_tpu_resilience_bad_steps_total')
        # flight recorder: the flag that just drained bad belongs to the
        # PREVIOUS recorded step (deferred read) — mark it and log the
        # trip so a crash dump shows the divergence window
        from ..telemetry import flight as _flight
        _flight.annotate_last(guard_ok=False)
        _flight.note('guard.bad_step', consecutive=self.consecutive_bad)
        _log.warning(
            "non-finite training step detected (%d consecutive, "
            "update skipped on device)", self.consecutive_bad)
        if self.consecutive_bad < self.max_consecutive_bad:
            return False
        if self.policy == 'skip':
            return False
        if self.policy == 'raise':
            raise MXNetError(
                f"NonFiniteGuard: {self.consecutive_bad} consecutive "
                f"non-finite steps (policy='raise')")
        return self._rollback()

    def _rollback(self):
        t0 = _time.perf_counter()
        self.consecutive_bad = 0
        from ..telemetry import flight as _flight, trace as _trace
        with _trace.span('guard.rollback'):
            step = self.manager.restore_latest()
        if step is None:
            raise MXNetError(
                "NonFiniteGuard: rollback triggered but no committed "
                "checkpoint exists yet — save one before the first "
                "divergence (autosave_steps) or lower "
                "max_consecutive_bad")
        for fn in self._post_restore_hooks:
            fn()
        self.rollbacks += 1
        self.last_rollback_step = step
        dt = _time.perf_counter() - t0
        if _telem['on']:
            from .. import telemetry as _telemetry
            _telemetry.inc('mxnet_tpu_resilience_rollbacks_total')
            _telemetry.set_gauge('mxnet_tpu_resilience_last_rollback_step',
                                 step)
            _telemetry.observe('mxnet_tpu_resilience_recovery_seconds', dt)
        _log.warning(
            "non-finite guard rolled back to checkpoint step %d "
            "(%.3fs): params, optimizer state, RNG and LR schedule "
            "restored", step, dt)
        # the rollback ladder is a post-mortem moment: dump the flight
        # recorder so the NaN burst's span timeline survives the
        # recovery (failure here must never break the recovery itself)
        _flight.note('guard.rollback', step=step,
                     recovery_seconds=round(dt, 4))
        try:
            _flight.dump(reason='rollback')
        except Exception:
            _log.exception("flight-recorder dump after rollback failed")
        return True

    # -- checkpoint gating --------------------------------------------------

    def maybe_save(self, step, metadata=None):
        """Cadence-gated save through the bound manager, additionally
        gated on the current step's flag being finite — a checkpoint must
        never capture the state of a step the guard is about to reject.
        The flag read syncs, so this only happens when the manager's
        autosave cadence is actually due. Returns True when it saved."""
        mgr = self.manager
        if mgr is None:
            raise MXNetError("NonFiniteGuard.maybe_save needs a manager")
        mgr._current_step = int(step)
        if not mgr.save_due(int(step)) and not self._save_deferred:
            return False
        if not self.peek_ok() and not mgr.preempted:
            # DEFER, don't drop: with a steps cadence the next due save
            # would otherwise be a full interval away, doubling the
            # worst-case rollback re-train exactly during NaN bursts.
            # EXCEPT under preemption: every guard path skips a bad
            # update before it applies, so the parameters are clean —
            # the last-chance grace-window save must never be deferred.
            self._save_deferred = True
            _log.warning(
                "deferring checkpoint at step %d: the step's non-finite "
                "flag is set (saved at the next finite step)", step)
            return False
        self._save_deferred = False
        mgr.save(int(step), metadata=metadata, block=mgr.preempted)
        return True
