"""Step watchdog: detect a wedged training step and say WHY.

A hung collective or a deadlocked input pipeline doesn't crash — it
wedges. The process sits at 0% MFU forever and the only signal is the
absence of log lines. The watchdog is a heartbeat-fed background thread:
the training loop calls ``beat(step)`` once per step; when no beat
arrives for ``deadline_seconds`` the watchdog dumps every thread's stack
plus a telemetry snapshot to the log (so the post-mortem names the
wedged frame, not just the wall-clock) and can optionally trigger the
checkpoint manager's synchronous ``save_now()`` — the same path the
SIGTERM preemption hook uses — so a supervisor can kill/restart the job
without losing the step window.

One dump per stall: the watchdog re-arms only after the next beat, so a
wedge produces one actionable report, not a log flood.
"""
from __future__ import annotations

import logging
import sys
import threading
import time as _time
import traceback

from ..base import telem_flags as _telem

__all__ = ['StepWatchdog', 'format_all_stacks']

_log = logging.getLogger('mxnet_tpu.resilience')


def format_all_stacks():
    """One string with every live thread's name + current stack."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    chunks = []
    for ident, frame in sorted(frames.items()):
        name = names.get(ident, '?')
        stack = ''.join(traceback.format_stack(frame))
        chunks.append(f"--- thread {name} (ident {ident}) ---\n{stack}")
    return ''.join(chunks)


class StepWatchdog:
    """Heartbeat watchdog for a training loop.

    ::

        wd = resilience.StepWatchdog(deadline_seconds=120, manager=mgr,
                                     save_on_stall=True)
        with wd:
            for step in ...:
                ... train ...
                wd.beat(step)

    ``on_stall`` (optional callable ``fn(report_str)``) replaces the
    default log dump — tests and custom supervisors hook in there.
    ``save_on_stall`` attempts ``manager.save_now()`` from a separate
    daemon thread (the stalled thread may hold the manager lock — the
    attempt must never wedge the watchdog itself).
    """

    def __init__(self, deadline_seconds=None, poll_seconds=None,
                 manager=None, save_on_stall=False, on_stall=None,
                 membership=None):
        if deadline_seconds is None:
            from .. import config as _config
            deadline_seconds = _config.get('MXTPU_WATCHDOG_SECONDS')
        self.deadline_seconds = float(deadline_seconds)
        if self.deadline_seconds <= 0:
            raise ValueError("watchdog deadline must be > 0 seconds")
        self.poll_seconds = float(poll_seconds) if poll_seconds \
            else max(0.05, self.deadline_seconds / 4.0)
        self.manager = manager
        self.save_on_stall = bool(save_on_stall)
        self.on_stall = on_stall
        # elastic membership for the stall verdict: explicit, or the
        # process-global one (resolved at dump time, so construction
        # order vs dist.init() does not matter)
        self.membership = membership
        self.stalls = 0
        self.last_step = None
        self._beat_time = None
        self._dumped_since_beat = False
        self._stop = threading.Event()
        self._thread = None
        self._lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._beat_time = _time.monotonic()
        self._dumped_since_beat = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name='mxtpu-step-watchdog')
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(1.0, 2 * self.poll_seconds))
        self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- heartbeat ---------------------------------------------------------

    def beat(self, step=None):
        """The training loop made progress. Cheap: a timestamp + flag."""
        with self._lock:
            self._beat_time = _time.monotonic()
            self._dumped_since_beat = False
            if step is not None:
                self.last_step = step

    # -- the watchdog thread ----------------------------------------------

    def _run(self):
        while not self._stop.wait(self.poll_seconds):
            with self._lock:
                stalled = (not self._dumped_since_beat
                           and self._beat_time is not None
                           and _time.monotonic() - self._beat_time
                           > self.deadline_seconds)
                if stalled:
                    self._dumped_since_beat = True
                    age = _time.monotonic() - self._beat_time
                    step = self.last_step
            if stalled:
                self._on_stall(age, step)

    def _on_stall(self, age, step):
        self.stalls += 1
        if _telem['on']:
            from .. import telemetry as _telemetry
            _telemetry.inc('mxnet_tpu_resilience_watchdog_stalls_total')
        # one verdict per stall, shared by the report and the flight
        # note (computing it twice could disagree mid-transition)
        verdict = self._stall_verdict()
        report = self._format_report(age, step, verdict)
        # flight recorder: note the stall and dump the black box (span
        # rings are flushed — open spans get synthetic closes — so the
        # hang leaves a loadable timeline naming the wedged scope, not
        # just thread stacks). Must never wedge the watchdog itself.
        try:
            from ..telemetry import flight as _flight
            note = dict(age_seconds=round(age, 1), step=step)
            if verdict is not None:
                # the classified verdict + per-peer heartbeat ages ride
                # in the dump, so a post-mortem never misattributes a
                # remote preemption to local code (or vice versa)
                note.update(verdict=verdict['verdict'],
                            peer_ages=verdict['peer_ages'],
                            lost_peers=verdict['lost'])
                if verdict.get('during'):
                    note['during'] = verdict['during']
                if verdict.get('straggler'):
                    note['straggler'] = verdict['straggler']
                if verdict.get('compiling'):
                    note['compiling'] = verdict['compiling']
                if verdict.get('joining'):
                    note['joining'] = verdict['joining']
            _flight.note('watchdog.stall', **note)
            path = _flight.dump(reason='watchdog_stall')
            if path:
                report += f"\nflight recorder dumped to {path}"
        except Exception:
            _log.exception("watchdog flight-recorder dump failed")
        if self.on_stall is not None:
            try:
                self.on_stall(report)
            except Exception:
                _log.exception("watchdog on_stall callback failed")
        else:
            _log.error("%s", report)
        if self.save_on_stall and self.manager is not None:
            # separate thread: save_now serializes on the manager lock,
            # which the wedged thread may hold — the watchdog must keep
            # running (and keep reporting) regardless
            threading.Thread(target=self._try_save, daemon=True,
                             name='mxtpu-watchdog-save').start()

    def _try_save(self):
        try:
            step = self.manager._current_step
            if step is None:
                # nothing has told the manager a step yet (e.g. a stall
                # in the very first batch): fall back to the heartbeat
                # step, or 0 — an initial-state checkpoint still beats
                # losing the run. last_step is beat()'s state: this
                # save thread reads it under the same lock.
                with self._lock:
                    last = self.last_step
                step = last if last is not None else 0
            self.manager.save_now(step)
            _log.warning("watchdog: emergency checkpoint committed at "
                         "step %s", step)
        except Exception:
            _log.exception("watchdog: emergency save_now() failed")

    def _stall_verdict(self):
        """Classified stall verdict from the elastic membership layer
        (None when no membership is running). Never raises — the
        watchdog must keep reporting whatever else is broken."""
        try:
            from .elastic import stall_verdict
            return stall_verdict(self.membership)
        except Exception:
            return None

    def _format_report(self, age, step, verdict=None):
        lines = [
            f"watchdog: no training-step heartbeat for {age:.1f}s "
            f"(deadline {self.deadline_seconds:.1f}s, last step "
            f"{step if step is not None else 'unknown'}) — the step is "
            f"stalled. All-thread stacks follow.",
        ]
        if verdict is None:
            verdict = self._stall_verdict()
        if verdict is not None:
            during = ' (during replica fetch)' \
                if verdict.get('during') == 'replica_fetch' else ''
            if verdict['lost']:
                lines.insert(1, (
                    f"verdict: PEER LOSS SUSPECTED{during} — peer(s) "
                    f"{verdict['lost']} silent past the "
                    f"{verdict['deadline_seconds']:.1f}s membership "
                    f"deadline (last-heartbeat ages per peer: "
                    f"{verdict['peer_ages']}); the wedge is most likely "
                    f"a remote preemption, not local code."))
            elif during:
                lines.insert(1, (
                    f"verdict: PEER LOSS SUSPECTED{during} — a "
                    f"checkpoint replica fetch has been in flight for "
                    f"the whole stall; the serving peer is the prime "
                    f"suspect even though it still heartbeats "
                    f"(last-heartbeat ages per peer: "
                    f"{verdict['peer_ages']}). The fetch itself is "
                    f"bounded by MXTPU_REPLICA_TIMEOUT_SECONDS."))
            elif verdict.get('verdict') == 'compiling':
                c = verdict['compiling']
                rank = c.get('rank')
                rank_s = rank if rank is not None else 'this process'
                lines.insert(1, (
                    f"verdict: COMPILING: rank {rank_s}, site "
                    f"{c.get('site')}, {c.get('elapsed_seconds')}s "
                    f"elapsed — an XLA compile (phase "
                    f"{c.get('phase')}) has the step, not a wedge; "
                    f"expect it to clear, or persist the cache "
                    f"(MXTPU_COMPILE_CACHE_DIR) so the next cold start "
                    f"skips it."))
            elif verdict.get('verdict') == 'reform_pending':
                j = verdict.get('joining') or {}
                names = ', '.join(
                    f"rank {r} (announced {a:.1f}s ago)"
                    for r, a in sorted(j.items()))
                lines.insert(1, (
                    f"verdict: REFORM PENDING — a scale-up admission "
                    f"rendezvous is in flight: joining {names or j}; "
                    f"every survivor quiesces at its next step boundary "
                    f"and re-forms at the larger world, so the stall is "
                    f"the rendezvous, not a wedge. Bounded by "
                    f"MXTPU_JOIN_TIMEOUT_SECONDS."))
            elif verdict.get('verdict') == 'straggler_suspected':
                s = verdict['straggler']
                lines.insert(1, (
                    f"verdict: STRAGGLER SUSPECTED: rank {s['rank']} — "
                    f"every peer still heartbeats, but the fleet "
                    f"telemetry names rank {s['rank']} as the "
                    f"{'most-stale' if s['reason'] == 'stale' else 'slowest'}"
                    f" rank (last snapshot "
                    f"{s.get('snapshot_age_seconds')}s ago, step "
                    f"{s.get('step')} vs fleet max {s.get('max_step')}); "
                    f"this process is most likely wedged inside a "
                    f"collective waiting on it."))
            else:
                s = verdict.get('straggler')
                suffix = ''
                if s is not None:
                    suffix = (
                        f" Fleet telemetry's worst rank: {s['rank']} "
                        f"({s['reason']}, last snapshot "
                        f"{s.get('snapshot_age_seconds')}s ago, step "
                        f"{s.get('step')} vs fleet max "
                        f"{s.get('max_step')}) — below the detector "
                        f"thresholds.")
                lines.insert(1, (
                    f"verdict: LOCAL STALL — every peer is still "
                    f"heartbeating (last-heartbeat ages per peer: "
                    f"{verdict['peer_ages']}); the wedge is in THIS "
                    f"process.{suffix}"))
        lines.append(format_all_stacks())
        try:
            from .. import telemetry as _telemetry
            snap = _telemetry.report()
            if snap:
                lines.append(snap)
        except Exception:
            pass
        try:
            from ..telemetry import flight as _flight, trace as _trace
            if _trace.enabled():
                lines.append(_flight.get().format_summary())
        except Exception:
            pass
        return '\n'.join(lines)
