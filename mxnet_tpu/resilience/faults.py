"""Deterministic fault injection for the training stack.

At v5e-64 scale, preemptions, hung collectives, corrupt records and loss
blow-ups are routine — but without a way to *produce* those failures on
demand, every recovery path in the stack is dead code until it breaks in
production. This module is a process-global registry of named fault
sites threaded through every layer that can fail (IO decode, device
transfer, the train step, checkpoint writes, collectives, dataloader
workers). Arm a site and the real code path takes the real failure:

    MXTPU_FAULT=step.dispatch:nan:1:0:5-7   # NaN grads on steps 5..7
    MXTPU_FAULT=io.decode:corrupt:0.01:42   # 1% of decodes, seed 42
    MXTPU_FAULT=checkpoint.write:raise:1:0:1-1,collective.all_reduce:hang

Grammar (comma/semicolon-separated specs)::

    site:kind[:prob[:seed[:first-last]]]

- ``site``  — a registered fault site (see ``sites()``); arming an
  unknown site raises, so typos fail loudly.
- ``kind``  — ``raise`` (InjectedFault), ``hang`` (sleep
  MXTPU_FAULT_HANG_SECONDS), ``corrupt`` (the site mangles its payload
  bytes), ``nan`` (the site poisons its numerics).
- ``prob``  — firing probability per occurrence (default 1).
- ``seed``  — seed of the *deterministic* per-occurrence firing stream
  (default 0). Same seed + same occurrence index -> same decision, in
  every process, on every run — resilience tests are exactly
  reproducible (tools/flakiness_checker.py proves it 3x in CI).
- ``first-last`` — 1-based inclusive occurrence window (``5-7``, or
  ``5`` for exactly one occurrence). Outside the window the site never
  fires regardless of prob.

Most sites count occurrences in call order; ``io.decode`` keys them by
the 1-based record index instead, so the default multi-threaded decode
pool corrupts the same records in every run (and a window like ``5-7``
means records 5..7 of the file, once per epoch).

Disarmed sites cost one empty-dict check per call.
"""
from __future__ import annotations

import hashlib
import threading
import time as _time

from ..base import MXNetError, telem_flags as _telem

__all__ = ['InjectedFault', 'KINDS', 'sites', 'register_site', 'arm',
           'disarm', 'arm_from_env', 'active', 'is_armed', 'fire',
           'corrupt_bytes']


class InjectedFault(MXNetError):
    """Raised by an armed ``raise`` fault site (never by real failures)."""

    def __init__(self, site, occurrence):
        super().__init__(
            f"injected fault at site '{site}' (occurrence {occurrence}) — "
            f"armed via MXTPU_FAULT / resilience.faults.arm()")
        self.site = site
        self.occurrence = occurrence


KINDS = ('raise', 'hang', 'corrupt', 'nan')

# site -> (description, kinds that make sense there). The wiring lives at
# the call site (io/io.py, gluon/trainer.py, parallel/step.py,
# checkpoint/manager.py, kvstore/kvstore.py, gluon/data/dataloader.py).
_SITES = {
    'io.decode': ('ImageRecordIter record read + image decode (corrupt '
                  'mangles the image bytes before decode)',
                  ('raise', 'corrupt', 'hang')),
    'io.device_put': ('host->device staging of a prefetched batch',
                      ('raise', 'hang')),
    'dataloader.worker': ('gluon DataLoader worker batch fetch (a raise '
                          'here exercises the bounded respawn path)',
                          ('raise', 'hang')),
    'step.dispatch': ('train-step dispatch (gluon Trainer.step and '
                      'ShardedTrainStep.__call__; nan poisons the '
                      'gradients/loss so the non-finite guard trips)',
                      ('raise', 'hang', 'nan')),
    'checkpoint.write': ('CheckpointManager payload write (raise is '
                         'retried as a transient FS error; corrupt '
                         'mangles one payload so restore falls back)',
                         ('raise', 'hang', 'corrupt')),
    'checkpoint.read': ('CheckpointManager payload read at restore and '
                        'scrub time (corrupt mangles the bytes AFTER the '
                        'disk read so the hash check fails — restore '
                        'falls back / repairs from a replica and the '
                        'scrubber quarantines, no hand-flipped bytes '
                        'needed; raise surfaces a hard read error)',
                        ('raise', 'hang', 'corrupt')),
    'dist.file_put': ('checkpoint replica transfer send (parallel.dist.'
                      'file_put; raise fails the transfer — the push '
                      'worker retries bounded; corrupt mangles the '
                      'payload in flight so the receiver hash check '
                      'rejects it; hang stalls the transfer into its '
                      'socket timeout)', ('raise', 'hang', 'corrupt')),
    'collective.all_reduce': ('kvstore gradient reduction across device '
                              'copies', ('raise', 'hang')),
    'dist.heartbeat': ('elastic membership heartbeat send (parallel.dist.'
                       'Membership; raise drops the beat — enough '
                       'consecutive drops and the coordinator declares '
                       'this worker lost; hang delays the beat past the '
                       'peer deadline)', ('raise', 'hang')),
    'dist.barrier': ('membership barrier entry (dist.barrier / kvstore '
                     'barrier on dist stores) — the rendezvous every '
                     'mesh re-form crosses', ('raise', 'hang')),
    'dist.join': ('elastic membership JOIN announcement (parallel.dist.'
                  'Membership.join; raise fails the announcement so the '
                  'joiner retries or aborts; hang delays it so the '
                  'admission rendezvous ages — the REFORM PENDING '
                  'verdict drills against this)', ('raise', 'hang')),
    'elastic.admit': ('scale-up admission re-form entry (Elastic'
                      'Controller._admit, survivors and joiner alike) — '
                      'raise aborts the admission before teardown; hang '
                      'stalls the rendezvous into the watchdog window',
                      ('raise', 'hang')),
    'alloc.oom': ('device allocator exhaustion: a raise here surfaces '
                  'as a synthetic RESOURCE_EXHAUSTED through the '
                  'telemetry.memory.oom_guard wrapping step dispatch, '
                  'h2d batch/param placement and checkpoint-restore '
                  're-place — the OOM forensics dump drills without a '
                  'real 16GB chip (resilience.drill.run_oom_drill)',
                  ('raise',)),
}

_lock = threading.RLock()
_armed = {}          # site -> dict(kind, prob, seed, first, last, count)


def sites():
    """{site: description} of every registered fault site."""
    return {name: desc for name, (desc, _) in sorted(_SITES.items())}


def register_site(name, description, kinds=KINDS):
    """Register an additional fault site (for tests / downstream code)."""
    with _lock:
        _SITES[name] = (description, tuple(kinds))


def arm(site, kind, prob=1.0, seed=0, window=None):
    """Arm one fault site programmatically. ``window`` is a 1-based
    inclusive ``(first, last)`` occurrence range (or a single int)."""
    if site not in _SITES:
        raise MXNetError(
            f"unknown fault site {site!r}; registered sites: "
            f"{sorted(_SITES)}")
    if kind not in KINDS:
        raise MXNetError(f"unknown fault kind {kind!r}; kinds: {KINDS}")
    allowed = _SITES[site][1]
    if kind not in allowed:
        raise MXNetError(
            f"fault kind {kind!r} is not meaningful at site {site!r} "
            f"(allowed: {allowed})")
    prob = float(prob)
    if not 0.0 <= prob <= 1.0:
        raise MXNetError(f"fault prob must be in [0, 1], got {prob}")
    if window is None:
        first, last = 1, None
    elif isinstance(window, int):
        first = last = int(window)
    else:
        first, last = int(window[0]), int(window[1])
    if first < 1 or (last is not None and last < first):
        raise MXNetError(f"fault window must be 1-based and ordered, "
                         f"got {window!r}")
    with _lock:
        _armed[site] = {'kind': kind, 'prob': prob, 'seed': int(seed),
                        'first': first, 'last': last, 'count': 0,
                        'fired': 0}


def disarm(site=None):
    """Disarm one site (or every site) and reset occurrence counters."""
    with _lock:
        if site is None:
            _armed.clear()
        else:
            _armed.pop(site, None)


def active():
    """{site: spec} snapshot of the armed sites (counters included)."""
    with _lock:
        return {s: dict(spec) for s, spec in _armed.items()}


def is_armed(site=None):
    """Lock-free armed check (the same fast path fire() uses): is ANY
    site armed (``site=None``), or this specific site? Safe to call on
    hot paths."""
    if site is None:
        return bool(_armed)
    return site in _armed


def arm_from_env(spec=None):
    """Parse an ``MXTPU_FAULT`` spec string and arm the named sites.
    Called at package import; call again after changing the env var.
    Returns the number of sites armed."""
    if spec is None:
        from .. import config as _config
        spec = _config.get('MXTPU_FAULT')
    disarm()
    spec = (spec or '').strip()
    if not spec:
        return 0
    n = 0
    for part in spec.replace(';', ',').split(','):
        part = part.strip()
        if not part:
            continue
        fields = part.split(':')
        if len(fields) < 2:
            raise MXNetError(
                f"MXTPU_FAULT spec {part!r}: expected "
                f"site:kind[:prob[:seed[:first-last]]]")
        site, kind = fields[0], fields[1]
        try:
            prob = float(fields[2]) if len(fields) > 2 and fields[2] \
                else 1.0
            seed = int(fields[3]) if len(fields) > 3 and fields[3] else 0
            window = None
            if len(fields) > 4 and fields[4]:
                w = fields[4]
                if '-' in w:
                    a, b = w.split('-', 1)
                    window = (int(a), int(b))
                else:
                    window = int(w)
        except ValueError as e:
            # same loud-typo contract as unknown sites/kinds: a bad
            # numeric field must name the env var and the grammar, not
            # crash import with a bare ValueError
            raise MXNetError(
                f"MXTPU_FAULT spec {part!r}: bad numeric field ({e}); "
                f"expected site:kind[:prob[:seed[:first-last]]]")
        arm(site, kind, prob=prob, seed=seed, window=window)
        n += 1
    return n


def _unit(seed, occurrence):
    """Deterministic uniform [0, 1) for (seed, occurrence) — stable
    across processes/platforms (sha256, not the process RNG)."""
    h = hashlib.sha256(f'{seed}:{occurrence}'.encode()).digest()
    return int.from_bytes(h[:8], 'big') / float(1 << 64)


def fire(site, occurrence=None):
    """Advance `site`'s occurrence counter and fire the armed fault when
    the deterministic (seed, occurrence) stream says so.

    ``occurrence`` — explicit 1-based occurrence key for sites whose
    natural ordering is data-defined rather than call-defined: io.decode
    passes the record index, so a multi-threaded decode pool corrupts
    the SAME records on every run no matter how its threads interleave.
    When omitted the site's process-global call counter is the key.

    Returns None (not armed / did not fire) or the fault kind. ``raise``
    raises InjectedFault here; ``hang`` sleeps MXTPU_FAULT_HANG_SECONDS
    here (that IS the fault — a stalled call the watchdog should catch);
    ``corrupt`` / ``nan`` are returned for the site to apply to its own
    payload (see corrupt_bytes)."""
    if not _armed:      # the disarmed fast path: no lock, one dict check
        return None
    with _lock:
        spec = _armed.get(site)
        if spec is None:
            return None
        spec['count'] += 1
        n = spec['count'] if occurrence is None else int(occurrence)
        if n < spec['first'] or \
                (spec['last'] is not None and n > spec['last']):
            return None
        if spec['prob'] < 1.0 and _unit(spec['seed'], n) >= spec['prob']:
            return None
        spec['fired'] += 1
        kind = spec['kind']
    if _telem['on']:
        from .. import telemetry as _telemetry
        _telemetry.inc('mxnet_tpu_resilience_faults_injected_total',
                       site=site, kind=kind)
    # flight recorder: a fired fault is exactly the kind of event a
    # post-mortem needs in its timeline (no-op unless tracing is armed)
    from ..telemetry import flight as _flight
    _flight.note('fault', site=site, fault_kind=kind, occurrence=n)
    if kind == 'raise':
        raise InjectedFault(site, n)
    if kind == 'hang':
        from .. import config as _config
        _time.sleep(_config.get('MXTPU_FAULT_HANG_SECONDS'))
    return kind


def corrupt_bytes(data, occurrence=0):
    """Deterministically mangle a bytes payload: the first 16 bytes are
    overwritten with a seeded pattern (destroying any format magic so
    decoders fail loudly instead of producing silently-wrong pixels) and
    one mid-payload byte is flipped (so content hashes mismatch even for
    formats without magic)."""
    buf = bytearray(data)
    if not buf:
        return bytes(buf)
    pat = hashlib.sha256(b'mxtpu-fault-%d' % occurrence).digest()
    head = min(16, len(buf))
    buf[:head] = pat[:head]
    mid = len(buf) // 2
    buf[mid] ^= 0xA5
    return bytes(buf)
