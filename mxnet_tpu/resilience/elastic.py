"""Elastic multi-host training: peer loss -> commit -> re-form -> resume.

The reference survives worker churn at the ps-lite tracker level
(dist_sync workers re-register; PAPER.md layer 6); the GSPMD replacement
has no such story — one preempted host wedges every peer inside a
collective until the job is killed. This module is the recovery seam
between three existing substrates:

- the **membership side channel** (``parallel.dist.Membership``): rank-0
  coordinator + per-process heartbeat senders on a TCP socket, so peer
  loss is observable while the collective fabric is wedged;
- **layout-independent checkpoints** (``checkpoint.CheckpointManager``):
  every states payload is host-gathered fp32, so ANY survivor set can
  restore what any world size committed;
- the **resilience ladder** (guard -> rollback -> retry): the same
  commit/restore/re-place plumbing, pointed at a world-size change
  instead of a NaN burst.

``ElasticController`` supervises a training loop::

    ms  = dist.start_membership()                  # or MXTPU_ELASTIC=1
    ctl = resilience.ElasticController(manager=mgr, step=sharded_step)
    ctl.install()                                  # SIGTERM -> preempt
    i = start
    while i < total:
        resumed = ctl.pre_step()                   # peer lost? commit+reform
        if resumed is not None:
            i = resumed                            # back to the commit
            continue
        loss = sharded_step(data, label)
        i += 1
        ctl.beat(i)                            # feeds the heartbeats

On **SIGTERM** (preemption notice): commit a final checkpoint, say
goodbye on the side channel (peers see a departure, not a failure) and
raise ``Preempted`` — the loop exits resumable. On **peer loss** (a
heartbeat age past ``MXTPU_PEER_DEADLINE_SECONDS``): commit at the last
completed step, tear down ``jax.distributed`` (bounded — the runtime's
own shutdown barrier would wait for the dead peer), re-form the mesh at
the survivor world size, re-place params/optimizer state through the
attached step/trainer hooks, restore the committed checkpoint and
return the resumed step. ``gluon.Trainer`` loops run unmodified via
``trainer.attach_elastic(ctl)``.
"""
from __future__ import annotations

import logging
import signal as _signal
import threading
import time as _time

from ..base import MXNetError, telem_flags as _telem

__all__ = ['Preempted', 'PeerLossError', 'ElasticController',
           'stall_verdict', 'raise_if_peer_lost']

_log = logging.getLogger('mxnet_tpu.resilience')


class Preempted(MXNetError):
    """Raised by ``ElasticController.pre_step()`` after a SIGTERM: the
    final checkpoint is committed — the process should exit and be
    restarted (or not) by its scheduler."""

    def __init__(self, step):
        super().__init__(
            f"preemption notice received: final checkpoint committed — "
            f"resumable from step {step}")
        self.step = step


class PeerLossError(MXNetError):
    """A peer went silent past the deadline and the caller cannot
    re-form (no manager/controller) — raised instead of entering a
    collective that would wedge forever."""

    def __init__(self, lost, ages=None):
        ages = ages or {}
        detail = ', '.join(
            f"rank {r} (last heartbeat {ages.get(r, float('nan')):.1f}s "
            f"ago)" for r in lost)
        super().__init__(
            f"peer loss detected on the membership side channel: "
            f"{detail or lost} — refusing to enter a collective that "
            f"would wedge; commit + re-form via "
            f"resilience.ElasticController, or restart the job")
        self.lost = list(lost)


def raise_if_peer_lost():
    """Shared guard for collective entry points (ShardedTrainStep
    dispatch, dist kvstore push): once the membership layer has declared
    a peer lost, entering a cross-process collective would wedge forever
    — raise the recoverable ``PeerLossError`` instead. No-op without a
    membership layer."""
    from ..parallel import dist as _dist
    ms = _dist.membership()
    if ms is None:
        return
    lost = ms.lost_peers()
    if lost:
        raise PeerLossError(lost, ms.peer_ages())


def stall_verdict(membership=None):
    """Classify a stall: ``peer_loss`` (some peer's heartbeat age is
    past the deadline — the wedge is a REMOTE preemption) vs
    ``straggler`` (every peer heartbeats but the fleet telemetry names
    a slowest/most-stale rank — ISSUE 13) vs ``local_stall`` (every
    peer is beating and nobody straggles — the wedge is local code).
    Returns ``{'verdict', 'peer_ages', 'lost', 'deadline_seconds'}``
    (plus ``'during': 'replica_fetch'`` when a checkpoint replica fetch
    is in flight — then the serving peer is the prime suspect even
    while it still heartbeats — and ``'straggler'`` when cross-rank
    fleet snapshots are available: the suspected rank with its
    last-snapshot age, ``flagged`` saying whether a detector actually
    tripped vs a worst-of-fleet fallback) or None when no membership
    layer is running and nothing remote is in flight (single-process
    jobs have no peers to blame)."""
    fetching = 0
    try:
        from ..checkpoint import replica as _replica
        fetching = _replica.active_fetches()
    except Exception:
        pass
    if membership is None:
        from ..parallel import dist as _dist
        membership = _dist.membership()
    if membership is None:
        if not fetching:
            # single-process: no peers to blame, but an open compile
            # window still classifies the stall — XLA is just slow
            try:
                from ..telemetry import compile as _compile
                fl = _compile.in_flight()
            except Exception:
                fl = None
            if fl is None:
                return None
            c = dict(fl)
            c['rank'] = None
            return {'verdict': 'compiling', 'peer_ages': {},
                    'lost': [], 'deadline_seconds': 0.0,
                    'compiling': c}
        return {'verdict': 'peer_loss_suspected', 'peer_ages': {},
                'lost': [], 'deadline_seconds': 0.0,
                'during': 'replica_fetch'}
    try:
        lost = membership.lost_peers()
        ages = membership.peer_ages()
    except Exception:
        return None
    v = {
        'verdict': 'peer_loss_suspected' if (lost or fetching)
                   else 'local_stall',
        'peer_ages': {int(r): round(float(a), 3)
                      for r, a in ages.items()},
        'lost': [int(r) for r in lost],
        'deadline_seconds': membership.deadline_seconds,
    }
    if fetching:
        v['during'] = 'replica_fetch'
    # scale-up admission upgrade: a "local" stall while a JOIN
    # candidate is pending is almost always the admission rendezvous in
    # flight — every survivor quiesces at its next step boundary, so
    # the last ones to arrive see the early ones "stalled". The verdict
    # names the joining rank(s) and the rendezvous age instead of
    # blaming local code. Peer loss still wins: a rank dying DURING an
    # admission is the more urgent story.
    try:
        jm = getattr(membership, 'joining', None)
        joining = jm() if callable(jm) else {}
    except Exception:
        joining = {}
    if joining:
        v['joining'] = {int(r): round(float(a), 3)
                        for r, a in joining.items()}
        if v['verdict'] == 'local_stall':
            v['verdict'] = 'reform_pending'
    # fleet straggler upgrade (ISSUE 13): when cross-rank telemetry
    # snapshots are flowing, a "local" stall with a detector-flagged
    # straggler is most likely THIS rank waiting inside a collective on
    # the named rank — the verdict says so instead of blaming local
    # code. The coordinator reads its own monitor; every other rank
    # reads the flagged summary the coordinator attaches to each beat
    # reply (cached in the membership view, refreshed by the daemon
    # heartbeat thread even while the training thread is wedged).
    try:
        from ..telemetry import fleet as _fleet
        mon = _fleet.monitor()
        if mon is not None:
            s = mon.straggler(worst=True)
        else:
            s = (membership.view() or {}).get('straggler')
        if s is not None:
            v['straggler'] = s
            if v['verdict'] == 'local_stall' and s.get('flagged'):
                v['verdict'] = 'straggler_suspected'
    except Exception:
        pass
    # compile-window upgrade (ISSUE 16): a rank mid-compile is not
    # wedged — XLA is just slow. Prefer the LOCAL open window (this
    # rank is the one compiling), else the straggler's heartbeat-
    # carried window (rank N is compiling; everyone else is waiting in
    # a collective on it).
    try:
        from ..telemetry import compile as _compile
        fl = _compile.in_flight()
        if fl is not None:
            c = dict(fl)
            c['rank'] = getattr(membership, 'rank', None)
            v['compiling'] = c
            if v['verdict'] == 'local_stall':
                v['verdict'] = 'compiling'
        else:
            s = v.get('straggler')
            if s and s.get('compiling'):
                c = dict(s['compiling'])
                c['rank'] = s.get('rank')
                v['compiling'] = c
                if v['verdict'] in ('local_stall',
                                    'straggler_suspected'):
                    v['verdict'] = 'compiling'
    except Exception:
        pass
    return v


class ElasticController:
    """Supervises commit -> re-form -> resume for one training loop.

    Parameters
    ----------
    manager : checkpoint.CheckpointManager
        Commits the final checkpoint and restores it post-re-form.
    membership : parallel.dist.Membership, optional
        Defaults to the process-global one (``dist.membership()``),
        resolved lazily so construction order does not matter.
    step / trainer : optional
        A ``ShardedTrainStep`` (re-formed via ``reset_mesh``) and/or a
        ``gluon.Trainer`` (re-formed via ``_on_reform``); attach more
        with ``attach_step`` / ``attach_trainer``.
    mesh_fn : callable(new_world, new_rank) -> Mesh, optional
        Builds the survivor mesh. Default: every LOCAL device on one
        ``dp`` axis (always valid for the survivors' processes; a
        process-spanning re-form needs ``reinit_fn`` too).
    reinit_fn : callable(new_world, new_rank) -> None, optional
        Re-initializes ``jax.distributed`` for a >1-process survivor
        world (deployment-specific: someone must pick the new
        coordinator address). Without it a multi-process re-form keeps
        process-local meshes and logs what it skipped.
    coordinator_host_fn : callable(rank) -> host, optional
        Resolves a rank's host for membership-coordinator failover:
        when rank 0 dies, the lowest survivor promotes itself and the
        others retarget their heartbeats at it. Default keeps the
        current host (correct when survivors share one, e.g. the CPU
        drill; multi-host deployments must supply the resolver).
    commit_on_reform : bool
        Whether a peer-loss re-form commits a checkpoint at this rank's
        last completed step before restoring (default True). Set False
        on ranks that do NOT own the checkpoint directory (deployments
        where only one rank writes checkpoints): their re-form then
        rolls straight back to the newest committed copy — which, when
        the owner died WITH its disk, the any-replica restore fetches
        from a hosted peer replica.
    """

    def __init__(self, manager, membership=None, step=None, trainer=None,
                 mesh_fn=None, reinit_fn=None, on_reform=None,
                 coordinator_host_fn=None, commit_on_reform=True):
        self.manager = manager
        self._membership = membership
        self._steps = [step] if step is not None else []
        self._trainers = [trainer] if trainer is not None else []
        self.mesh_fn = mesh_fn
        self.reinit_fn = reinit_fn
        self.coordinator_host_fn = coordinator_host_fn
        self.commit_on_reform = bool(commit_on_reform)
        self._on_reform_hooks = [on_reform] if on_reform else []
        self.preempt_requested = False
        self.last_step = None
        self.peer_losses = 0
        self.reforms = 0
        self.last_reform = None       # phase timings of the newest re-form
        self._old_handlers = {}
        self._monitor = None
        self._monitor_stop = threading.Event()
        # suspected-lost ranks: mutated by the monitor thread (update)
        # AND the training thread's _reform (clear after recovery) —
        # both sides go through _suspected_lock
        self._suspected = set()
        self._suspected_lock = threading.Lock()

    # -- wiring ------------------------------------------------------------

    @property
    def membership(self):
        if self._membership is None:
            from ..parallel import dist as _dist
            self._membership = _dist.membership()
        return self._membership

    def attach_step(self, step):
        """Attach a ShardedTrainStep: re-formed via ``reset_mesh``."""
        self._steps.append(step)
        return self

    def attach_trainer(self, trainer):
        """Attach a gluon Trainer: re-formed via ``_on_reform`` (and its
        ``step()`` consults this controller when bound the other way
        round with ``trainer.attach_elastic``)."""
        self._trainers.append(trainer)
        return self

    def add_reform_hook(self, fn):
        """Run ``fn(mesh)`` after every re-form (post-restore)."""
        self._on_reform_hooks.append(fn)

    # -- preemption --------------------------------------------------------

    def install(self, signals=(_signal.SIGTERM,)):
        """SIGTERM -> ``preempt_requested`` (the commit happens at the
        next ``pre_step``, on the training thread, where device state is
        consistent). Chains any previous handler — including the
        CheckpointManager preemption hook, so the grace-window save
        still runs even if the loop never reaches another step."""
        for sig in signals:
            try:
                old = _signal.signal(sig, self._on_signal)
            except ValueError:
                import warnings
                warnings.warn(
                    "elastic preemption hook not installed: signal "
                    "handlers can only be set from the main thread",
                    RuntimeWarning)
                return self
            self._old_handlers.setdefault(sig, old)
        return self

    def uninstall(self):
        for sig, old in self._old_handlers.items():
            _signal.signal(sig, old if old is not None else _signal.SIG_DFL)
        self._old_handlers.clear()

    def _on_signal(self, signum, frame):
        # handler body stays lock-free: flight.note takes the recorder
        # lock, and a signal landing while THIS thread holds it (e.g.
        # inside record_step under MXTPU_TRACE) would self-deadlock —
        # the note is emitted from pre_step instead
        self.preempt_requested = True
        self._preempt_signum = int(signum)
        old = self._old_handlers.get(signum)
        if callable(old):
            old(signum, frame)

    # -- monitor thread ----------------------------------------------------

    def start_monitor(self, poll_seconds=None):
        """Background collective-deadline monitor: polls the membership
        and records a classified ``elastic.peer_loss_suspected`` flight
        note + telemetry the moment a peer goes silent — even while the
        training thread is wedged inside a collective (the re-form
        itself still happens on the training thread at ``pre_step``,
        where device state is consistent)."""
        if self._monitor is not None and self._monitor.is_alive():
            return self
        ms = self.membership
        poll = float(poll_seconds) if poll_seconds else max(
            0.05, (ms.heartbeat_seconds if ms else 1.0))
        self._monitor_stop.clear()
        self._monitor = threading.Thread(
            target=self._monitor_run, args=(poll,), daemon=True,
            name='mxtpu-elastic-monitor')
        self._monitor.start()
        return self

    def stop_monitor(self):
        self._monitor_stop.set()
        t = self._monitor
        if t is not None:
            t.join(timeout=2.0)
        self._monitor = None

    def _monitor_run(self, poll):
        from ..telemetry import flight as _flight
        while not self._monitor_stop.wait(poll):
            ms = self.membership
            if ms is None:
                continue
            try:
                lost_now = ms.lost_peers()
            except Exception:
                continue
            with self._suspected_lock:
                lost = [r for r in lost_now
                        if r not in self._suspected]
                self._suspected.update(lost)
            if not lost:
                continue
            v = stall_verdict(ms) or {}
            _log.error(
                "elastic monitor: peer(s) %s silent past the %.1fs "
                "deadline (ages: %s) — will commit + re-form at the "
                "next step boundary", lost, ms.deadline_seconds,
                v.get('peer_ages'))
            _flight.note('elastic.peer_loss_suspected', lost=lost,
                         peer_ages=v.get('peer_ages'))

    # -- per-step supervision ----------------------------------------------

    def beat(self, step):
        """The training loop completed ``step``. Cheap: remembers the
        commit point and piggybacks it on the next heartbeat."""
        self.last_step = int(step)
        ms = self.membership
        if ms is not None:
            ms.current_step = int(step)

    def pre_step(self):
        """Call at the start of every training step (gluon ``Trainer``
        does this automatically once ``attach_elastic`` is bound).

        - Preemption requested: commit the final checkpoint, leave the
          membership gracefully, raise ``Preempted``.
        - Peer lost: commit, tear down, re-form at the survivor world
          size, restore — returns the RESUMED step number (the loop
          should continue from there).
        - JOIN candidate pending: commit, quiesce, admission
          rendezvous, re-form at the LARGER world, restore — returns
          the resumed step, same contract as the shrink path.
        - Otherwise: returns None, costing a few lock-free reads.
        """
        if self.preempt_requested:
            self._commit(final=True)
            ms = self.membership
            if ms is not None:
                ms.leave()
            from ..telemetry import flight as _flight
            _flight.note('elastic.preempt_exit', step=self.last_step,
                         signum=getattr(self, '_preempt_signum', None))
            raise Preempted(self.last_step)
        ms = self.membership
        if ms is None:
            return None
        lost = ms.lost_peers()
        if lost:
            return self._reform(lost)
        joining = self._pending_joins(ms)
        if joining:
            return self._admit(joining)
        return None

    @staticmethod
    def _pending_joins(ms):
        jm = getattr(ms, 'joining', None)
        try:
            return jm() if callable(jm) else {}
        except Exception:
            return {}

    # -- the re-form path --------------------------------------------------

    def _commit(self, final=False):
        if self.manager is None:
            return None
        step = self.last_step
        if step is None:
            step = self.manager._current_step or 0
        if self.manager.latest_step() == int(step):
            self.manager.wait()       # already committed (cadence save)
            return int(step)
        self.manager.save_now(int(step))
        if final:
            _log.warning(
                "elastic: final checkpoint committed at step %d", step)
        return int(step)

    def _reform(self, lost):
        from ..telemetry import flight as _flight, trace as _trace
        from ..parallel import dist as _dist
        from ..parallel.mesh import make_mesh, set_default_mesh
        import jax

        ms = self.membership
        ages = {}
        try:
            ages = ms.peer_ages()
        except Exception:
            pass
        self.peer_losses += len(lost)
        if _telem['on']:
            from .. import telemetry as _telemetry
            _telemetry.counter(
                'mxnet_tpu_elastic_peer_losses_total').inc(len(lost))
        _log.error(
            "elastic: peer(s) %s lost (heartbeat ages %s > %.1fs "
            "deadline) — committing, re-forming at the survivor world "
            "size", lost, {r: ages.get(r) for r in lost},
            ms.deadline_seconds)
        _flight.note('elastic.peer_loss', lost=list(lost),
                     peer_ages={int(r): ages.get(r) for r in lost})
        t0 = _time.perf_counter()
        with _trace.span('elastic.reform', lost=len(lost)):
            # 1. commit: the survivors' restart point. States payloads
            # are host-gathered (PR-4/PR-7 layout independence), so this
            # world's layout does not constrain who restores it. Ranks
            # that don't own the checkpoint dir (commit_on_reform=False)
            # skip this and roll back to the newest committed copy —
            # locally, or from a peer replica when the owner's disk died
            # with it (manager.restore_latest's any-replica fallback).
            committed = self._commit() if self.commit_on_reform else \
                (self.manager.latest_step()
                 if self.manager is not None else None)
            t_commit = _time.perf_counter()
            # 2. tear down the old world (bounded: the runtime's shutdown
            # barrier waits for the dead peer). Survivors are computed
            # BEFORE remove_peers: once the lost set is retired, a stale
            # coordinator-produced view could no longer exclude it.
            survivors = sorted(
                (set(ms.alive()) | {ms.rank}) - set(lost))
            _dist.shutdown()
            ms.remove_peers(lost)
            new_world = len(survivors)
            new_rank = survivors.index(ms.rank)
            if 0 in lost:
                if new_rank == 0:
                    # lowest survivor inherits the side channel
                    ms.become_coordinator()
                else:
                    ms.retarget(host=self.coordinator_host_fn(survivors[0])
                                if self.coordinator_host_fn else None)
            # 3. re-form at the new world size. One FIXED tag for every
            # re-form: survivors whose views diverged (losses declared a
            # heartbeat apart) must still rendezvous at the same tag —
            # the barrier's generation counter keeps successive re-forms
            # distinct, and its completion re-reads the live alive set,
            # so a straggler that dies mid-rendezvous is not waited for.
            if new_world > 1:
                ms.barrier('reform')
                if self.reinit_fn is not None:
                    self.reinit_fn(new_world, new_rank)
                else:
                    _log.warning(
                        "elastic: %d survivors but no reinit_fn — "
                        "keeping process-local meshes (cross-process "
                        "collectives need a new jax.distributed "
                        "coordinator; pass reinit_fn to re-span)",
                        new_world)
            if self.mesh_fn is not None:
                mesh = self.mesh_fn(new_world, new_rank)
            else:
                mesh = make_mesh(devices=jax.local_devices())
            set_default_mesh(mesh)
            t_teardown = _time.perf_counter()
            # 4. re-place + restore: steps drop their compiled programs
            # and shardings (rebuilt at the new world on next call),
            # then the committed checkpoint restores params + optimizer
            # state + RNG through the layout-independent payloads.
            for st in self._steps:
                st.reset_mesh(mesh)
            for tr in self._trainers:
                tr._on_reform(mesh)
            resumed = self.manager.restore_latest() \
                if self.manager is not None else committed
            for fn in self._on_reform_hooks:
                fn(mesh)
        dt = _time.perf_counter() - t0
        self.reforms += 1
        with self._suspected_lock:
            self._suspected -= set(lost)
        self.last_reform = {
            'lost': list(lost),
            'world': new_world,
            'rank': new_rank,
            'resumed_step': resumed,
            'commit_seconds': round(t_commit - t0, 4),
            'teardown_seconds': round(t_teardown - t_commit, 4),
            'restore_seconds': round(
                dt - (t_teardown - t0), 4),
            'reform_seconds': round(dt, 4),
        }
        if _telem['on']:
            from .. import telemetry as _telemetry
            _telemetry.inc('mxnet_tpu_elastic_reforms_total')
            _telemetry.set_gauge('mxnet_tpu_elastic_last_world_size',
                                 new_world)
            _telemetry.observe('mxnet_tpu_elastic_reform_seconds', dt)
        _log.warning(
            "elastic: re-formed at world size %d (rank %d) in %.3fs "
            "(commit %.3fs, teardown %.3fs, restore %.3fs) — resuming "
            "from committed step %s", new_world, new_rank, dt,
            self.last_reform['commit_seconds'],
            self.last_reform['teardown_seconds'],
            self.last_reform['restore_seconds'], resumed)
        _flight.note('elastic.reform', **self.last_reform)
        return resumed

    # -- the scale-up admission path ---------------------------------------

    def join(self, timeout=None):
        """Joiner-side admission (scale-UP): announce this rank on the
        membership side channel, rendezvous with the survivors when
        they quiesce at their next step boundary, re-form the mesh at
        the LARGER world and restore the committed checkpoint — the
        attach-anytime property the reference's kvstore fleet had.
        Bounded by ``MXTPU_JOIN_TIMEOUT_SECONDS``. Returns the resumed
        step (None when nothing was committed yet)."""
        return self._admit({}, joiner=True, timeout=timeout)

    def _admit(self, joining, joiner=False, timeout=None):
        from .. import config as _config
        from ..telemetry import flight as _flight, trace as _trace
        from ..parallel import dist as _dist
        from ..parallel.mesh import make_mesh, set_default_mesh
        from . import faults as _faults
        import jax

        ms = self.membership
        _faults.fire('elastic.admit')
        timeout = float(timeout) if timeout is not None else \
            float(_config.get('MXTPU_JOIN_TIMEOUT_SECONDS'))
        if not joiner:
            _log.warning(
                "elastic: JOIN candidate(s) %s pending (announced %ss "
                "ago) — committing, quiescing at this step boundary "
                "and re-forming at the larger world", sorted(joining),
                {r: round(a, 1) for r, a in joining.items()})
        t0 = _time.perf_counter()
        with _trace.span('elastic.admit', joining=len(joining)):
            # 1. survivors commit: the admission's restart point. The
            # payloads are host-gathered fp32, so the joiner re-places
            # state committed by a world it was never part of. The
            # joiner itself has nothing to commit (and no live
            # jax.distributed world to tear down).
            committed = None
            if not joiner:
                committed = self._commit() if self.commit_on_reform \
                    else (self.manager.latest_step()
                          if self.manager is not None else None)
                _dist.shutdown()
            t_commit = _time.perf_counter()
            # 2. the generation-counted admission rendezvous: it
            # completes only when every ALIVE rank and every PENDING
            # joiner has arrived, and completion atomically promotes
            # the joiners into the alive set — the completed reply's
            # view is already the larger world, identical on every
            # rank. A joiner whose announcement was cancelled by a
            # concurrent loss re-form (removed ranks drop pending
            # joins) re-announces and waits for the next boundary.
            deadline = _time.monotonic() + timeout
            while True:
                if joiner:
                    ms.join()
                view = ms.barrier(
                    _dist.ADMIT_TAG,
                    timeout=max(1.0, deadline - _time.monotonic()))
                alive = sorted(int(r) for r in view.get('alive', []))
                if ms.rank in alive:
                    break
                if not joiner or _time.monotonic() > deadline:
                    raise MXNetError(
                        f"elastic admission failed: rank {ms.rank} not "
                        f"in the post-rendezvous alive set {alive} "
                        f"(announcement cancelled by a concurrent "
                        f"re-form?)")
            new_world = len(alive)
            new_rank = alive.index(ms.rank)
            if new_world > 1:
                if self.reinit_fn is not None:
                    self.reinit_fn(new_world, new_rank)
                else:
                    _log.warning(
                        "elastic: %d ranks after admission but no "
                        "reinit_fn — keeping process-local meshes "
                        "(cross-process collectives need a new "
                        "jax.distributed coordinator; pass reinit_fn "
                        "to re-span)", new_world)
            if self.mesh_fn is not None:
                mesh = self.mesh_fn(new_world, new_rank)
            else:
                mesh = make_mesh(devices=jax.local_devices())
            set_default_mesh(mesh)
            t_rendezvous = _time.perf_counter()
            # 3. re-place + restore at the larger world: survivors drop
            # compiled programs/shardings and re-derive their ZeRO
            # stage for the new dp degree (reset_mesh handles growth
            # the same way it handles shrink), the joiner compiles
            # fresh; then the committed layout-independent checkpoint
            # restores params + optimizer state + RNG on every rank.
            for st in self._steps:
                st.reset_mesh(mesh)
            for tr in self._trainers:
                tr._on_reform(mesh)
            resumed = self.manager.restore_latest() \
                if self.manager is not None else committed
            for fn in self._on_reform_hooks:
                fn(mesh)
        dt = _time.perf_counter() - t0
        self.reforms += 1
        self.last_reform = {
            'joined': [ms.rank] if joiner
                      else sorted(int(r) for r in joining),
            'world': new_world,
            'rank': new_rank,
            'resumed_step': resumed,
            'grow': True,
            'commit_seconds': round(t_commit - t0, 4),
            'rendezvous_seconds': round(t_rendezvous - t_commit, 4),
            'restore_seconds': round(dt - (t_rendezvous - t0), 4),
            'admission_seconds': round(dt, 4),
            'reform_seconds': round(dt, 4),
        }
        if _telem['on']:
            from .. import telemetry as _telemetry
            _telemetry.inc('mxnet_tpu_elastic_reforms_total')
            _telemetry.set_gauge('mxnet_tpu_elastic_last_world_size',
                                 new_world)
            _telemetry.observe('mxnet_tpu_elastic_admission_seconds', dt)
        _log.warning(
            "elastic: admitted rank(s) %s — re-formed at world size %d "
            "(rank %d) in %.3fs (commit %.3fs, rendezvous %.3fs, "
            "restore %.3fs) — resuming from committed step %s",
            self.last_reform['joined'], new_world, new_rank, dt,
            self.last_reform['commit_seconds'],
            self.last_reform['rendezvous_seconds'],
            self.last_reform['restore_seconds'], resumed)
        _flight.note('elastic.admit', **self.last_reform)
        return resumed

    # -- lifecycle ---------------------------------------------------------

    def close(self):
        self.stop_monitor()
        self.uninstall()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
