"""Engine control surface (ref: python/mxnet/engine.py, src/engine/).

The reference's dependency engine schedules each op asynchronously with
read/write var tracking. On TPU, XLA + jax's async dispatch own scheduling,
so this module provides the *API* (bulk scopes, waitall) with jax-backed
semantics: `bulk` maps to a jit-staging hint (no-op today — XLA already
fuses), `set_bulk_size` is retained for script compatibility.
"""
from __future__ import annotations

import contextlib

_bulk_size = 15


def set_bulk_size(size):
    global _bulk_size
    prev = _bulk_size
    _bulk_size = size
    return prev


def bulk(size):
    """Ref: python/mxnet/engine.py bulk."""
    @contextlib.contextmanager
    def _ctx():
        prev = set_bulk_size(size)
        try:
            yield
        finally:
            set_bulk_size(prev)
    return _ctx()
