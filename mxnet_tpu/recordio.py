"""RecordIO file format: MXRecordIO / MXIndexedRecordIO / pack-unpack.

Ref: python/mxnet/recordio.py and dmlc-core recordio. Binary-compatible with
the reference format: records framed as [magic u32][lrec u32][data][pad to 4B]
where lrec encodes cflag (top 3 bits) and length (29 bits); image records
carry an IRHeader (flag, label, id, id2).
"""
from __future__ import annotations

import collections
import os
import struct

import numpy as onp

from .base import DataError, MXNetError

_MAGIC = 0xced7230a

IRHeader = collections.namedtuple('HEADER', ['flag', 'label', 'id', 'id2'])
_IR_FORMAT = 'IfQQ'
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def _encode_lrec(cflag, length):
    return (cflag << 29) | length


def _decode_lrec(lrec):
    return (lrec >> 29) & 7, lrec & ((1 << 29) - 1)


class MXRecordIO:
    """Sequential .rec reader/writer (ref: recordio.py MXRecordIO).

    Backed by the native C++ runtime (src/io/mxtpu_io.cc — the analog of
    dmlc-core's recordio + the reference's C API handles) when the shared
    library is available; a pure-Python file path otherwise. Both produce
    identical bytes.
    """

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self._native = None
        self.is_open = False
        self.open()

    def open(self):
        from . import _native
        lib = _native.get_lib()
        if self.flag == 'w':
            self.writable = True
        elif self.flag == 'r':
            self.writable = False
        else:
            raise MXNetError(f"invalid flag {self.flag}")
        if lib is not None:
            path = self.uri.encode()
            h = (lib.mxt_recordio_writer_create(path) if self.writable
                 else lib.mxt_recordio_reader_create(path))
            if not h:
                raise MXNetError(f"cannot open {self.uri}")
            self._native = (lib, h)
            self._wpos = 0  # a reopen truncates; stale offsets corrupt .idx
        else:
            self.handle = open(self.uri, 'wb' if self.writable else 'rb')
        self.is_open = True
        self._read_count = 0   # sequential record index for error context

    def close(self):
        if not self.is_open:
            return
        if self._native is not None:
            lib, h = self._native
            if self.writable:
                lib.mxt_recordio_writer_free(h)
            else:
                lib.mxt_recordio_reader_free(h)
            self._native = None
        if self.handle:
            self.handle.close()
            self.handle = None
        self.is_open = False

    def __del__(self):
        self.close()

    def __getstate__(self):
        d = dict(self.__dict__)
        d['handle'] = None
        d['_native'] = None
        d['is_open'] = False
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        if not self.is_open:
            self.open()

    def reset(self):
        self.close()
        self.open()

    def tell(self):
        if self._native is not None:
            lib, h = self._native
            if self.writable:
                return getattr(self, '_wpos', 0)
            return lib.mxt_recordio_reader_tell(h)
        return self.handle.tell()

    def seek(self, pos):
        assert not self.writable
        # sequential record counting is meaningless after a random seek;
        # None makes read()'s corrupt-record context say "record ?"
        # instead of naming the WRONG record (MXIndexedRecordIO.read_idx
        # fills in the real key)
        self._read_count = None
        if self._native is not None:
            lib, h = self._native
            lib.mxt_recordio_reader_seek(h, pos)
        else:
            self.handle.seek(pos)

    def write(self, buf):
        assert self.writable
        if self._native is not None:
            import ctypes
            lib, h = self._native
            pos = ctypes.c_uint64()
            if lib.mxt_recordio_writer_write(h, bytes(buf), len(buf),
                                             ctypes.byref(pos)) != 0:
                raise MXNetError(f"write failed on {self.uri}")
            # next record's start offset, for MXIndexedRecordIO.write_idx
            self._wpos = pos.value + 8 + len(buf) + (4 - len(buf) % 4) % 4
            return
        lrec = _encode_lrec(0, len(buf))
        self.handle.write(struct.pack('<II', _MAGIC, lrec))
        self.handle.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self.handle.write(b'\x00' * pad)

    def _data_error(self, what, pos, detail=''):
        # _read_count is None after a random seek (sequential index
        # unknown) — say "record ?" rather than naming the wrong record
        rec = self._read_count if self._read_count is not None else '?'
        return DataError(
            f"{what} in {self.uri} (record {rec} at offset {pos}"
            + (f": {detail}" if detail else '') + ')',
            index=self._read_count, offset=pos, path=self.uri)

    def read(self):
        assert not self.writable
        if self._native is not None:
            import ctypes
            lib, h = self._native
            out = ctypes.c_char_p()
            n = lib.mxt_recordio_reader_read(h, ctypes.byref(out))
            if n == -1:
                return None
            if n < 0:
                # tell() only on the error path (a failed read does not
                # advance past the bad record) — the happy path stays at
                # one FFI call per record
                raise self._data_error('invalid record magic',
                                       lib.mxt_recordio_reader_tell(h))
            if self._read_count is not None:
                self._read_count += 1
            return ctypes.string_at(out, n)
        pos = self.handle.tell()
        head = self.handle.read(8)
        if not head:
            return None
        if len(head) < 8:
            raise self._data_error('truncated record header', pos)
        magic, lrec = struct.unpack('<II', head)
        if magic != _MAGIC:
            raise self._data_error('invalid record magic', pos)
        _, length = _decode_lrec(lrec)
        buf = self.handle.read(length)
        if len(buf) < length:
            raise self._data_error(
                'truncated record payload', pos,
                f'read {len(buf)} of {length} bytes')
        pad = (4 - length % 4) % 4
        if pad:
            self.handle.read(pad)
        if self._read_count is not None:
            self._read_count += 1
        return buf


class MXIndexedRecordIO(MXRecordIO):
    """Random-access .rec with .idx (ref: recordio.py MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin:
                    parts = line.strip().split('\t')
                    if len(parts) < 2:
                        continue
                    key = self.key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)

    def close(self):
        if self.is_open and self.writable:
            with open(self.idx_path, 'w') as fout:
                for k in self.keys:
                    fout.write(f"{k}\t{self.idx[k]}\n")
        super().close()

    def seek(self, idx):
        super().seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        try:
            return self.read()
        except DataError as e:
            # random access knows the real record key — restore the
            # context the sequential counter lost at seek()
            raise DataError(
                f"record {idx!r} in {self.uri} (offset {e.offset}): {e}",
                index=idx, offset=e.offset, path=self.uri) from e

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


def pack(header, s):
    """Pack a string with IRHeader (ref: recordio.py pack)."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        hdr = struct.pack(_IR_FORMAT, 0, float(header.label), header.id, header.id2)
        return hdr + s
    label = onp.asarray(header.label, dtype=onp.float32)
    hdr = struct.pack(_IR_FORMAT, label.size, 0.0, header.id, header.id2)
    return hdr + label.tobytes() + s


def unpack(s):
    """Unpack to (IRHeader, payload) (ref: recordio.py unpack)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = onp.frombuffer(s[:header.flag * 4], dtype=onp.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def unpack_img(s, iscolor=1):
    header, img_bytes = unpack(s)
    import io as _io
    from PIL import Image
    img = onp.asarray(Image.open(_io.BytesIO(img_bytes)))
    return header, img


def pack_img(header, img, quality=95, img_fmt='.jpg'):
    import io as _io
    from PIL import Image
    buf = _io.BytesIO()
    fmt = 'JPEG' if img_fmt in ('.jpg', '.jpeg') else 'PNG'
    Image.fromarray(onp.asarray(img)).save(buf, format=fmt, quality=quality)
    return pack(header, buf.getvalue())
