"""Python side of the C predict API (driven by src/predict/c_predict_api.cc).

Keeps the deployment path on the exact same executor the Python frontend
uses: SymbolBlock + jit-compiled forward (ref: src/c_api/c_predict_api.cc,
which rebuilt a static executor — here XLA compilation is the static
executor).
"""
from __future__ import annotations

import io as _pyio

import numpy as onp

__all__ = ['create', 'Predictor']


class Predictor:
    def __init__(self, symbol_json_str, param_bytes, input_keys,
                 input_shapes, dev_type):
        from . import symbol as sym_mod
        from .gluon.block import SymbolBlock
        from .ndarray.ndarray import array as nd_array

        s = sym_mod.fromjson(symbol_json_str)
        inputs = [sym_mod.var(k) for k in input_keys]
        self.block = SymbolBlock(s, inputs)
        # the C predict ABI is a deployment boundary — model files may come
        # from third parties, so the params blob is parsed as the
        # non-executable reference binary format only (no pickle;
        # ref: src/c_api/c_predict_api.cc consumes plain NDArray payloads)
        from .serialization import load_params_dict
        payload = load_params_dict(param_bytes, allow_pickle=False)
        self.block._load_arg_dict(
            {k: nd_array(v) for k, v in payload.items()})
        self.input_keys = list(input_keys)
        self.input_shapes = {k: tuple(int(d) for d in shp)
                             for k, shp in zip(input_keys, input_shapes)}
        self.inputs = {}
        self.outputs = []

    def set_input(self, key, data_bytes):
        if key not in self.input_shapes:
            raise KeyError(f"unknown input '{key}' "
                           f"(declared: {self.input_keys})")
        shape = self.input_shapes[key]
        arr = onp.frombuffer(data_bytes, dtype=onp.float32)
        expected = int(onp.prod(shape)) if shape else 1
        if arr.size != expected:
            raise ValueError(
                f"input '{key}': got {arr.size} floats, shape {shape} "
                f"needs {expected}")
        self.inputs[key] = arr.reshape(shape)

    def forward(self):
        from .ndarray.ndarray import array as nd_array
        missing = [k for k in self.input_keys if k not in self.inputs]
        if missing:
            raise ValueError(f"inputs not set: {missing}")
        args = [nd_array(self.inputs[k]) for k in self.input_keys]
        out = self.block(*args)
        self.outputs = list(out) if isinstance(out, (list, tuple)) else [out]

    def _out(self, index):
        if not self.outputs:
            raise ValueError("call forward() before reading outputs")
        if not 0 <= index < len(self.outputs):
            raise IndexError(f"output index {index} out of range")
        return self.outputs[index]

    def output_shape(self, index):
        return tuple(int(d) for d in self._out(index).shape)

    def output_bytes(self, index):
        return onp.ascontiguousarray(
            self._out(index).asnumpy().astype(onp.float32)).tobytes()


def create(symbol_json_str, param_bytes, input_keys, input_shapes, dev_type):
    return Predictor(symbol_json_str, param_bytes, input_keys, input_shapes,
                     dev_type)
