"""Module API: legacy symbolic training loop (ref: python/mxnet/module/).

BaseModule.fit (base_module.py:409), Module (module.py), BucketingModule
(bucketing_module.py). Data-parallel slicing over contexts follows
DataParallelExecutorGroup.decide_slices (executor_group.py:282); each
context gets its own compiled Executor.
"""
from __future__ import annotations

import logging

import numpy as onp

from .base import MXNetError
from .context import cpu
from .ndarray.ndarray import NDArray, array, zeros as nd_zeros
from .ndarray.utils import split_data
from . import metric as metric_mod
from . import optimizer as opt_mod
from . import initializer as init_mod
from .model import BatchEndParam, save_checkpoint, load_checkpoint
from . import symbol as sym_mod


class BaseModule:
    """Ref: module/base_module.py BaseModule."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    def forward_backward(self, data_batch):
        """Ref: base_module.py:193."""
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None, batch_end_callback=None,
              score_end_callback=None, reset=True, epoch=0, sparse_row_id_fn=None):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                bec = BatchEndParam(epoch, nbatch, eval_metric)
                for cb in _as_list(batch_end_callback):
                    cb(bec)
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        outputs = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad or 0
            out = self.get_outputs()[0]
            real = out.shape[0] - pad
            outputs.append(out[0:real] if pad else out)
        if merge_batches:
            from .ndarray import concat
            return concat(*outputs, dim=0) if len(outputs) > 1 else outputs[0]
        return outputs

    def fit(self, train_data, eval_data=None, eval_metric='acc',
            epoch_end_callback=None, batch_end_callback=None, kvstore='local',
            optimizer='sgd', optimizer_params=(('learning_rate', 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None, checkpoint_manager=None):
        """Training loop (ref: base_module.py:409).

        ``checkpoint_manager`` (or a ``callback.module_checkpoint(...,
        manager=...)`` in ``epoch_end_callback``) makes interrupts
        resumable: KeyboardInterrupt and SIGTERM commit one final
        synchronous checkpoint and exit cleanly with a "resumable from
        step N" message instead of a raw traceback."""
        assert num_epoch is not None, 'please specify number of epochs'
        if initializer is None:
            initializer = init_mod.Uniform(0.01)
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if monitor is not None:
            self.install_monitor(monitor)
        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        # explicit checkpoint_manager=: fit owns the save cadence and
        # numbers steps in the BATCH domain. A manager discovered from a
        # module_checkpoint callback already saves in the EPOCH domain
        # (iter_no+1) — fit must not add batch-numbered saves into the
        # same directory (retention sorts numerically; mixing domains
        # would GC epoch saves and skew resume numbering), so it only
        # polls preemption and reports on that manager.
        mgr = checkpoint_manager
        mgr_owns_cadence = checkpoint_manager is not None
        if mgr is None and epoch_end_callback is not None:
            for cb in _as_list(epoch_end_callback):
                if getattr(cb, 'manager', None) is not None:
                    mgr = cb.manager
                    break
        installed_hook = False
        bound_params = False
        if mgr is not None:
            if not mgr.params_bound:
                # Module managers are usually constructed params-unbound
                # (callback.module_checkpoint passes arg:/aux: per save);
                # bind a provider for the duration of fit so cadence
                # saves and the SIGTERM hook commit REAL parameters, not
                # empty checkpoints
                def _module_params():
                    from .callback import prefix_arg_aux_params
                    return prefix_arg_aux_params(*self.get_params())
                mgr.bind_params(_module_params)
                bound_params = True
            if not mgr.hook_installed:
                mgr.install_preemption_hook()
                installed_hook = mgr.hook_installed
        # step numbering continues from the manager's newest committed
        # checkpoint: a run resumed after an interrupt must not restart
        # at 0, or its new checkpoints sort below the stale pre-resume
        # ones and retention GCs the fresh progress first
        global_step = (mgr.latest_step() or 0) if mgr_owns_cadence else 0
        interrupted = None
        try:
            for epoch in range(begin_epoch, num_epoch):
                eval_metric.reset()
                nbatch = 0
                train_data.reset()
                for data_batch in train_data:
                    if monitor is not None:
                        monitor.tic()
                    self.forward_backward(data_batch)
                    self.update()
                    if monitor is not None:
                        monitor.toc_print()
                    self.update_metric(eval_metric, data_batch.label)
                    if batch_end_callback is not None:
                        bec = BatchEndParam(epoch, nbatch, eval_metric)
                        for cb in _as_list(batch_end_callback):
                            cb(bec)
                    nbatch += 1
                    global_step += 1
                    if mgr_owns_cadence:
                        # advances the manager's step (so a SIGTERM save
                        # lands on the right one) + autosave cadence
                        mgr.maybe_save(global_step,
                                       metadata={'epoch': epoch,
                                                 'nbatch': nbatch})
                    if mgr is not None and mgr.preempted:
                        interrupted = 'SIGTERM'
                        break
                if interrupted:
                    break
                for name, val in eval_metric.get_name_value():
                    self.logger.info('Epoch[%d] Train-%s=%f', epoch, name,
                                     val)
                if epoch_end_callback is not None:
                    arg_params, aux_params = self.get_params()
                    for cb in _as_list(epoch_end_callback):
                        cb(epoch, self.symbol, arg_params, aux_params)
                if eval_data is not None:
                    res = self.score(
                        eval_data, validation_metric,
                        score_end_callback=eval_end_callback,
                        batch_end_callback=eval_batch_end_callback,
                        epoch=epoch)
                    for name, val in res:
                        self.logger.info('Epoch[%d] Validation-%s=%f',
                                         epoch, name, val)
        except KeyboardInterrupt:
            interrupted = 'KeyboardInterrupt'
        finally:
            # the final interrupt save below still needs the bound
            # params provider — only the signal hook is torn down here;
            # the provider is unbound at the very end of fit, or right
            # now when an error is escaping (this finally is then the
            # last fit code that runs)
            if installed_hook:
                mgr.uninstall_preemption_hook()
            import sys as _sys
            if bound_params and _sys.exc_info()[0] is not None:
                mgr.bind_params(None)
                bound_params = False
        try:
            if interrupted:
                if mgr_owns_cadence and global_step:
                    try:
                        if mgr.latest_step() != global_step:
                            mgr.save_now(global_step)
                        self.logger.warning(
                            'training interrupted (%s); checkpoint '
                            'committed — resumable from step %d',
                            interrupted, global_step)
                    except Exception:
                        self.logger.exception(
                            'training interrupted (%s) but the final '
                            'checkpoint save failed', interrupted)
                elif mgr is not None:
                    # callback-owned manager: its saves live in the
                    # EPOCH domain — report what is committed, add
                    # nothing
                    latest = mgr.latest_step()
                    if latest is not None:
                        self.logger.warning(
                            'training interrupted (%s); resumable from '
                            'the checkpoint at step %d', interrupted,
                            latest)
                    else:
                        self.logger.warning(
                            'training interrupted (%s) before the first '
                            'completed checkpoint; nothing saved',
                            interrupted)
                else:
                    self.logger.warning(
                        'training interrupted (%s) at step %d; no '
                        'checkpoint manager bound, nothing saved',
                        interrupted, global_step)
        finally:
            # a SECOND Ctrl-C during the final save must not escape with
            # the temporary provider still bound (restore_latest through
            # this manager would then refuse with the callable error)
            if bound_params:
                mgr.bind_params(None)

    @property
    def symbol(self):
        return self._symbol

    def install_monitor(self, mon):
        """Install a Monitor on every bound executor (ref:
        base_module.py install_monitor)."""
        assert self.binded, 'call bind before installing a monitor'
        for e in self._execs:
            mon.install(e)

    # abstract methods
    def bind(self, *args, **kwargs):
        raise NotImplementedError

    def init_params(self, *args, **kwargs):
        raise NotImplementedError

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError


def _as_list(obj):
    if isinstance(obj, (list, tuple)):
        return obj
    return [obj]


class Module(BaseModule):
    """Ref: module/module.py Module. One Executor per context; batches are
    sliced over contexts like DataParallelExecutorGroup."""

    def __init__(self, symbol, data_names=('data',), label_names=('softmax_label',),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger)
        self._symbol = symbol
        # Module's update path never passes a kvstore push, so the
        # error-feedback codec applies to the summed gradient in
        # update() — routed for real, same contract as gluon.Trainer's
        # no-push paths (ISSUE 12; an unknown ctype raises here)
        self._compression = None
        if compression_params is not None and \
                compression_params.get('type', '2bit') != 'none':
            from .kvstore.gradient_compression import GradientCompression
            self._compression = GradientCompression(
                compression_params.get('type', '2bit'),
                compression_params.get('threshold', 0.5),
                compression_params.get('block_size', 0))
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        if context is None:
            context = [cpu()]
        if not isinstance(context, (list, tuple)):
            context = [context]
        self._context = list(context)
        # group2ctxs (ref: module/module.py): dict group->Context (or a
        # list of such dicts, one per DP context) placing symbol groups
        # annotated via AttrScope(ctx_group=...) on specific devices
        if isinstance(group2ctxs, dict):
            group2ctxs = [group2ctxs] * len(self._context)
        if group2ctxs is not None and len(group2ctxs) != len(self._context):
            raise ValueError(
                f"group2ctxs has {len(group2ctxs)} entries for "
                f"{len(self._context)} contexts; pass one dict (shared) "
                f"or one per context")
        self._group2ctxs = group2ctxs
        self._fixed_param_names = set(fixed_param_names or [])
        self._arg_params = None
        self._aux_params = None
        self._execs = []
        self._optimizer = None
        self._updater = None
        self._kvstore = None

    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req='write'):
        if self.binded and not force_rebind:
            return
        self.for_training = for_training
        shapes = {}
        for desc in data_shapes:
            name, shape = (desc.name, desc.shape) if hasattr(desc, 'name') else desc[:2]
            shapes[name] = shape
        if label_shapes:
            for desc in label_shapes:
                name, shape = (desc.name, desc.shape) if hasattr(desc, 'name') else desc[:2]
                shapes[name] = shape
        self._data_shapes = shapes
        n = len(self._context)
        self._execs = []
        for i, ctx in enumerate(self._context):
            ctx_shapes = {}
            for name, shape in shapes.items():
                if name in self._data_names or name in self._label_names:
                    b = shape[0] // n
                    ctx_shapes[name] = (b,) + tuple(shape[1:])
                else:
                    ctx_shapes[name] = shape
            # fill missing arg shapes by inference
            arg_names = self._symbol.list_arguments()
            inferred, _, _ = self._symbol.infer_shape(
                **{k: v for k, v in ctx_shapes.items() if k in arg_names}) \
                if all(a in ctx_shapes for a in arg_names) else (None, None, None)
            if inferred is None:
                # partial: infer param shapes from data shapes via eval_shape
                inferred_shapes = _infer_missing(self._symbol, ctx_shapes)
                ctx_shapes.update(inferred_shapes)
            req = 'null' if not for_training else grad_req
            g2c = self._group2ctxs[i] if self._group2ctxs else None
            self._execs.append(self._symbol.simple_bind(
                ctx, grad_req=req, group2ctx=g2c, **ctx_shapes))
        self.binded = True

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, 'call bind before initializing the parameters'
        initializer = initializer or init_mod.Uniform(0.01)
        param_names = [n for n in self._symbol.list_arguments()
                       if n not in self._data_names and n not in self._label_names]
        self._arg_params = {}
        for name in param_names:
            arr = self._execs[0].arg_dict[name]
            if arg_params and name in arg_params:
                arr._data = arg_params[name]._data
            else:
                host = nd_zeros(arr.shape)
                initializer(init_mod.InitDesc(name), host)
                arr._data = host._data
            self._arg_params[name] = arr
            for e in self._execs[1:]:
                e.arg_dict[name]._data = arr._data
        # aux states (BN moving stats): initializer routes by suffix
        # (moving_mean -> zeros, moving_var -> ones), shared across execs
        self._aux_params = {}
        for name, arr in self._execs[0].aux_dict.items():
            if aux_params and name in aux_params:
                arr._data = aux_params[name]._data
            else:
                host = nd_zeros(arr.shape)
                initializer(init_mod.InitDesc(name), host)
                arr._data = host._data
            self._aux_params[name] = arr
            for e in self._execs[1:]:
                e.aux_dict[name]._data = arr._data
        self.params_initialized = True

    def get_params(self):
        return dict(self._arg_params), dict(self._aux_params or {})

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(None, arg_params, aux_params, allow_missing,
                         force_init, allow_extra)

    def init_optimizer(self, kvstore='local', optimizer='sgd',
                       optimizer_params=(('learning_rate', 0.01),),
                       force_init=False):
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, str):
            optimizer_params = dict(optimizer_params)
            # the reference normalizes summed DP gradients by the global
            # batch size unless the caller overrides rescale_grad
            # (ref: python/mxnet/module/module.py:527-537 init_optimizer)
            if 'rescale_grad' not in optimizer_params:
                batch = 0
                if self.binded:
                    for name in self._data_names:
                        shape = self._data_shapes.get(name)
                        if shape:
                            batch = shape[0]
                            break
                if batch:
                    optimizer_params['rescale_grad'] = 1.0 / batch
                else:
                    # same warning the reference emits when it cannot
                    # normalize (init before bind, or bound data shapes
                    # carry no batch dimension)
                    why = ('init_optimizer called before bind'
                           if not self.binded else
                           'bound data shapes have no usable batch size')
                    self.logger.warning(
                        '%s: cannot infer batch size, rescale_grad stays '
                        '1.0 — gradients will NOT be normalized by batch '
                        'size', why)
            optimizer = opt_mod.create(optimizer, **optimizer_params)
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        n = len(self._execs)
        data_slices = [split_data(d, n) for d in data_batch.data]
        label_slices = [split_data(l, n) for l in (data_batch.label or [])]
        for i, e in enumerate(self._execs):
            feed = {}
            for name, slices in zip(self._data_names, data_slices):
                feed[name] = slices[i]
            for name, slices in zip(self._label_names, label_slices):
                feed[name] = slices[i]
            e.forward(is_train=is_train, **feed)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        for e in self._execs:
            e.backward(out_grads)

    def update(self):
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        param_names = list(self._arg_params)
        for idx, name in enumerate(param_names):
            if name in self._fixed_param_names:
                continue
            # sum gradient over executors (DP reduce)
            grads = [e.grad_dict[name] for e in self._execs
                     if name in e.grad_dict]
            if not grads:
                continue
            total = grads[0]
            for g in grads[1:]:
                total = total + g
            if self._compression is not None:
                total = self._compression.compress_decompress(total, name)
            weight = self._arg_params[name]
            self._updater(idx, total, weight)
            for e in self._execs:
                e.arg_dict[name]._data = weight._data

    def get_outputs(self, merge_multi_context=True):
        outs = [e.outputs[0] for e in self._execs]
        if merge_multi_context and len(outs) > 1:
            from .ndarray import concat
            return [concat(*outs, dim=0)]
        return outs if not merge_multi_context else outs

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        outputs = self.get_outputs()
        eval_metric.update(labels, outputs)

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        remove_amp_cast=True):
        arg_params, aux_params = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg_params, aux_params)
        if save_optimizer_states:
            from .serialization import atomic_write_file
            atomic_write_file(f'{prefix}-{epoch:04d}.states',
                              self._updater.get_states())

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        mod = Module(symbol, **kwargs)
        mod._preloaded_params = (arg_params, aux_params)
        return mod


def _infer_missing(symbol, known_shapes):
    """Infer missing arg shapes given the bound data/label shapes by running
    shape propagation down the DAG (lightweight InferShape pass)."""
    import jax

    names = symbol.list_arguments()
    missing = [n for n in names if n not in known_shapes]
    if not missing:
        return {}
    # forward shape propagation first (resolves auto-created params and
    # anything downstream of the data shapes), then __shape__ hints
    from .symbol import infer_shapes_partial
    inferred = {n: s for n, s in
                infer_shapes_partial(symbol, known_shapes).items()
                if n in missing}
    for n in missing:
        if n in inferred:
            continue
        node = _find_var(symbol, n)
        hint = node.attrs.get('__shape__') if node is not None else None
        if hint:
            inferred[n] = tuple(hint)
        else:
            raise MXNetError(
                f"cannot infer shape for argument '{n}'; pass it to bind() "
                "or declare shape on the variable")
    return inferred


def _find_var(symbol, name):
    found = [None]

    def visit(s):
        if s.op is None and s._name == name:
            found[0] = s
        for i in s.inputs:
            visit(i)
    visit(symbol)
    return found[0]


class BucketingModule(BaseModule):
    """Variable-length sequence training (ref: module/bucketing_module.py).

    On TPU this is the shape-bucketed compile cache: one Module per bucket
    key, sharing parameters."""

    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, **kwargs):
        super().__init__(logger)
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._kwargs = kwargs
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None

    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    def _gen_module(self, bucket_key, data_shapes=None, label_shapes=None):
        if bucket_key not in self._buckets:
            symbol, data_names, label_names = self._sym_gen(bucket_key)
            mod = Module(symbol, data_names, label_names,
                         logger=self.logger, context=self._context)
            self._buckets[bucket_key] = mod
        return self._buckets[bucket_key]

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, **kwargs):
        self._curr_module = self._gen_module(self._default_bucket_key)
        self._curr_bucket_key = self._default_bucket_key
        self._curr_module.bind(data_shapes, label_shapes, for_training,
                               inputs_need_grad, force_rebind)
        self.binded = True
        self.for_training = for_training

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        mod = self._gen_module(bucket_key)
        if not mod.binded:
            mod.bind(data_shapes, label_shapes, self.for_training)
            if self._curr_module.params_initialized:
                arg, aux = self._curr_module.get_params()
                mod.init_params(arg_params=arg, aux_params=aux,
                                force_init=True)
                mod.optimizer_initialized = self._curr_module.optimizer_initialized
                mod._optimizer = self._curr_module._optimizer
                mod._updater = self._curr_module._updater
        self._curr_module = mod
        self._curr_bucket_key = bucket_key

    def init_params(self, *args, **kwargs):
        self._curr_module.init_params(*args, **kwargs)
        self.params_initialized = True

    def get_params(self):
        return self._curr_module.get_params()

    def init_optimizer(self, *args, **kwargs):
        self._curr_module.init_optimizer(*args, **kwargs)
        self.optimizer_initialized = True
        for mod in self._buckets.values():
            if mod is not self._curr_module and mod.binded:
                mod._optimizer = self._curr_module._optimizer
                mod._updater = self._curr_module._updater
                mod.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        if data_batch.bucket_key is not None and \
                data_batch.bucket_key != self._curr_bucket_key:
            self.switch_bucket(data_batch.bucket_key,
                               data_batch.provide_data,
                               data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels)

    @property
    def symbol(self):
        return self._curr_module.symbol


class SequentialModule(BaseModule):
    """Chain of modules (ref: module/sequential_module.py)."""

    def __init__(self, logger=logging):
        super().__init__(logger)
        self._modules = []

    def add(self, module, **kwargs):
        self._modules.append(module)
        return self

    def bind(self, data_shapes, label_shapes=None, for_training=True, **kwargs):
        shapes = data_shapes
        for mod in self._modules:
            mod.bind(shapes, label_shapes, for_training)
        self.binded = True

    def init_params(self, *args, **kwargs):
        for mod in self._modules:
            mod.init_params(*args, **kwargs)
        self.params_initialized = True

    def forward(self, data_batch, is_train=None):
        from .io import DataBatch
        cur = data_batch
        for mod in self._modules:
            mod.forward(cur, is_train)
            out = mod.get_outputs()
            cur = DataBatch(data=out, label=data_batch.label)

    def backward(self, out_grads=None):
        for mod in reversed(self._modules):
            mod.backward(out_grads)

    def update(self):
        for mod in self._modules:
            mod.update()

    def get_outputs(self):
        return self._modules[-1].get_outputs()

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._modules[-1].update_metric(eval_metric, labels)
