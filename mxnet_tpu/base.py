"""Core shared infrastructure: errors, op registry, version.

TPU-native re-imagination of the reference's base layer
(ref: include/mxnet/base.h, python/mxnet/base.py). Instead of a C API +
ctypes bridge, ops are plain Python callables over jax.Arrays registered in
an in-process registry (the analog of NNVM_REGISTER_OP,
ref: include/mxnet/op_attr_types.h:218-347).
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

__version__ = "2.0.0.tpu"


class MXNetError(RuntimeError):
    """Default error type raised by the framework (ref: python/mxnet/base.py MXNetError)."""


class DataError(MXNetError):
    """A corrupt or truncated input record. Carries enough context to
    act on (which record, at what file offset) instead of an opaque
    struct/decode error that kills the epoch; the IO layer can also be
    told to skip-and-count these (MXNET_TPU_IO_CORRUPT_POLICY=skip)."""

    def __init__(self, message, index=None, offset=None, path=None):
        super().__init__(message)
        self.index = index
        self.offset = offset
        self.path = path


# ---------------------------------------------------------------------------
# Operator registry.
#
# The reference registers 533 ops via NNVM with attribute functions
# (FCompute, FInferShape, FGradient...). On TPU the compute function IS the
# lowering rule: a pure function over jax arrays that XLA traces and fuses.
# Shape/dtype inference comes for free from jax's abstract evaluation, so the
# registry only carries the compute fn plus optional metadata.
# ---------------------------------------------------------------------------

class OpDef:
    __slots__ = ("name", "fn", "num_outputs", "mutate_inputs", "nograd", "doc")

    def __init__(self, name: str, fn: Callable, num_outputs: int = 1,
                 mutate_inputs: tuple = (), nograd: bool = False, doc: str = ""):
        # mutate_inputs: tuple of input indices the op rewrites in place,
        # or the sentinel 'all' for variadic ops that mutate every input
        # (resolve concrete indices with mutated_input_indices)
        self.name = name
        self.fn = fn
        self.num_outputs = num_outputs
        self.mutate_inputs = mutate_inputs
        self.nograd = nograd
        self.doc = doc or (fn.__doc__ or "")


_OP_REGISTRY: Dict[str, OpDef] = {}

# Alias → canonical-name map (ref: nnvm's Op::add_alias,
# 3rdparty/tvm/nnvm/include/nnvm/op.h — the reference registers legacy
# spellings like `_Plus`, `uniform`, `_npx_relu` as aliases of one
# canonical op). Aliases resolve through get_op but do not appear in
# list_ops(), mirroring the reference where ListAllOpNames returns
# canonical + alias names but attributes live on one Op record; we keep
# list_ops() canonical so per-op accounting (tests, AMP lists) never
# double-counts.
_OP_ALIASES: Dict[str, str] = {}

# Executed-op accounting: every canonical op name whose compute fn has
# actually been CALLED — through a frontend's _imperative.invoke or via
# get_op(name).fn(...). Resolution alone does not count: the test-suite
# coverage accounting asserts this set covers list_ops(), and an op
# merely looked up (or mentioned) in a test must not pass
# (VERDICT r4 weak #7).
invoked_ops: set = set()

# raw fn → {canonical names} reverse map so invoke() (which receives the
# raw compute fn from frontends, not the name) can record executions.
_FN_OPNAMES: Dict[Callable, set] = {}


def record_op_use(fn: Callable):
    # one-shot per fn: steady-state eager dispatch pays one attribute
    # check, not a dict lookup + set update per call
    if getattr(fn, '__op_use_recorded__', False):
        return
    names = _FN_OPNAMES.get(fn)
    if names:
        invoked_ops.update(names)
        try:
            fn.__op_use_recorded__ = True
        except AttributeError:
            pass


def register_op(name: Optional[str] = None, num_outputs: int = 1,
                mutate_inputs: tuple = (), nograd: bool = False):
    """Register a pure jax-level compute function as a framework op."""
    import functools

    def deco(fn: Callable):
        opname = name or fn.__name__
        raw = getattr(fn, '__wrapped_op_fn__', fn)

        @functools.wraps(raw)
        def recorded(*args, **kwargs):
            # execution-time accounting, recorded AFTER the compute fn
            # returns (an op that raises on every call is not covered).
            # One-shot: steady-state cost is a single attribute check.
            out = raw(*args, **kwargs)
            if not recorded._seen:
                recorded._seen = True
                invoked_ops.update(_FN_OPNAMES.get(raw, ()))
            return out

        recorded._seen = False

        recorded.__wrapped_op_fn__ = raw
        _OP_REGISTRY[opname] = OpDef(opname, recorded, num_outputs,
                                     mutate_inputs, nograd)
        _FN_OPNAMES.setdefault(raw, set()).add(opname)
        return fn
    return deco


def mutated_input_indices(opdef: "OpDef", num_inputs: int) -> tuple:
    """Concrete indices of the inputs `opdef` mutates, resolving the
    'all' sentinel used by variadic in-place ops (e.g. reset_arrays)."""
    if opdef.mutate_inputs == 'all':
        return tuple(range(num_inputs))
    return tuple(opdef.mutate_inputs)


def register_op_alias(alias: str, canonical: str):
    """Make `alias` resolve to the already-registered op `canonical`."""
    if canonical not in _OP_REGISTRY:
        raise MXNetError(f"Cannot alias {alias!r}: target {canonical!r} "
                         f"is not registered")
    if alias in _OP_REGISTRY:
        raise MXNetError(f"Alias {alias!r} collides with a registered op")
    _OP_ALIASES[alias] = canonical


def get_op(name: str) -> OpDef:
    od = _OP_REGISTRY.get(name)
    if od is None:
        target = _OP_ALIASES.get(name)
        if target is None:
            raise MXNetError(f"Operator {name!r} is not registered")
        od = _OP_REGISTRY[target]
    return od


def list_ops():
    return sorted(_OP_REGISTRY)


def list_op_aliases():
    return dict(_OP_ALIASES)


# Storage-driven kernel dispatch (ref: FComputeEx,
# include/mxnet/op_attr_types.h:304): an op may register alternative
# implementations keyed by the storage types of its tensor arguments;
# the imperative invoke swaps them in when the stype signature matches.
_SPARSE_IMPLS: Dict[tuple, Callable] = {}


def register_sparse_impl(opname: str, stypes: tuple):
    """Register a storage-specific implementation of `opname` for the
    given tuple of positional-argument storage types, e.g.
    ('csr', 'default')."""
    def deco(fn: Callable):
        _SPARSE_IMPLS[(opname, tuple(stypes))] = fn
        return fn
    return deco


def lookup_sparse_impl(opname: str, stypes: tuple):
    return _SPARSE_IMPLS.get((opname, tuple(stypes)))


# ---------------------------------------------------------------------------
# Generic string-keyed object registries (ref: python/mxnet/registry.py) used
# by optimizers, initializers, metrics, datasets...
# ---------------------------------------------------------------------------

class Registry:
    def __init__(self, kind: str):
        self.kind = kind
        self._store: Dict[str, Any] = {}

    def register(self, obj: Any = None, name: Optional[str] = None):
        def deco(o):
            key = (name or o.__name__).lower()
            self._store[key] = o
            return o
        if obj is None:
            return deco
        return deco(obj)

    def get(self, name: str):
        key = name.lower()
        if key not in self._store:
            raise MXNetError(f"Unknown {self.kind} {name!r}. "
                             f"Registered: {sorted(self._store)}")
        return self._store[key]

    def create(self, name, *args, **kwargs):
        if isinstance(name, str):
            return self.get(name)(*args, **kwargs)
        return name

    def list(self):
        return sorted(self._store)


class _ThreadLocalState(threading.local):
    """Thread-local runtime flags (ref: include/mxnet/imperative.h:206-212)."""

    def __init__(self):
        self.is_recording = False
        self.is_training = False
        self.is_deferred_compute = False
        self.record_depth = 0  # nesting depth of autograd.record scopes


state = _ThreadLocalState()

# PROCESS-wide profiling flags (plain dict, shared across threads — the
# profiler's start/stop must affect worker threads too, unlike the
# autograd flags above which are deliberately thread-local). Written by
# profiler._sync_flags(), read by _imperative.invoke.
prof_flags = {'op': False, 'sync': False}

# PROCESS-wide telemetry gate, same pattern: written by
# telemetry.enable()/disable(), read inline by every instrumented hot
# path (imperative dispatch, compile caches, kvstore, IO, trainer step)
# so a disabled run pays one dict lookup per site and records nothing.
telem_flags = {'on': False}
