"""Loader for the native IO runtime (src/io/mxtpu_io.cc).

The analog of the reference's libmxnet.so ctypes bootstrap
(ref: python/mxnet/base.py _load_lib) scoped to the IO runtime: the TPU
compute path needs no native library (XLA is the backend), but the host
data pipeline is C++ like the reference's (ref: src/io/). Falls back to
pure Python transparently when the .so is absent and a build fails.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_LIB_PATH = os.path.join(os.path.dirname(__file__), '_lib', 'libmxtpu_io.so')
_SRC_DIR = os.path.join(os.path.dirname(__file__), os.pardir, 'src')

_lib = None
_lib_tried = False
_lock = threading.Lock()


def _configure(lib):
    u64 = ctypes.c_uint64
    lib.mxt_recordio_writer_create.restype = ctypes.c_void_p
    lib.mxt_recordio_writer_create.argtypes = [ctypes.c_char_p]
    lib.mxt_recordio_writer_write.restype = ctypes.c_int
    lib.mxt_recordio_writer_write.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
        ctypes.POINTER(u64)]
    lib.mxt_recordio_writer_free.argtypes = [ctypes.c_void_p]

    lib.mxt_recordio_reader_create.restype = ctypes.c_void_p
    lib.mxt_recordio_reader_create.argtypes = [ctypes.c_char_p]
    lib.mxt_recordio_reader_read.restype = ctypes.c_int64
    lib.mxt_recordio_reader_read.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p)]
    lib.mxt_recordio_reader_tell.restype = u64
    lib.mxt_recordio_reader_tell.argtypes = [ctypes.c_void_p]
    lib.mxt_recordio_reader_seek.restype = ctypes.c_int
    lib.mxt_recordio_reader_seek.argtypes = [ctypes.c_void_p, u64]
    lib.mxt_recordio_reader_free.argtypes = [ctypes.c_void_p]

    lib.mxt_pipeline_create.restype = ctypes.c_void_p
    lib.mxt_pipeline_create.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, u64,
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ctypes.c_int, u64]
    lib.mxt_pipeline_num_records.restype = ctypes.c_int64
    lib.mxt_pipeline_num_records.argtypes = [ctypes.c_void_p]
    lib.mxt_pipeline_next.restype = ctypes.c_int
    lib.mxt_pipeline_next.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_float))]
    lib.mxt_pipeline_next_lease.restype = ctypes.c_int
    lib.mxt_pipeline_next_lease.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_float)), ctypes.POINTER(u64)]
    lib.mxt_pipeline_return.restype = ctypes.c_int
    lib.mxt_pipeline_return.argtypes = [ctypes.c_void_p, u64]
    lib.mxt_pipeline_leased.restype = ctypes.c_int
    lib.mxt_pipeline_leased.argtypes = [ctypes.c_void_p]
    lib.mxt_pipeline_cache_stats.restype = None
    lib.mxt_pipeline_cache_stats.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(u64), ctypes.POINTER(u64),
        ctypes.POINTER(u64)]
    lib.mxt_pipeline_error.restype = ctypes.c_char_p
    lib.mxt_pipeline_error.argtypes = [ctypes.c_void_p]
    lib.mxt_pipeline_reset.argtypes = [ctypes.c_void_p]
    lib.mxt_pipeline_free.argtypes = [ctypes.c_void_p]
    return lib


def _try_build():
    import logging
    try:
        subprocess.run(['make', '-C', os.path.abspath(_SRC_DIR)],
                       check=True, capture_output=True, timeout=120)
        logging.info("built native IO runtime at %s", _LIB_PATH)
        return os.path.isfile(_LIB_PATH)
    except subprocess.CalledProcessError as e:
        logging.warning(
            "native IO runtime build failed (falling back to pure Python); "
            "run `make -C src` for details. stderr tail: %s",
            e.stderr.decode(errors='replace')[-500:] if e.stderr else '')
        return False
    except Exception as e:
        logging.warning("native IO runtime unavailable (%s); "
                        "falling back to pure Python", e)
        return False


def get_lib():
    """The native IO library, or None (pure-Python fallback)."""
    global _lib, _lib_tried
    with _lock:
        if _lib_tried:
            return _lib
        _lib_tried = True
        if not os.path.isfile(_LIB_PATH):
            from . import config as _config
            if _config.get('MXNET_TPU_NO_NATIVE_BUILD'):
                return None
            if not _try_build():
                return None
        try:
            _lib = _configure(ctypes.CDLL(_LIB_PATH))
        except OSError:
            _lib = None
        return _lib


def native_available():
    return get_lib() is not None
