"""Profiler: scoped tracing with chrome://tracing JSON output.

Ref: src/profiler/profiler.h:79,251-299 and python/mxnet/profiler.py. On TPU
the heavy lifting is jax.profiler (XLA/TPU traces viewable in TensorBoard or
Perfetto); this module keeps the reference's API (set_config, start/stop,
scoped Task/Frame/Event/Counter/Marker) and emits a chrome-tracing JSON of
python-level scopes, while optionally also capturing a jax device trace.
"""
from __future__ import annotations

import json
import os
import threading
import time

import jax

from .base import MXNetError

_config = {
    'filename': 'profile.json',
    'profile_all': False,
    'profile_symbolic': False,
    'profile_imperative': False,
    'profile_memory': False,
    'profile_api': False,
    'aggregate_stats': False,
    'continuous_dump': False,
}
_state = {'running': False, 'jax_trace_dir': None}
_events = []
_events_lock = threading.Lock()


def set_config(**kwargs):
    """Ref: python/mxnet/profiler.py set_config."""
    for k, v in kwargs.items():
        _config[k] = v


def profiler_set_config(mode='symbolic', filename='profile.json'):
    _config['filename'] = filename


def set_state(state='stop', profile_process='worker'):
    if state == 'run':
        start()
    else:
        stop()


def start(profile_process='worker'):
    _state['running'] = True
    _events.clear()
    tdir = os.environ.get('MXNET_TPU_JAX_TRACE_DIR')
    if tdir:
        jax.profiler.start_trace(tdir)
        _state['jax_trace_dir'] = tdir


def stop(profile_process='worker'):
    _state['running'] = False
    if _state['jax_trace_dir']:
        jax.profiler.stop_trace()
        _state['jax_trace_dir'] = None


def pause(profile_process='worker'):
    _state['running'] = False


def resume(profile_process='worker'):
    _state['running'] = True


def dump(finished=True, profile_process='worker'):
    """Write chrome://tracing JSON (ref: profiler.h:79 'chrome tracing')."""
    with _events_lock:
        trace = {'traceEvents': list(_events), 'displayTimeUnit': 'ms'}
    with open(_config['filename'], 'w') as f:
        json.dump(trace, f)


def dumps(reset=False):
    with _events_lock:
        out = json.dumps({'traceEvents': list(_events)})
        if reset:
            _events.clear()
    return out


def _emit(name, cat, ph, ts=None, args=None, dur=None):
    ev = {'name': name, 'cat': cat, 'ph': ph,
          'ts': (ts if ts is not None else time.time() * 1e6),
          'pid': os.getpid(), 'tid': threading.get_ident()}
    if args:
        ev['args'] = args
    if dur is not None:
        ev['dur'] = dur
    with _events_lock:
        _events.append(ev)


class _Scope:
    def __init__(self, name, cat):
        self.name = name
        self.cat = cat
        self._t0 = None

    def start(self):
        self._t0 = time.time() * 1e6
        if _state['running']:
            _emit(self.name, self.cat, 'B', self._t0)
        return self

    def stop(self):
        if _state['running']:
            _emit(self.name, self.cat, 'E')

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class Domain:
    def __init__(self, name):
        self.name = name

    def new_task(self, name):
        return Task(self, name)

    def new_counter(self, name, value=None):
        return Counter(self, name, value)

    def new_marker(self, name):
        return Marker(self, name)


class Task(_Scope):
    def __init__(self, domain, name):
        super().__init__(name, f'task/{domain.name}')


class Frame(_Scope):
    def __init__(self, domain, name):
        super().__init__(name, f'frame/{domain.name}')


class Event(_Scope):
    def __init__(self, name):
        super().__init__(name, 'event')


class Counter:
    def __init__(self, domain, name, value=None):
        self.domain = domain
        self.name = name
        self.value = value if value is not None else 0
        if value is not None:
            self._record()

    def _record(self):
        if _state['running']:
            _emit(self.name, f'counter/{self.domain.name}', 'C',
                  args={self.name: self.value})

    def set_value(self, value):
        self.value = value
        self._record()

    def increment(self, delta=1):
        self.value += delta
        self._record()

    def decrement(self, delta=1):
        self.value -= delta
        self._record()

    def __iadd__(self, v):
        self.increment(v)
        return self

    def __isub__(self, v):
        self.decrement(v)
        return self


class Marker:
    def __init__(self, domain, name):
        self.domain = domain
        self.name = name

    def mark(self, scope='process'):
        if _state['running']:
            _emit(self.name, f'marker/{self.domain.name}', 'I')


def scope(name='<unk>:'):
    return _Scope(name, 'scope')


def annotate(name):
    """Decorator/context adding a named region to both the python trace and
    the jax/XLA device trace."""
    return jax.profiler.TraceAnnotation(name)


class StepTraceAnnotation:
    def __init__(self, step_num):
        self._ctx = jax.profiler.StepTraceAnnotation("train", step_num=step_num)

    def __enter__(self):
        return self._ctx.__enter__()

    def __exit__(self, *exc):
        return self._ctx.__exit__(*exc)
