"""Profiler: scoped tracing with chrome://tracing JSON output.

Ref: src/profiler/profiler.h:79,251-299 and python/mxnet/profiler.py. On TPU
the heavy lifting is jax.profiler (XLA/TPU traces viewable in TensorBoard or
Perfetto); this module keeps the reference's API (set_config, start/stop,
scoped Task/Frame/Event/Counter/Marker) and emits a chrome-tracing JSON of
python-level scopes, while optionally also capturing a jax device trace.
"""
from __future__ import annotations

import json
import os
import threading
import time

import jax

from .base import MXNetError, prof_flags as _prof_flags
from .telemetry import trace as _trace_mod

_config = {
    'filename': 'profile.json',
    'profile_all': False,
    'profile_symbolic': False,
    'profile_imperative': False,
    'profile_memory': False,
    'profile_api': False,
    'aggregate_stats': False,
    'continuous_dump': False,
    # block each profiled op to completion before timing it: true device
    # time instead of dispatch time, at the cost of pipelining
    'profile_sync': False,
    # directory for the jax/XLA device trace started by start(); replaces
    # the old MXNET_TPU_JAX_TRACE_DIR env-only path (still honored)
    'jax_trace_dir': None,
}
_state = {'running': False, 'jax_trace_dir': None,
          # whether THIS run has already dumped to the configured file:
          # continuous_dump only extends a file this run wrote — a
          # leftover trace from a previous run/process is overwritten,
          # never merged into the new timeline
          'dumped_in_run': False}
_events = []
_events_lock = threading.Lock()
# op name -> [count, total_us, min_us, max_us] (aggregate_stats)
_op_stats = {}


def record_op(name, dur_us):
    """One per-op profiler row (called from _imperative.invoke when
    profile_imperative/profile_all is active)."""
    now = time.time() * 1e6
    # tid from the shared trace registry: profiler op rows and telemetry
    # spans land in ONE stable small-int tid space (+ thread names)
    ev = {'name': name, 'cat': 'operator', 'ph': 'X',
          'ts': now - dur_us, 'dur': dur_us,
          'pid': os.getpid(), 'tid': _trace_mod.tid_for_current_thread()}
    with _events_lock:
        _events.append(ev)
        st = _op_stats.get(name)
        if st is None:
            _op_stats[name] = [1, dur_us, dur_us, dur_us]
        else:
            st[0] += 1
            st[1] += dur_us
            st[2] = min(st[2], dur_us)
            st[3] = max(st[3], dur_us)


def get_summary(reset=False):
    """Aggregate per-op table (ref: profiler.py dumps(aggregate_stats)):
    name, calls, total/min/max/avg in ms."""
    with _events_lock:
        rows = sorted(_op_stats.items(), key=lambda kv: -kv[1][1])
        if reset:
            _op_stats.clear()
    lines = [f"{'Name':<40s}{'Total Count':>12s}{'Time (ms)':>12s}"
             f"{'Min (ms)':>12s}{'Max (ms)':>12s}{'Avg (ms)':>12s}"]
    for name, (cnt, tot, mn, mx) in rows:
        lines.append(f"{name[:39]:<40s}{cnt:>12d}{tot / 1e3:>12.4f}"
                     f"{mn / 1e3:>12.4f}{mx / 1e3:>12.4f}"
                     f"{tot / cnt / 1e3:>12.4f}")
    return '\n'.join(lines)


def set_config(**kwargs):
    """Ref: python/mxnet/profiler.py set_config. profile_imperative /
    profile_all turn on per-op rows (one entry per imperative op dispatch,
    the analog of the reference wrapping engine pushes,
    src/profiler/profiler.h:299); takes effect immediately if the
    profiler is already running."""
    unknown = [k for k in kwargs if k not in _config]
    if unknown:
        raise MXNetError(
            f"profiler.set_config: unknown keys {unknown!r}")
    _config.update(kwargs)
    _sync_flags()


def _sync_flags():
    _prof_flags['op'] = bool(_state['running'] and (
        _config['profile_imperative'] or _config['profile_all']))
    _prof_flags['sync'] = bool(_config['profile_sync']
                               or _config['aggregate_stats'])


def profiler_set_config(mode='symbolic', filename='profile.json'):
    _config['filename'] = filename


def set_state(state='stop', profile_process='worker'):
    if state == 'run':
        start()
    else:
        stop()


def start(profile_process='worker'):
    _state['running'] = True
    with _events_lock:
        # both clears under the lock: a worker thread appending through
        # record_op/_emit must never interleave with a half-done reset
        _events.clear()
        _op_stats.clear()
    _state['dumped_in_run'] = False
    _sync_flags()
    from . import config as _envcfg
    tdir = _config['jax_trace_dir'] or \
        _envcfg.get('MXNET_TPU_JAX_TRACE_DIR')
    if tdir:
        jax.profiler.start_trace(tdir)
        _state['jax_trace_dir'] = tdir


def stop(profile_process='worker'):
    _state['running'] = False
    _sync_flags()
    if _state['jax_trace_dir']:
        jax.profiler.stop_trace()
        _state['jax_trace_dir'] = None


def pause(profile_process='worker'):
    _state['running'] = False
    _sync_flags()


def resume(profile_process='worker'):
    _state['running'] = True
    _sync_flags()


def _telemetry_events():
    """Telemetry counters/gauges as chrome 'C' events, merged into the
    trace stream so the counter tracks render alongside the op scopes."""
    try:
        from . import telemetry
        if telemetry.enabled():
            return telemetry.chrome_events()
    except Exception:
        pass
    return []


def _span_events():
    """Balanced span events (+ thread-name metadata) from the step
    tracer, merged into the same traceEvents array as the op rows and
    counter tracks — ONE chrome://tracing-loadable stream, one stable
    pid/tid space. Empty when tracing is disarmed or has no spans."""
    try:
        evs = _trace_mod.chrome_events(flush_open=True)
        if not evs:
            return []
        return _trace_mod.thread_metadata() + evs
    except Exception:
        return []


def dump(finished=True, profile_process='worker'):
    """Write chrome://tracing JSON (ref: profiler.h:79 'chrome tracing').

    With continuous_dump set, events already written are cleared from
    memory and the on-disk trace is extended in place, so repeated dumps
    neither re-emit nor unboundedly regrow the same trace. Telemetry 'C'
    counters and step-tracer spans are folded into the same traceEvents
    array (span events dedupe across continuous dumps — the tracer's
    rings are snapshots, not drains)."""
    continuous = _config['continuous_dump']
    with _events_lock:
        new_events = list(_events)
        if continuous:
            _events.clear()
    events = new_events + _telemetry_events() + _span_events()
    if continuous and _state['dumped_in_run'] \
            and os.path.exists(_config['filename']):
        try:
            with open(_config['filename']) as f:
                prev = json.load(f).get('traceEvents', [])
        except (OSError, ValueError):
            prev = []
        seen = {(e.get('name'), e.get('ph'), e.get('ts'), e.get('tid'))
                for e in prev}
        events = prev + [e for e in events
                         if (e.get('name'), e.get('ph'), e.get('ts'),
                             e.get('tid')) not in seen]
    events = _trace_mod.balance_events(events)
    trace = {'traceEvents': events, 'displayTimeUnit': 'ms'}
    with open(_config['filename'], 'w') as f:
        json.dump(trace, f)
    _state['dumped_in_run'] = True


def dumps(reset=False, format='table'):
    """Aggregate-stats table when aggregate_stats is configured (the
    reference's dumps contract, python/mxnet/profiler.py:dumps), else the
    chrome-trace JSON of collected events (incl. per-op rows)."""
    if _config['aggregate_stats'] and format == 'table':
        out = get_summary(reset=reset)
        if reset:
            with _events_lock:
                _events.clear()
        return out
    with _events_lock:
        evs = list(_events)
        if reset:
            _events.clear()
            _op_stats.clear()
    return json.dumps({'traceEvents': _trace_mod.balance_events(
        evs + _telemetry_events() + _span_events())})


def _emit(name, cat, ph, ts=None, args=None, dur=None):
    ev = {'name': name, 'cat': cat, 'ph': ph,
          'ts': (ts if ts is not None else time.time() * 1e6),
          'pid': os.getpid(), 'tid': _trace_mod.tid_for_current_thread()}
    if args:
        ev['args'] = args
    if dur is not None:
        ev['dur'] = dur
    with _events_lock:
        _events.append(ev)


class _Scope:
    def __init__(self, name, cat):
        self.name = name
        self.cat = cat
        self._t0 = None

    def start(self):
        self._t0 = time.time() * 1e6
        if _state['running']:
            _emit(self.name, self.cat, 'B', self._t0)
        return self

    def stop(self):
        if _state['running']:
            _emit(self.name, self.cat, 'E')

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class Domain:
    def __init__(self, name):
        self.name = name

    def new_task(self, name):
        return Task(self, name)

    def new_counter(self, name, value=None):
        return Counter(self, name, value)

    def new_marker(self, name):
        return Marker(self, name)


class Task(_Scope):
    def __init__(self, domain, name):
        super().__init__(name, f'task/{domain.name}')


class Frame(_Scope):
    def __init__(self, domain, name):
        super().__init__(name, f'frame/{domain.name}')


class Event(_Scope):
    def __init__(self, name):
        super().__init__(name, 'event')


class Counter:
    def __init__(self, domain, name, value=None):
        self.domain = domain
        self.name = name
        self.value = value if value is not None else 0
        if value is not None:
            self._record()

    def _record(self):
        if _state['running']:
            _emit(self.name, f'counter/{self.domain.name}', 'C',
                  args={self.name: self.value})

    def set_value(self, value):
        self.value = value
        self._record()

    def increment(self, delta=1):
        self.value += delta
        self._record()

    def decrement(self, delta=1):
        self.value -= delta
        self._record()

    def __iadd__(self, v):
        self.increment(v)
        return self

    def __isub__(self, v):
        self.decrement(v)
        return self


class Marker:
    def __init__(self, domain, name):
        self.domain = domain
        self.name = name

    def mark(self, scope='process'):
        if _state['running']:
            _emit(self.name, f'marker/{self.domain.name}', 'I')


def scope(name='<unk>:'):
    return _Scope(name, 'scope')


def annotate(name):
    """Decorator/context adding a named region to both the python trace and
    the jax/XLA device trace."""
    return jax.profiler.TraceAnnotation(name)


class StepTraceAnnotation:
    def __init__(self, step_num):
        self._ctx = jax.profiler.StepTraceAnnotation("train", step_num=step_num)

    def __enter__(self):
        return self._ctx.__enter__()

    def __exit__(self, *exc):
        return self._ctx.__exit__(*exc)
